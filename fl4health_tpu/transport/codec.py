"""Pytree <-> wire codec for the cross-silo transport.

Parity surface (SURVEY §2.14): the reference's wire format is Flower's
``Parameters`` — a list of NumPy arrays serialized per round over gRPC
(strategies own pack/unpack; grpcio's C core does the byte handling). For
cross-silo deployments (real hospitals, no shared mesh) the TPU build keeps
a host-level wire with the same contract.

Design:
- header = JSON metadata (dotted leaf paths, shapes, dtypes) — code never
  executes from the wire (no pickle);
- payload = the raw little-endian array bytes, concatenated in path order;
- framing (magic/version/flags/lengths/CRC-32) is the native C++ codec
  (transport/native.py) with a byte-identical Python fallback;
- sparse packets cross as real COO (values + int32 indices) — the dense
  0/1-mask encoding used on-device (exchange/packer.py SparseMaskPacket)
  converts at this host boundary, reproducing the reference's
  SparseCooParameterPacker wire compactness (parameter_packer.py:94,124);
- compressed updates cross as COMPRESSED frames (flag bit 1): per leaf an
  optional gap-uint16 index sidecar (global magnitude top-k), int8/int4
  quantized values with one f32 scale per leaf (packed nibbles for int4),
  CRC-checked by the same framing — the byte realization of the in-graph
  lossy channel (fl4health_tpu/compression/), arXiv:1610.05492;
- ``decode(data, like=template)`` restores the EXACT pytree structure
  (flax struct dataclasses included) by unflattening into the template's
  treedef; a path set that does not match the template raises naming the
  first mismatched path; without a template the result is nested dicts.
"""

from __future__ import annotations

import json
import math
from typing import Any

import jax
import numpy as np

from fl4health_tpu.compression.config import QUANT_LEVELS, CompressionConfig
from fl4health_tpu.core.types import PyTree
from fl4health_tpu.exchange.packer import SparseMaskPacket
from fl4health_tpu.observability.registry import get_registry
from fl4health_tpu.transport.native import get_framing, pack_int4, unpack_int4

FLAG_COO = 1
FLAG_COMPRESSED = 2


def _account(direction: str, nbytes: int, kind: str) -> None:
    """Wire byte accounting (arXiv:1610.05492-style per-round cost) into the
    process-wide registry. Host-side counter bumps only — no device work, so
    the codec hot path cost is unchanged to first order."""
    reg = get_registry()
    reg.counter(
        f"transport_bytes_{direction}_total",
        help=f"total wire bytes {direction} by the codec",
    ).inc(nbytes)
    reg.counter(
        f"transport_frames_{direction}_total",
        help=f"wire frames {direction} by the codec",
        labels={"kind": kind},
    ).inc()


def _paths_and_leaves(tree: PyTree) -> list[tuple[str, np.ndarray]]:
    flat = jax.tree_util.tree_flatten_with_path(tree)[0]
    out = []
    for key_path, leaf in flat:
        dotted = ".".join(str(getattr(k, "key", k)) for k in key_path)
        out.append((dotted, np.asarray(leaf)))
    return out


def _match_template_paths(
    payload_paths: list[str], like: PyTree, what: str
) -> "tuple[list[str], Any]":
    """Template leaf paths + treedef, validated against the payload's paths.

    A mismatch raises naming the FIRST mismatched path (template leaf the
    payload lacks, else payload leaf the template lacks) — previously a
    missing leaf surfaced as whatever zip/KeyError misalignment produced."""
    flat_t, treedef = jax.tree_util.tree_flatten_with_path(like)
    template_paths = [
        ".".join(str(getattr(k, "key", k)) for k in key_path)
        for key_path, _ in flat_t
    ]
    have = set(payload_paths)
    for p in template_paths:
        if p not in have:
            raise ValueError(
                f"{what}: payload is missing leaf {p!r} required by the "
                f"decode template ({len(payload_paths)} payload leaves vs "
                f"{len(template_paths)} template leaves)"
            )
    want = set(template_paths)
    for p in payload_paths:
        if p not in want:
            raise ValueError(
                f"{what}: payload leaf {p!r} does not exist in the decode "
                f"template ({len(payload_paths)} payload leaves vs "
                f"{len(template_paths)} template leaves)"
            )
    return template_paths, treedef


def encode(tree: PyTree, trace: "dict[str, Any] | None" = None) -> bytes:
    """Dense pytree -> one wire frame.

    ``trace`` (a ``TraceContext.to_header()`` dict) rides in the JSON
    header under a ``"trace"`` key so silo handlers can correlate spans
    across processes (observability/tracectx.py). ``decode`` reads only
    ``meta["leaves"]``, so traced frames decode everywhere; without a
    trace the frame bytes are exactly what they always were."""
    entries = _paths_and_leaves(tree)
    meta, chunks = [], []
    for path, arr in entries:
        data = np.ascontiguousarray(arr)
        if data.dtype.byteorder == ">":
            data = data.astype(data.dtype.newbyteorder("<"))
        # dtype recorded AFTER the little-endian conversion — the header must
        # describe the payload bytes, not the caller's original layout.
        meta.append({"path": path, "shape": list(arr.shape), "dtype": str(data.dtype)})
        chunks.append(data.tobytes())
    head: dict[str, Any] = {"leaves": meta}
    if trace is not None:
        head["trace"] = trace
    header = json.dumps(head).encode("utf-8")
    frame = get_framing().frame(header, b"".join(chunks), flags=0)
    _account("encoded", len(frame), "dense")
    return frame


def frame_trace(data: bytes) -> "dict[str, Any] | None":
    """Extract the ``"trace"`` header dict from any codec frame (dense,
    COO, or compressed), or None for untraced/unparseable input. Never
    raises — the silo-side traced handler calls this on raw request
    bytes before it knows the frame is well-formed."""
    try:
        header, _, _ = get_framing().unframe(data)
        doc = json.loads(header.decode("utf-8"))
    except Exception:
        return None
    trace = doc.get("trace") if isinstance(doc, dict) else None
    return trace if isinstance(trace, dict) else None


def _rebuild_nested(items: list[tuple[str, np.ndarray]]) -> dict:
    root: dict = {}
    for path, arr in items:
        node = root
        parts = path.split(".")
        for part in parts[:-1]:
            node = node.setdefault(part, {})
        node[parts[-1]] = arr
    return root


def decode(data: bytes, like: PyTree | None = None) -> PyTree:
    """Wire frame -> pytree. With ``like``, leaves are unflattened into the
    template's exact treedef (paths must match); otherwise nested dicts."""
    header, payload, flags = get_framing().unframe(data)
    meta = json.loads(header.decode("utf-8"))
    if flags & FLAG_COO:
        raise ValueError("COO frame: use decode_sparse()")
    if flags & FLAG_COMPRESSED:
        raise ValueError("compressed frame: use decode_compressed()")
    _account("decoded", len(data), "dense")
    items: list[tuple[str, np.ndarray]] = []
    off = 0
    for entry in meta["leaves"]:
        dt = np.dtype(entry["dtype"])
        n = int(np.prod(entry["shape"], dtype=np.int64)) if entry["shape"] else 1
        nbytes = n * dt.itemsize
        arr = np.frombuffer(payload, dt, count=n, offset=off).reshape(entry["shape"])
        items.append((entry["path"], arr))
        off += nbytes
    if like is None:
        return _rebuild_nested(items)
    by_path = dict(items)
    template_paths, treedef = _match_template_paths(
        [p for p, _ in items], like, "dense wire frame"
    )
    return jax.tree_util.tree_unflatten(
        treedef, [by_path[p] for p in template_paths]
    )


# ---------------------------------------------------------------------------
# Sparse (COO) boundary
# ---------------------------------------------------------------------------

def encode_sparse(packet: SparseMaskPacket) -> bytes:
    """SparseMaskPacket (dense 0/1 element masks, the device encoding) ->
    COO wire frame shipping only selected values + their flat indices."""
    params = _paths_and_leaves(packet.params)
    masks = dict(_paths_and_leaves(packet.element_mask))
    meta, chunks = [], []
    for path, arr in params:
        mask = masks[path]
        flat_idx = np.nonzero(mask.ravel() > 0)[0].astype(np.int32)
        values = np.ascontiguousarray(arr.ravel()[flat_idx])
        meta.append(
            {
                "path": path,
                "shape": list(arr.shape),
                "dtype": str(arr.dtype),
                "nnz": int(flat_idx.size),
            }
        )
        chunks.append(flat_idx.tobytes())
        chunks.append(values.tobytes())
    header = json.dumps({"coo": meta}).encode("utf-8")
    frame = get_framing().frame(header, b"".join(chunks), flags=FLAG_COO)
    _account("encoded", len(frame), "coo")
    return frame


def decode_sparse(data: bytes, like: SparseMaskPacket | None = None) -> SparseMaskPacket:
    """COO wire frame -> dense params + element masks (zeros where absent)."""
    header, payload, flags = get_framing().unframe(data)
    if not flags & FLAG_COO:
        raise ValueError("dense frame: use decode()")
    _account("decoded", len(data), "coo")
    meta = json.loads(header.decode("utf-8"))
    items, mask_items = [], []
    off = 0
    for entry in meta["coo"]:
        dt = np.dtype(entry["dtype"])
        nnz = entry["nnz"]
        idx = np.frombuffer(payload, np.int32, count=nnz, offset=off)
        off += nnz * 4
        vals = np.frombuffer(payload, dt, count=nnz, offset=off)
        off += nnz * dt.itemsize
        n = int(np.prod(entry["shape"], dtype=np.int64)) if entry["shape"] else 1
        dense = np.zeros((n,), dt)
        dense[idx] = vals
        mask = np.zeros((n,), np.float32)
        mask[idx] = 1.0
        items.append((entry["path"], dense.reshape(entry["shape"])))
        mask_items.append((entry["path"], mask.reshape(entry["shape"])))
    if like is None:
        return SparseMaskPacket(
            params=_rebuild_nested(items), element_mask=_rebuild_nested(mask_items)
        )
    by_path, by_path_m = dict(items), dict(mask_items)
    template_paths, treedef = _match_template_paths(
        [p for p, _ in items], like.params, "COO wire frame"
    )
    return SparseMaskPacket(
        params=jax.tree_util.tree_unflatten(
            treedef, [by_path[p] for p in template_paths]
        ),
        element_mask=jax.tree_util.tree_unflatten(
            treedef, [by_path_m[p] for p in template_paths]
        ),
    )


# ---------------------------------------------------------------------------
# Compressed boundary (top-k + int8/int4 quantized frames)
# ---------------------------------------------------------------------------

def account_wire(logical: int, wire: int, direction: str) -> None:
    """fl_wire_* accounting for the compressed exchange: logical (dense)
    vs actual wire bytes, plus the live compression-ratio gauge. Shared by
    the real compressed frames here (direction encoded/decoded) and the
    simulation's per-round estimate (direction gather) so the metric
    family has ONE definition."""
    reg = get_registry()
    reg.counter(
        "fl_wire_bytes_logical_total",
        help="dense byte footprint of trees crossing the compressed codec",
        labels={"direction": direction},
    ).inc(logical)
    reg.counter(
        "fl_wire_bytes_compressed_total",
        help="actual wire bytes of compressed frames",
        labels={"direction": direction},
    ).inc(wire)
    if wire > 0:
        # labeled like the counters: real frames (encoded/decoded, full
        # frame length) and the simulation's payload-only estimate
        # (gather) are different definitions — last-writer-wins on one
        # unlabeled gauge would let the optimistic estimate masquerade as
        # a measured frame ratio
        reg.gauge(
            "fl_wire_compression_ratio",
            help="logical/wire byte ratio of the last compressed exchange",
            labels={"direction": direction},
        ).set(logical / wire)


def _encode_gaps(idx: np.ndarray) -> np.ndarray:
    """Sorted flat indices -> uint16 gap tokens. A token of 0xFFFF is an
    ESCAPE meaning "add 65535 and keep reading"; every real gap token is
    < 0xFFFF, so the stream is unambiguous at any density."""
    idx = np.asarray(idx, np.int64)
    gaps = np.empty_like(idx)
    if idx.size:
        gaps[0] = idx[0]
        gaps[1:] = np.diff(idx)
    esc = gaps // 0xFFFF
    rem = (gaps % 0xFFFF).astype(np.uint16)
    total = int(esc.sum()) + idx.size
    tokens = np.full(total, 0xFFFF, np.uint16)
    tokens[np.cumsum(esc + 1) - 1] = rem
    return tokens


def _decode_gaps(tokens: np.ndarray) -> np.ndarray:
    """Inverse of :func:`_encode_gaps` (indices at the non-escape tokens
    of the running sum)."""
    t = np.asarray(tokens, np.int64)
    return np.cumsum(t)[t != 0xFFFF]


def _global_topk_indices(abs_concat: np.ndarray, k: int) -> np.ndarray:
    """Exact global top-k with the in-graph tie rule (largest magnitude,
    ties broken by LOWEST flat index — jax.lax.top_k semantics, which
    also sorts NaN past every finite value: a poisoned coordinate is
    SELECTED, so the frame carries the poison visibly instead of
    laundering it to zeros)."""
    n = abs_concat.size
    k = max(1, min(int(k), n))
    a = np.where(np.isfinite(abs_concat), abs_concat, np.inf)
    part = np.argpartition(-a, k - 1)[:k]
    kth = a[part].min()
    if np.isinf(kth):
        # >= k non-finite coordinates: lax.top_k ranks NaN above Inf,
        # each group by ascending index (verified empirically) — mirror
        # that exactly so both channels poison the same coordinates
        nan_idx = np.nonzero(np.isnan(abs_concat))[0]
        inf_idx = np.nonzero(np.isinf(abs_concat))[0]
        return np.sort(
            np.concatenate([nan_idx, inf_idx])[:k].astype(np.int64)
        )
    # Everything strictly above the kth magnitude is selected (< k entries
    # by construction); the kth-level plateau fills the remainder by
    # LOWEST index (np.nonzero is already ascending). O(n) with no sort
    # over value ties — a dense plateau (quantized grids, zero tails)
    # costs nothing extra.
    greater = np.nonzero(a > kth)[0]
    ties = np.nonzero(a == kth)[0]
    cand = np.concatenate([greater, ties[: k - greater.size]])
    return np.sort(cand.astype(np.int64))


def compressed_frame_kind(config: CompressionConfig) -> str:
    """Frame-kind label for the byte counters (``topk+int8``-style)."""
    parts = []
    if config.topk_fraction is not None:
        parts.append("topk")
    if config.quant_bits is not None:
        parts.append(f"int{config.quant_bits}")
    return "+".join(parts) if parts else "dense"


def encode_compressed(tree: PyTree, config: CompressionConfig) -> bytes:
    """Dense pytree -> one COMPRESSED wire frame under ``config``.

    The byte realization of the in-graph channel: global magnitude top-k
    (same tie rule, non-finite coordinates selected first so poison stays
    visible), per-leaf f32 scales, int8 bytes / packed int4 nibbles,
    gap-uint16 index sidecars — all CRC-checked by the shared framing.
    Quantization here is DETERMINISTIC round-to-nearest with the scale
    re-derived from the serialized values (max|v|/L): one round trip is
    bounded by half a grid step, and the codec is IDEMPOTENT — a decoded
    frame re-encodes bit-stably, and values whose max magnitude attains
    the grid's top level (fresh in-graph quantization of the same leaf)
    round-trip exactly. The stochastic draw belongs to the client-side
    in-graph transform, not the serializer. ``rotation`` is an in-graph
    preconditioner and does not change the byte format (serializing an
    unrotated reconstruction adds this codec's own bounded quantization
    step on top — see docs/module_guides/compression.md)."""
    entries = _paths_and_leaves(tree)
    logical = sum(a.nbytes for _, a in entries)
    flats = [np.asarray(a, np.float32).ravel() for _, a in entries]
    sizes = [f.size for f in flats]
    n_total = int(sum(sizes))

    leaf_idx: list[np.ndarray | None]
    if config.topk_fraction is not None and n_total:
        from fl4health_tpu.compression.codecs import topk_count

        sel = _global_topk_indices(
            np.abs(np.concatenate(flats)) if flats else np.zeros((0,)),
            topk_count(n_total, config.topk_fraction),
        )
        leaf_idx = []
        off = 0
        for n in sizes:
            local = sel[(sel >= off) & (sel < off + n)] - off
            leaf_idx.append(local.astype(np.int64))
            off += n
    else:
        leaf_idx = [None] * len(flats)

    meta, chunks = [], []
    for (path, arr), flat, idx in zip(entries, flats, leaf_idx):
        values = flat if idx is None else flat[idx]
        entry: dict[str, Any] = {
            "path": path,
            "shape": list(arr.shape),
            "dtype": str(arr.dtype),
        }
        if idx is not None:
            tokens = _encode_gaps(idx)
            entry["nnz"] = int(idx.size)
            entry["idx_tokens"] = int(tokens.size)
            chunks.append(tokens.astype("<u2").tobytes())
        if config.quant_bits is not None:
            L = QUANT_LEVELS[config.quant_bits]
            vmax = float(np.max(np.abs(values))) if values.size else 0.0
            entry["bits"] = config.quant_bits
            if not np.isfinite(vmax):
                # poisoned leaf: a NaN scale makes every selected value
                # decode as NaN — the poison stays visible on the far side
                # (int8 has no NaN, so it rides in the scale sidecar)
                entry["scale"] = float("nan")
                q = np.zeros(values.shape, np.int8)
            else:
                scale = np.float32(vmax / L)
                entry["scale"] = float(scale)
                q = (np.rint(values / scale) if scale > 0
                     else np.zeros_like(values)).clip(-L, L).astype(np.int8)
            chunks.append(pack_int4(q) if config.quant_bits == 4
                          else q.tobytes())
        else:
            chunks.append(values.astype("<f4").tobytes())
        meta.append(entry)
    header = json.dumps({"comp": meta}).encode("utf-8")
    frame = get_framing().frame(
        header, b"".join(chunks), flags=FLAG_COMPRESSED
    )
    _account("encoded", len(frame), compressed_frame_kind(config))
    account_wire(logical, len(frame), "encoded")
    return frame


def decode_compressed(data: bytes, like: PyTree | None = None) -> PyTree:
    """COMPRESSED wire frame -> dense pytree (unselected coordinates are
    zero; values dequantized by the per-leaf scale, cast to the encoded
    dtype). With ``like``, leaves unflatten into the template's treedef —
    a path mismatch raises naming the first mismatched path."""
    header, payload, flags = get_framing().unframe(data)
    if not flags & FLAG_COMPRESSED:
        raise ValueError("not a compressed frame: use decode()/decode_sparse()")
    meta = json.loads(header.decode("utf-8"))
    logical = 0
    items: list[tuple[str, np.ndarray]] = []
    off = 0
    for entry in meta["comp"]:
        dt = np.dtype(entry["dtype"])
        n = int(np.prod(entry["shape"], dtype=np.int64)) if entry["shape"] else 1
        logical += n * dt.itemsize
        idx = None
        nnz = n
        if "nnz" in entry:
            nnz = int(entry["nnz"])
            tok_n = int(entry["idx_tokens"])
            tokens = np.frombuffer(payload, "<u2", count=tok_n, offset=off)
            off += 2 * tok_n
            idx = _decode_gaps(tokens)
            if idx.size != nnz or (idx.size and int(idx[-1]) >= n):
                raise ValueError(
                    f"compressed frame: corrupt index sidecar for leaf "
                    f"{entry['path']!r}"
                )
        bits = entry.get("bits")
        if bits == 4:
            packed_len = math.ceil(nnz / 2)
            values = unpack_int4(
                payload[off: off + packed_len], nnz
            ).astype(np.float32)
            off += packed_len
        elif bits == 8:
            values = np.frombuffer(
                payload, np.int8, count=nnz, offset=off
            ).astype(np.float32)
            off += nnz
        else:
            values = np.frombuffer(
                payload, "<f4", count=nnz, offset=off
            ).astype(np.float32)
            off += 4 * nnz
        if bits is not None:
            values = values * np.float32(entry["scale"])
        dense = np.zeros((n,), np.float32)
        if idx is None:
            dense[:] = values
        else:
            dense[idx] = values
        if np.issubdtype(dt, np.integer):
            # round, don't truncate: astype's toward-zero cast would bias
            # dequantized integer leaves (e.g. -2.976 -> -2, not -3)
            dense = np.rint(dense)
        items.append(
            (entry["path"], dense.reshape(entry["shape"]).astype(dt))
        )
    _account("decoded", len(data), "compressed")
    account_wire(logical, len(data), "decoded")
    if like is None:
        return _rebuild_nested(items)
    by_path = dict(items)
    template_paths, treedef = _match_template_paths(
        [p for p, _ in items], like, "compressed wire frame"
    )
    return jax.tree_util.tree_unflatten(
        treedef, [by_path[p] for p in template_paths]
    )

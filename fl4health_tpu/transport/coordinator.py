"""Coordinator-side primitives for cross-silo rounds over the wire.

The broadcast/gather/weighted-merge core that every host-RPC deployment
shares (examples/cross_silo_example, examples/docker_basic_example,
research/fedprox_cluster) — the role Flower's server-side
``aggregate_fit``/NumPy ndarray plumbing plays in the reference
(/root/reference/fl4health/strategies/basic_fedavg.py ``aggregate_fit``
over gRPC results). One implementation so the wire pattern (single
serialization per round, n-weighted FedAvg over reply trees) has one home.

Resilience rework (resilience subsystem PR): the round fan-out is
CONCURRENT — every silo is dialed in parallel, so round wall time tracks
the slowest *surviving* silo instead of the sum of the chain — with
per-silo retry/backoff, circuit breakers and quorum semantics layered from
``fl4health_tpu.resilience.retry``:

- ``retry=RetryPolicy(...)`` re-dials a failed silo with jittered
  exponential backoff (each attempt bounded by the policy's per-attempt
  timeout);
- ``breakers=`` (a ``dict[str, CircuitBreaker]``, keyed ``"host:port"``)
  skips a silo whose circuit is open without paying its connect timeout;
- ``quorum=`` proceeds once enough silos replied — the missing silos'
  weights simply never enter ``weighted_merge``'s normalization, which is
  the renormalize-and-continue semantics of partial participation.

Failures land in ``transport_rpc_failures_total`` with a ``reason`` label
(``timeout`` / ``connection`` / ``decode`` / ``circuit_open`` / ``other``)
per attempt, and retries in ``transport_rpc_retries_total`` — dead-silo
triage reads off the metrics page, not the logs.
"""

from __future__ import annotations

import dataclasses
import math
import time
from concurrent.futures import ThreadPoolExecutor, as_completed
from typing import Any, Mapping, Sequence

import jax
import numpy as np

from fl4health_tpu.observability.registry import get_registry
from fl4health_tpu.observability.spans import get_tracer
from fl4health_tpu.resilience.retry import (
    CircuitBreaker,
    RetryPolicy,
    call_with_retry,
    classify_failure,
)
from fl4health_tpu.transport.codec import decode, encode
from fl4health_tpu.transport.loopback import call

# RPC latency buckets tuned for LAN/WAN silo links (1ms .. 60s)
_RPC_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                10.0, 30.0, 60.0)


class QuorumError(RuntimeError):
    """Raised when fewer silos replied than the round's quorum requires.

    Attributes: ``required``, ``succeeded``, ``failures`` (list of
    ``(silo, reason)``)."""

    def __init__(self, message: str, *, required: int, succeeded: int,
                 failures: Sequence[tuple[str, str]]):
        super().__init__(message)
        self.required = required
        self.succeeded = succeeded
        self.failures = list(failures)


@dataclasses.dataclass
class SiloResult:
    """Outcome of one silo's round trip (success XOR error)."""

    silo: str
    index: int
    reply: dict[str, Any] | None = None
    error: Exception | None = None
    reason: str | None = None
    attempts: int = 0
    elapsed_s: float = 0.0

    @property
    def ok(self) -> bool:
        return self.reply is not None


@dataclasses.dataclass
class BroadcastReport:
    """Per-silo results of one concurrent broadcast, in silo order."""

    results: list[SiloResult]

    @property
    def replies(self) -> list[dict[str, Any]]:
        return [r.reply for r in self.results if r.ok]

    @property
    def failures(self) -> list[SiloResult]:
        return [r for r in self.results if not r.ok]


def _required_replies(quorum: int | float | None, n_silos: int) -> int:
    """Quorum spec -> required success count. ``None`` = every silo; a
    float in (0, 1] is a fraction (ceil); an int is an absolute count."""
    if quorum is None:
        return n_silos
    if isinstance(quorum, float):
        if not 0.0 < quorum <= 1.0:
            raise ValueError(f"fractional quorum must be in (0, 1]; got {quorum}")
        return max(1, math.ceil(quorum * n_silos))
    q = int(quorum)
    if not 1 <= q <= n_silos:
        raise ValueError(
            f"quorum must be in [1, {n_silos}] for {n_silos} silos; got {q}"
        )
    return q


def _silo_round_trip(
    index: int,
    host: str,
    port: int,
    frame: bytes,
    reply_template: Mapping[str, Any],
    timeout: float | None,
    retry: RetryPolicy | None,
    breaker: CircuitBreaker | None,
) -> SiloResult:
    """One silo's full round trip (runs on a fan-out worker thread)."""
    reg, tracer = get_registry(), get_tracer()
    silo = f"{host}:{port}"
    hist = reg.histogram(
        "transport_rpc_latency_seconds",
        help="per-silo round-trip latency (request + decode)",
        labels={"silo": silo},
        buckets=_RPC_BUCKETS,
    )
    attempt_timeout = timeout
    if attempt_timeout is None and retry is not None:
        attempt_timeout = retry.timeout_s
    kwargs = {} if attempt_timeout is None else {"timeout": attempt_timeout}
    result = SiloResult(silo=silo, index=index)

    def do_call():
        result.attempts += 1
        raw = call(host, port, frame, **kwargs)
        return decode(raw, like=reply_template), len(raw)

    def on_failure(exc: BaseException, attempt: int, will_retry: bool):
        reg.counter(
            "transport_rpc_failures_total",
            help="silo round trips that raised, by failure reason",
            labels={"silo": silo, "reason": classify_failure(exc)},
        ).inc()
        if will_retry:
            reg.counter(
                "transport_rpc_retries_total",
                help="re-dials of a failed silo round trip",
                labels={"silo": silo},
            ).inc()

    t0 = time.perf_counter()
    with tracer.span("rpc", cat="transport", silo=silo,
                     request_bytes=len(frame)) as sp:
        try:
            reply, raw_len = call_with_retry(
                do_call, policy=retry, breaker=breaker, on_failure=on_failure
            )
        except Exception as e:  # noqa: BLE001 — reported per silo, quorum decides
            result.error = e
            result.reason = classify_failure(e)
            result.elapsed_s = time.perf_counter() - t0
            sp.set(failed=True, reason=result.reason)
            return result
        result.elapsed_s = time.perf_counter() - t0
        # successes only: a timed-out silo's 60s ceiling in the latency
        # histogram would swamp the percentiles of working round trips
        # (dead-silo visibility lives in the failure counter above)
        hist.observe(result.elapsed_s)
        sp.set(reply_bytes=raw_len)
    result.reply = reply
    return result


def broadcast_round_detailed(
    silos: Sequence[tuple[str, int]],
    global_params: Any,
    reply_template: Mapping[str, Any],
    timeout: float | None = None,
    *,
    retry: RetryPolicy | None = None,
    breakers: Mapping[str, CircuitBreaker] | None = None,
    max_workers: int | None = None,
    fail_fast: bool = False,
) -> BroadcastReport:
    """Concurrent fan-out: encode ONCE (the frame is identical for every
    silo), dial every silo in parallel, decode each reply against
    ``reply_template``. Never raises for a silo failure — the report
    carries per-silo success/error/reason and the caller applies its
    quorum policy (``broadcast_round`` does).

    ``fail_fast`` (the no-quorum legacy profile): return as soon as the
    first failure is KNOWN instead of waiting out the slowest silo —
    not-yet-dialed silos are cancelled (their results are absent from the
    report); in-flight round trips finish on their worker threads but the
    caller stops waiting. Without a quorum the round is doomed the moment
    one silo fails, so there is nothing to wait for."""
    frame = encode(global_params)
    if not silos:
        return BroadcastReport(results=[])
    workers = max_workers or min(len(silos), 32)

    def task(i: int, host: str, port: int) -> SiloResult:
        breaker = (breakers or {}).get(f"{host}:{port}")
        return _silo_round_trip(
            i, host, port, frame, reply_template, timeout, retry, breaker
        )

    pool = ThreadPoolExecutor(max_workers=workers)
    try:
        futures = [pool.submit(task, i, host, port)
                   for i, (host, port) in enumerate(silos)]
        results: list[SiloResult] = []
        for fut in as_completed(futures):
            res = fut.result()
            results.append(res)
            if fail_fast and not res.ok:
                for f in futures:
                    f.cancel()
                break
        results.sort(key=lambda r: r.index)
        return BroadcastReport(results=results)
    finally:
        pool.shutdown(wait=not fail_fast, cancel_futures=fail_fast)


def broadcast_round(
    silos: Sequence[tuple[str, int]],
    global_params: Any,
    reply_template: Mapping[str, Any],
    timeout: float | None = None,
    *,
    retry: RetryPolicy | None = None,
    quorum: int | float | None = None,
    breakers: Mapping[str, CircuitBreaker] | None = None,
    max_workers: int | None = None,
) -> list[dict[str, Any]]:
    """Send the global params to every silo concurrently and decode each
    reply against ``reply_template``; returns the successful replies in
    silo order.

    Quorum semantics: with ``quorum=None`` every silo must reply and the
    first failure (in silo order) re-raises — the legacy contract. With a
    quorum (absolute count, or fraction of the cohort) the round proceeds
    once enough silos replied; the survivors' replies feed
    ``weighted_merge``, whose normalization IS the weight renormalization
    over the surviving cohort. Too few survivors raise :class:`QuorumError`
    naming every failed silo and its reason.

    Observability: each silo's round trip lands in a per-silo
    ``transport_rpc_latency_seconds`` histogram and an ``rpc`` span; every
    failed attempt bumps ``transport_rpc_failures_total`` with a
    ``reason`` label and retries bump ``transport_rpc_retries_total`` —
    partial rounds stay visible in the metrics even when an exception
    unwinds the round.
    """
    required = _required_replies(quorum, len(silos))
    report = broadcast_round_detailed(
        silos, global_params, reply_template, timeout,
        retry=retry, breakers=breakers, max_workers=max_workers,
        # no quorum = the round cannot survive any failure, so stop waiting
        # the moment one is known (legacy fail-fast profile)
        fail_fast=quorum is None,
    )
    failures = report.failures
    if quorum is None and failures:
        raise failures[0].error
    replies = report.replies
    if len(replies) < required:
        raise QuorumError(
            f"broadcast_round: {len(replies)}/{len(silos)} silos replied "
            f"but quorum requires {required} "
            f"(failed: {[(f.silo, f.reason) for f in failures]})",
            required=required,
            succeeded=len(replies),
            failures=[(f.silo, f.reason or "unknown") for f in failures],
        )
    return replies


def weighted_merge(
    replies: Sequence[Mapping[str, Any]],
    params_key: str = "params",
    weight_key: str = "n",
) -> tuple[Any, np.ndarray]:
    """n-weighted FedAvg over reply param trees -> (merged, weights).

    Normalizing by the sum of the PRESENT replies' weights is exactly the
    quorum path's renormalization: silos that missed the round contribute
    neither numerator nor denominator."""
    weights = np.asarray([float(r[weight_key]) for r in replies])
    total = weights.sum()
    if total <= 0:
        raise ValueError(
            f"weighted_merge: total weight is {total} (every silo reported "
            f"{weight_key}=0 — empty shards or failed fits); refusing to "
            "produce NaN global params"
        )
    weights = weights / total
    merged = jax.tree_util.tree_map(
        lambda *leaves: sum(w * leaf for w, leaf in zip(weights, leaves)),
        *[r[params_key] for r in replies],
    )
    return merged, weights

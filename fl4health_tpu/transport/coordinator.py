"""Coordinator-side primitives for cross-silo rounds over the wire.

The broadcast/gather/weighted-merge core that every host-RPC deployment
shares (examples/cross_silo_example, examples/docker_basic_example,
research/fedprox_cluster) — the role Flower's server-side
``aggregate_fit``/NumPy ndarray plumbing plays in the reference
(/root/reference/fl4health/strategies/basic_fedavg.py ``aggregate_fit``
over gRPC results). One implementation so the wire pattern (single
serialization per round, n-weighted FedAvg over reply trees) has one home.

Resilience rework (resilience subsystem PR): the round fan-out is
CONCURRENT — every silo is dialed in parallel, so round wall time tracks
the slowest *surviving* silo instead of the sum of the chain — with
per-silo retry/backoff, circuit breakers and quorum semantics layered from
``fl4health_tpu.resilience.retry``:

- ``retry=RetryPolicy(...)`` re-dials a failed silo with jittered
  exponential backoff (each attempt bounded by the policy's per-attempt
  timeout, the whole per-silo attempt loop by its optional ``deadline_s``
  budget — retries can never push a silo past the round deadline);
- ``breakers=`` (a ``dict[str, CircuitBreaker]``, keyed ``"host:port"``)
  skips a silo whose circuit is open without paying its connect timeout;
- ``quorum=`` proceeds once enough silos replied — the missing silos'
  weights simply never enter ``weighted_merge``'s normalization, which is
  the renormalize-and-continue semantics of partial participation.

Failures land in ``transport_rpc_failures_total`` with a ``reason`` label
(``timeout`` / ``connection`` / ``decode`` / ``circuit_open`` /
``deadline`` / ``other``) per attempt, and retries in
``transport_rpc_retries_total`` — dead-silo triage reads off the metrics
page, not the logs.
"""

from __future__ import annotations

import dataclasses
import math
import queue
import threading
import time
from concurrent.futures import ThreadPoolExecutor, as_completed
from typing import Any, Mapping, Sequence

import jax
import numpy as np

from fl4health_tpu.observability.registry import get_registry
from fl4health_tpu.observability.spans import get_tracer
from fl4health_tpu.observability.tracectx import TraceContext, flow_id
from fl4health_tpu.resilience.retry import (
    CircuitBreaker,
    RetryDeadlineError,
    RetryPolicy,
    call_with_retry,
    classify_failure,
)
from fl4health_tpu.transport.codec import decode, encode
from fl4health_tpu.transport.loopback import call

# RPC latency buckets tuned for LAN/WAN silo links (1ms .. 60s)
_RPC_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                10.0, 30.0, 60.0)


class QuorumError(RuntimeError):
    """Raised when fewer silos replied than the round's quorum requires.

    Attributes: ``required``, ``succeeded``, ``failures`` (list of
    ``(silo, reason)``), and — when the raising path had one — ``report``,
    the full per-silo :class:`BroadcastReport` (attempt counts, latencies,
    failure reasons), so a postmortem bundle's ``verdict.json`` can name
    every silo's outcome instead of just the shortfall."""

    def __init__(self, message: str, *, required: int, succeeded: int,
                 failures: Sequence[tuple[str, str]],
                 report: "BroadcastReport | None" = None):
        super().__init__(message)
        self.required = required
        self.succeeded = succeeded
        self.failures = list(failures)
        self.report = report


@dataclasses.dataclass
class SiloResult:
    """Outcome of one silo's round trip (success XOR error)."""

    silo: str
    index: int
    reply: dict[str, Any] | None = None
    error: Exception | None = None
    reason: str | None = None
    attempts: int = 0
    elapsed_s: float = 0.0

    @property
    def ok(self) -> bool:
        return self.reply is not None


@dataclasses.dataclass
class BroadcastReport:
    """Per-silo results of one concurrent broadcast, in silo order."""

    results: list[SiloResult]

    @property
    def replies(self) -> list[dict[str, Any]]:
        return [r.reply for r in self.results if r.ok]

    @property
    def failures(self) -> list[SiloResult]:
        return [r for r in self.results if not r.ok]


def _required_replies(quorum: int | float | None, n_silos: int) -> int:
    """Quorum spec -> required success count. ``None`` = every silo; a
    float in (0, 1] is a fraction (ceil); an int is an absolute count."""
    if quorum is None:
        return n_silos
    if isinstance(quorum, float):
        if not 0.0 < quorum <= 1.0:
            raise ValueError(f"fractional quorum must be in (0, 1]; got {quorum}")
        return max(1, math.ceil(quorum * n_silos))
    q = int(quorum)
    if not 1 <= q <= n_silos:
        raise ValueError(
            f"quorum must be in [1, {n_silos}] for {n_silos} silos; got {q}"
        )
    return q


def _silo_round_trip(
    index: int,
    host: str,
    port: int,
    frame: bytes,
    reply_template: Mapping[str, Any],
    timeout: float | None,
    retry: RetryPolicy | None,
    breaker: CircuitBreaker | None,
    decoder: Any = None,
    trace: TraceContext | None = None,
) -> SiloResult:
    """One silo's full round trip (runs on a fan-out worker thread).

    ``decoder`` overrides the default dense-template decode — e.g.
    ``lambda raw: decode_compressed(raw, like=template)`` when silos reply
    with COMPRESSED frames (transport/codec.py), so compressed exchange
    rides the same retry/breaker/metrics machinery as dense frames.

    ``trace`` stamps the rpc span with the round's trace context and, on
    a successful reply, closes the round's flow arrow (``"f"``) inside
    this span — the far end of the broadcast's ``"s"`` and the silo
    handler's ``"t"`` once ``tools/trace_merge.py`` has aligned the
    per-process traces."""
    reg, tracer = get_registry(), get_tracer()
    silo = f"{host}:{port}"
    hist = reg.histogram(
        "transport_rpc_latency_seconds",
        help="per-silo round-trip latency (request + decode)",
        labels={"silo": silo},
        buckets=_RPC_BUCKETS,
    )
    attempt_timeout = timeout
    if attempt_timeout is None and retry is not None:
        attempt_timeout = retry.timeout_s
    kwargs = {} if attempt_timeout is None else {"timeout": attempt_timeout}
    result = SiloResult(silo=silo, index=index)

    def do_call():
        result.attempts += 1
        raw = call(host, port, frame, **kwargs)
        if decoder is not None:
            return decoder(raw), len(raw)
        return decode(raw, like=reply_template), len(raw)

    def on_failure(exc: BaseException, attempt: int, will_retry: bool):
        reg.counter(
            "transport_rpc_failures_total",
            help="silo round trips that raised, by failure reason",
            labels={"silo": silo, "reason": classify_failure(exc)},
        ).inc()
        if will_retry:
            reg.counter(
                "transport_rpc_retries_total",
                help="re-dials of a failed silo round trip",
                labels={"silo": silo},
            ).inc()

    span_args: dict[str, Any] = {"silo": silo, "request_bytes": len(frame)}
    if trace is not None:
        span_args.update(trace_id=trace.trace_id, round=trace.round)
    t0 = time.perf_counter()
    with tracer.span("rpc", cat="transport", **span_args) as sp:
        try:
            reply, raw_len = call_with_retry(
                do_call, policy=retry, breaker=breaker, on_failure=on_failure
            )
        except Exception as e:  # noqa: BLE001 — reported per silo, quorum decides
            result.error = e
            result.reason = classify_failure(e)
            if isinstance(e, RetryDeadlineError):
                # the budget death is its own failure event: the
                # per-attempt counts above carried the underlying wire
                # reasons, this one records that the retry budget died
                reg.counter(
                    "transport_rpc_failures_total",
                    help="silo round trips that raised, by failure reason",
                    labels={"silo": silo, "reason": result.reason},
                ).inc()
            result.elapsed_s = time.perf_counter() - t0
            sp.set(failed=True, reason=result.reason)
            return result
        result.elapsed_s = time.perf_counter() - t0
        # successes only: a timed-out silo's 60s ceiling in the latency
        # histogram would swamp the percentiles of working round trips
        # (dead-silo visibility lives in the failure counter above)
        hist.observe(result.elapsed_s)
        sp.set(reply_bytes=raw_len)
        if trace is not None:
            tracer.flow("f", "rpc_flow",
                        flow_id(trace.trace_id, trace.round),
                        round=trace.round, silo=silo)
    result.reply = reply
    return result


def broadcast_round_detailed(
    silos: Sequence[tuple[str, int]],
    global_params: Any,
    reply_template: Mapping[str, Any],
    timeout: float | None = None,
    *,
    retry: RetryPolicy | None = None,
    breakers: Mapping[str, CircuitBreaker] | None = None,
    max_workers: int | None = None,
    fail_fast: bool = False,
    decoder: Any = None,
    trace: TraceContext | None = None,
) -> BroadcastReport:
    """Concurrent fan-out: encode ONCE (the frame is identical for every
    silo), dial every silo in parallel, decode each reply against
    ``reply_template``. Never raises for a silo failure — the report
    carries per-silo success/error/reason and the caller applies its
    quorum policy (``broadcast_round`` does).

    ``fail_fast`` (the no-quorum legacy profile): return as soon as the
    first failure is KNOWN instead of waiting out the slowest silo —
    not-yet-dialed silos are cancelled (their results are absent from the
    report); in-flight round trips finish on their worker threads but the
    caller stops waiting. Without a quorum the round is doomed the moment
    one silo fails, so there is nothing to wait for.

    Tracing: with the process tracer enabled, a trace context (``trace``,
    or a fresh one) rides in the frame header and a flow-start event
    (``"s"``) is emitted here, which silo-side ``traced_handler`` spans
    (``"t"``) and each reply's ``"f"`` continue — one arrowed
    broadcast → silo → reply flow per round in the merged timeline. The
    frame is encoded once for all silos, so the flow id is per ROUND, not
    per silo: Perfetto fans one start out to every silo's step, which is
    the actual fan-out topology."""
    tracer = get_tracer()
    ctx = trace
    if ctx is None and tracer.enabled:
        ctx = TraceContext.fresh(round=0)
    with tracer.span("broadcast_encode", cat="transport",
                     silos=len(silos),
                     **({"trace_id": ctx.trace_id, "round": ctx.round}
                        if ctx is not None else {})):
        frame = encode(
            global_params,
            trace=ctx.to_header() if ctx is not None else None,
        )
        if ctx is not None:
            tracer.flow("s", "rpc_flow", flow_id(ctx.trace_id, ctx.round),
                        round=ctx.round, silos=len(silos))
    if not silos:
        return BroadcastReport(results=[])
    workers = max_workers or min(len(silos), 32)

    def task(i: int, host: str, port: int) -> SiloResult:
        breaker = (breakers or {}).get(f"{host}:{port}")
        return _silo_round_trip(
            i, host, port, frame, reply_template, timeout, retry, breaker,
            decoder=decoder, trace=ctx,
        )

    pool = ThreadPoolExecutor(max_workers=workers)
    try:
        futures = [pool.submit(task, i, host, port)
                   for i, (host, port) in enumerate(silos)]
        results: list[SiloResult] = []
        for fut in as_completed(futures):
            res = fut.result()
            results.append(res)
            if fail_fast and not res.ok:
                for f in futures:
                    f.cancel()
                break
        results.sort(key=lambda r: r.index)
        return BroadcastReport(results=results)
    finally:
        pool.shutdown(wait=not fail_fast, cancel_futures=fail_fast)


def broadcast_round(
    silos: Sequence[tuple[str, int]],
    global_params: Any,
    reply_template: Mapping[str, Any],
    timeout: float | None = None,
    *,
    retry: RetryPolicy | None = None,
    quorum: int | float | None = None,
    breakers: Mapping[str, CircuitBreaker] | None = None,
    max_workers: int | None = None,
    trace: TraceContext | None = None,
) -> list[dict[str, Any]]:
    """Send the global params to every silo concurrently and decode each
    reply against ``reply_template``; returns the successful replies in
    silo order.

    Quorum semantics: with ``quorum=None`` every silo must reply and the
    first failure (in silo order) re-raises — the legacy contract. With a
    quorum (absolute count, or fraction of the cohort) the round proceeds
    once enough silos replied; the survivors' replies feed
    ``weighted_merge``, whose normalization IS the weight renormalization
    over the surviving cohort. Too few survivors raise :class:`QuorumError`
    naming every failed silo and its reason.

    Observability: each silo's round trip lands in a per-silo
    ``transport_rpc_latency_seconds`` histogram and an ``rpc`` span; every
    failed attempt bumps ``transport_rpc_failures_total`` with a
    ``reason`` label and retries bump ``transport_rpc_retries_total`` —
    partial rounds stay visible in the metrics even when an exception
    unwinds the round.
    """
    required = _required_replies(quorum, len(silos))
    report = broadcast_round_detailed(
        silos, global_params, reply_template, timeout,
        retry=retry, breakers=breakers, max_workers=max_workers,
        # no quorum = the round cannot survive any failure, so stop waiting
        # the moment one is known (legacy fail-fast profile)
        fail_fast=quorum is None,
        trace=trace,
    )
    failures = report.failures
    if quorum is None and failures:
        raise failures[0].error
    replies = report.replies
    if len(replies) < required:
        raise QuorumError(
            f"broadcast_round: {len(replies)}/{len(silos)} silos replied "
            f"but quorum requires {required} "
            f"(failed: {[(f.silo, f.reason) for f in failures]})",
            required=required,
            succeeded=len(replies),
            failures=[(f.silo, f.reason or "unknown") for f in failures],
            report=report,
        )
    return replies


@dataclasses.dataclass
class AsyncReply:
    """One silo update pulled from the :class:`SiloUpdateBuffer`.

    ``version`` is the server version the silo trained from (stamped at
    dispatch); the caller computes staleness as ``current_version -
    reply.version`` — the same accounting the simulation's static event
    plan uses (``server/async_schedule.py``)."""

    result: SiloResult
    version: int

    @property
    def reply(self) -> dict[str, Any]:
        return self.result.reply


class SiloUpdateBuffer:
    """Non-blocking silo round trips feeding a FedBuff-style buffer.

    ``broadcast_round`` is a BARRIER: the round returns when every (or a
    quorum of) silo replied, so wall time tracks the slowest survivor.
    This class is the wire-side counterpart of the simulation's
    buffered-async mode: ``dispatch`` fans requests out WITHOUT waiting —
    each silo's reply (decoded, CRC-checked, retry/breaker-wrapped by the
    same ``_silo_round_trip`` the synchronous path uses) lands in an
    internal arrival queue as it completes — and ``take(k)`` blocks only
    until ``k`` successful updates have arrived. Slow silos keep training
    through an aggregation; their updates arrive later, tagged with the
    (now stale) ``version`` they were dispatched under, and the caller
    discounts them exactly like the in-graph path discounts its event
    plan's staleness.

    Failures never fill the buffer: a failed round trip bumps the same
    reason-labeled ``transport_rpc_failures_total`` counters and is
    dropped from the arrival queue (``failures`` keeps them inspectable).
    ``take`` raises :class:`QuorumError` when fewer in-flight requests
    remain than the buffer still needs — a dead cohort cannot block the
    coordinator forever."""

    def __init__(
        self,
        reply_template: Mapping[str, Any],
        *,
        timeout: float | None = None,
        retry: RetryPolicy | None = None,
        breakers: Mapping[str, CircuitBreaker] | None = None,
        max_workers: int = 32,
        decoder: Any = None,
    ):
        self._template = reply_template
        self._decoder = decoder
        self._timeout = timeout
        self._retry = retry
        self._breakers = breakers or {}
        self._pool = ThreadPoolExecutor(
            max_workers=max_workers, thread_name_prefix="fl-silo-buffer"
        )
        self._arrived: queue.Queue[AsyncReply] = queue.Queue()
        self._lock = threading.Lock()
        self._in_flight = 0
        self._failures: list[AsyncReply] = []
        self._closed = False

    @property
    def failures(self) -> list[AsyncReply]:
        """Completed-but-failed round trips (reason on ``result.reason``)."""
        with self._lock:
            return list(self._failures)

    def in_flight(self) -> int:
        """Requests dispatched but not yet completed (success or failure)."""
        with self._lock:
            return self._in_flight

    def pending(self) -> int:
        """Successful updates sitting in the buffer right now."""
        return self._arrived.qsize()

    def dispatch(
        self,
        silos: Sequence[tuple[str, int]],
        global_params: Any,
        version: int,
        trace: TraceContext | None = None,
    ) -> None:
        """Ship ``global_params`` (encoded ONCE) to ``silos`` without
        waiting; each reply joins the arrival queue tagged ``version``.

        With the process tracer enabled, the dispatch carries a trace
        context (``round`` = the server version) and emits the flow-start
        event, exactly like the synchronous broadcast — stale replies'
        ``"f"`` arrows land rounds later, which is the staleness made
        visible."""
        if self._closed:
            raise RuntimeError("SiloUpdateBuffer is closed")
        if not silos:
            return
        tracer = get_tracer()
        ctx = trace
        if ctx is None and tracer.enabled:
            ctx = TraceContext.fresh(round=version)
        with tracer.span("dispatch_encode", cat="transport",
                         silos=len(silos), version=version):
            frame = encode(
                global_params,
                trace=ctx.to_header() if ctx is not None else None,
            )
            if ctx is not None:
                tracer.flow("s", "rpc_flow",
                            flow_id(ctx.trace_id, ctx.round),
                            round=ctx.round, silos=len(silos))
        with self._lock:
            self._in_flight += len(silos)
        for i, (host, port) in enumerate(silos):
            self._pool.submit(self._one, i, host, port, frame, version, ctx)

    def _one(self, index: int, host: str, port: int, frame: bytes,
             version: int, trace: TraceContext | None = None) -> None:
        breaker = self._breakers.get(f"{host}:{port}")
        try:
            result = _silo_round_trip(
                index, host, port, frame, self._template, self._timeout,
                self._retry, breaker, decoder=self._decoder, trace=trace,
            )
        except BaseException as e:  # noqa: BLE001 — a worker must never die silently
            result = SiloResult(silo=f"{host}:{port}", index=index, error=e,
                                reason=classify_failure(e))
        reply = AsyncReply(result=result, version=version)
        if not result.ok:
            with self._lock:
                self._in_flight -= 1
                self._failures.append(reply)
            return
        # success: enqueue BEFORE decrementing — take()'s reachability
        # check (in_flight + qsize) may transiently double-count this
        # reply, which is harmless, but must never see it in NEITHER
        # count (a spurious QuorumError on an update that was about to
        # land)
        self._arrived.put(reply)
        with self._lock:
            self._in_flight -= 1

    def take(self, k: int, timeout: float | None = None) -> list[AsyncReply]:
        """Block until ``k`` successful updates have arrived; returns them
        in ARRIVAL order (the buffer semantics — not silo order).

        Raises :class:`QuorumError` if the buffer can no longer fill
        (fewer in-flight requests remain than updates still needed) and
        ``TimeoutError`` if ``timeout`` elapses first. Either raise
        RE-QUEUES any updates this call had already dequeued — arrived,
        CRC-checked updates are never lost to a failed take (a retrying
        caller still receives them, re-queued behind any updates that
        landed in the meantime)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        out: list[AsyncReply] = []

        def bail(exc: BaseException) -> BaseException:
            for r in out:
                self._arrived.put(r)
            return exc

        while len(out) < k:
            with self._lock:
                reachable = self._in_flight + self._arrived.qsize()
            if reachable < k - len(out):
                failures = [
                    (f.result.silo, f.result.reason or "unknown")
                    for f in self.failures
                ]
                raise bail(QuorumError(
                    f"SiloUpdateBuffer: buffer needs {k - len(out)} more "
                    f"updates but only {reachable} round trips remain in "
                    f"flight (failed: {failures})",
                    required=k, succeeded=len(out), failures=failures,
                ))
            wait = 0.1
            if deadline is not None:
                wait = min(wait, deadline - time.monotonic())
                if wait <= 0:
                    raise bail(TimeoutError(
                        f"SiloUpdateBuffer.take({k}): only {len(out)} "
                        f"updates arrived within {timeout}s"
                    ))
            try:
                out.append(self._arrived.get(timeout=wait))
            except queue.Empty:
                continue
        return out

    def close(self, wait: bool = False) -> None:
        self._closed = True
        self._pool.shutdown(wait=wait, cancel_futures=not wait)


def weighted_merge(
    replies: Sequence[Mapping[str, Any]],
    params_key: str = "params",
    weight_key: str = "n",
) -> tuple[Any, np.ndarray]:
    """n-weighted FedAvg over reply param trees -> (merged, weights).

    Normalizing by the sum of the PRESENT replies' weights is exactly the
    quorum path's renormalization: silos that missed the round contribute
    neither numerator nor denominator."""
    weights = np.asarray([float(r[weight_key]) for r in replies])
    total = weights.sum()
    if total <= 0:
        raise ValueError(
            f"weighted_merge: total weight is {total} (every silo reported "
            f"{weight_key}=0 — empty shards or failed fits); refusing to "
            "produce NaN global params"
        )
    weights = weights / total
    merged = jax.tree_util.tree_map(
        lambda *leaves: sum(w * leaf for w, leaf in zip(weights, leaves)),
        *[r[params_key] for r in replies],
    )
    return merged, weights

"""Coordinator-side primitives for cross-silo rounds over the wire.

The broadcast/gather/weighted-merge core that every host-RPC deployment
shares (examples/cross_silo_example, examples/docker_basic_example,
research/fedprox_cluster) — the role Flower's server-side
``aggregate_fit``/NumPy ndarray plumbing plays in the reference
(/root/reference/fl4health/strategies/basic_fedavg.py ``aggregate_fit``
over gRPC results). One implementation so the wire pattern (single
serialization per round, n-weighted FedAvg over reply trees) has one home.
"""

from __future__ import annotations

import time
from typing import Any, Mapping, Sequence

import jax
import numpy as np

from fl4health_tpu.observability.registry import get_registry
from fl4health_tpu.observability.spans import get_tracer
from fl4health_tpu.transport.codec import decode, encode
from fl4health_tpu.transport.loopback import call

# RPC latency buckets tuned for LAN/WAN silo links (1ms .. 60s)
_RPC_BUCKETS = (0.001, 0.005, 0.01, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0,
                10.0, 30.0, 60.0)


def broadcast_round(
    silos: Sequence[tuple[str, int]],
    global_params: Any,
    reply_template: Mapping[str, Any],
    timeout: float | None = None,
) -> list[dict[str, Any]]:
    """Send the global params to every silo (ONE serialization — the frame
    is identical) and decode each reply against ``reply_template``.

    Observability: each silo's request/decode round trip lands in a
    per-silo ``transport_rpc_latency_seconds`` histogram and a ``rpc`` span
    (no-ops while the process tracer is disabled); failures bump
    ``transport_rpc_failures_total`` before re-raising so partial rounds
    stay visible in the metrics even when the exception unwinds the round.
    """
    reg, tracer = get_registry(), get_tracer()
    frame = encode(global_params)
    kwargs = {} if timeout is None else {"timeout": timeout}
    replies = []
    for host, port in silos:
        silo = f"{host}:{port}"
        hist = reg.histogram(
            "transport_rpc_latency_seconds",
            help="per-silo round-trip latency (request + decode)",
            labels={"silo": silo},
            buckets=_RPC_BUCKETS,
        )
        t0 = time.perf_counter()
        with tracer.span("rpc", cat="transport", silo=silo,
                         request_bytes=len(frame)) as sp:
            try:
                raw = call(host, port, frame, **kwargs)
                reply = decode(raw, like=reply_template)
            except Exception:
                reg.counter(
                    "transport_rpc_failures_total",
                    help="silo round trips that raised",
                    labels={"silo": silo},
                ).inc()
                raise
            # successes only: a timed-out silo's 60s ceiling in the latency
            # histogram would swamp the percentiles of working round trips
            # (dead-silo visibility lives in the failure counter above)
            hist.observe(time.perf_counter() - t0)
            sp.set(reply_bytes=len(raw))
        replies.append(reply)
    return replies


def weighted_merge(
    replies: Sequence[Mapping[str, Any]],
    params_key: str = "params",
    weight_key: str = "n",
) -> tuple[Any, np.ndarray]:
    """n-weighted FedAvg over reply param trees -> (merged, weights)."""
    weights = np.asarray([float(r[weight_key]) for r in replies])
    total = weights.sum()
    if total <= 0:
        raise ValueError(
            f"weighted_merge: total weight is {total} (every silo reported "
            f"{weight_key}=0 — empty shards or failed fits); refusing to "
            "produce NaN global params"
        )
    weights = weights / total
    merged = jax.tree_util.tree_map(
        lambda *leaves: sum(w * leaf for w, leaf in zip(weights, leaves)),
        *[r[params_key] for r in replies],
    )
    return merged, weights

"""Coordinator-side primitives for cross-silo rounds over the wire.

The broadcast/gather/weighted-merge core that every host-RPC deployment
shares (examples/cross_silo_example, examples/docker_basic_example,
research/fedprox_cluster) — the role Flower's server-side
``aggregate_fit``/NumPy ndarray plumbing plays in the reference
(/root/reference/fl4health/strategies/basic_fedavg.py ``aggregate_fit``
over gRPC results). One implementation so the wire pattern (single
serialization per round, n-weighted FedAvg over reply trees) has one home.
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

import jax
import numpy as np

from fl4health_tpu.transport.codec import decode, encode
from fl4health_tpu.transport.loopback import call


def broadcast_round(
    silos: Sequence[tuple[str, int]],
    global_params: Any,
    reply_template: Mapping[str, Any],
    timeout: float | None = None,
) -> list[dict[str, Any]]:
    """Send the global params to every silo (ONE serialization — the frame
    is identical) and decode each reply against ``reply_template``."""
    frame = encode(global_params)
    kwargs = {} if timeout is None else {"timeout": timeout}
    return [
        decode(call(host, port, frame, **kwargs), like=reply_template)
        for host, port in silos
    ]


def weighted_merge(
    replies: Sequence[Mapping[str, Any]],
    params_key: str = "params",
    weight_key: str = "n",
) -> tuple[Any, np.ndarray]:
    """n-weighted FedAvg over reply param trees -> (merged, weights)."""
    weights = np.asarray([float(r[weight_key]) for r in replies])
    total = weights.sum()
    if total <= 0:
        raise ValueError(
            f"weighted_merge: total weight is {total} (every silo reported "
            f"{weight_key}=0 — empty shards or failed fits); refusing to "
            "produce NaN global params"
        )
    weights = weights / total
    merged = jax.tree_util.tree_map(
        lambda *leaves: sum(w * leaf for w, leaf in zip(weights, leaves)),
        *[r[params_key] for r in replies],
    )
    return merged, weights

"""Host-level RPC loopback — the cross-silo transport seam.

Parity surface (SURVEY §2.14): the reference's server<->client wire is
Flower's gRPC stack; for genuinely-distributed (cross-silo) deployment the
TPU build retains a slim host RPC with the same fit/evaluate/get_properties
contract. This module is that seam in its minimal form: length-prefixed
frames (transport/codec.py) over TCP, one request/response per connection.
The in-process mesh remains the fast path; this is the boundary for peers
that do not share it.
"""

from __future__ import annotations

import socket
import struct
import threading
from typing import Callable

_LEN = struct.Struct("<Q")


def _recv_exact(conn: socket.socket, n: int) -> bytes:
    # bytearray accumulation: linear cost for multi-MB model frames (bytes
    # concatenation would re-copy the growing buffer every chunk).
    buf = bytearray(n)
    view = memoryview(buf)
    got = 0
    while got < n:
        received = conn.recv_into(view[got:], min(n - got, 1 << 20))
        if not received:
            raise ConnectionError("peer closed mid-frame")
        got += received
    return bytes(buf)


def send_frame(conn: socket.socket, frame: bytes) -> None:
    conn.sendall(_LEN.pack(len(frame)) + frame)


def recv_frame(conn: socket.socket) -> bytes:
    (n,) = _LEN.unpack(_recv_exact(conn, _LEN.size))
    return _recv_exact(conn, n)


class LoopbackServer:
    """One-thread request/response server: handler(frame_bytes) -> frame_bytes."""

    def __init__(self, handler: Callable[[bytes], bytes], host: str = "127.0.0.1",
                 port: int = 0):
        # port=0: OS-assigned (in-process silos); fixed port for real
        # cross-host deployment (e.g. the docker_basic_example containers).
        self.handler = handler
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.sock.listen(8)
        self.host, self.port = self.sock.getsockname()
        self._stop = threading.Event()
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _serve(self) -> None:
        self.sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except socket.timeout:
                continue
            with conn:
                try:
                    request = recv_frame(conn)
                    send_frame(conn, self.handler(request))
                except Exception:
                    # One bad peer/frame (corrupt bytes -> FrameError, handler
                    # bugs, disconnects) must not kill the serve loop; the
                    # connection closes, the server lives on.
                    import logging

                    logging.getLogger(__name__).exception(
                        "loopback request failed; connection dropped"
                    )

    def close(self) -> None:
        self._stop.set()
        self.thread.join(timeout=2)
        self.sock.close()


def call(host: str, port: int, frame: bytes, timeout: float = 10.0) -> bytes:
    with socket.create_connection((host, port), timeout=timeout) as conn:
        send_frame(conn, frame)
        return recv_frame(conn)

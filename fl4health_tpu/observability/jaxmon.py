"""JAX-specific observability hooks.

Three concerns the generic tracer/registry can't cover:

1. **Compile accounting** — ``jax.monitoring`` emits named events for every
   trace/lower/backend-compile (``/jax/core/compile/*_duration``) and for
   persistent-cache traffic (``/jax/compilation_cache/*``). ``CompileMonitor``
   forwards them into a ``MetricsRegistry`` so a run can answer "did round N
   recompile?" — the single most common TPU perf bug (shape drift silently
   re-paying a multi-second XLA compile every round).

   ``jax.monitoring`` has no per-listener unregister, so this module
   registers ONE forwarding listener pair lazily and fans out to whatever
   monitors are currently installed; ``uninstall()`` detaches a monitor
   without touching global JAX state.

2. **Honest device time** — an XLA dispatch returns before the device
   finishes; timing the Python call measures enqueue latency, not execute
   time. ``synced()`` fences with ``jax.block_until_ready`` *only when
   observability is enabled*, so the disabled path introduces zero extra
   device syncs on the round hot loop (the acceptance bar for this
   subsystem).

3. **Round profiling** — ``profile_round(dir)`` wraps one chosen round in
   ``jax.profiler.trace`` (TensorBoard/XProf-viewable device trace) without
   paying profiler overhead on every round the way a whole-``fit`` capture
   does.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Any

from fl4health_tpu.observability.registry import MetricsRegistry

# Map jax.monitoring event names -> registry counter names. Durations also
# accumulate a *_seconds_total counter so compile time (not just count) is
# visible per round.
_DURATION_EVENTS = {
    "/jax/core/compile/backend_compile_duration": "jax_backend_compiles",
    "/jax/core/compile/jaxpr_trace_duration": "jax_jaxpr_traces",
    "/jax/core/compile/jaxpr_to_mlir_module_duration": "jax_mlir_lowerings",
}
_COUNT_EVENTS = {
    "/jax/compilation_cache/cache_hits": "jax_persistent_cache_hits_total",
    "/jax/compilation_cache/cache_misses": "jax_persistent_cache_misses_total",
    "/jax/compilation_cache/compile_requests_use_cache":
        "jax_cache_compile_requests_total",
}

_monitors_lock = threading.Lock()
_monitors: list["CompileMonitor"] = []
_listeners_registered = False


def _fanout_event(event: str, **kwargs: Any) -> None:
    with _monitors_lock:
        targets = list(_monitors)
    for mon in targets:
        mon._on_event(event)


def _fanout_duration(event: str, duration: float, **kwargs: Any) -> None:
    with _monitors_lock:
        targets = list(_monitors)
    for mon in targets:
        mon._on_duration(event, duration)


def _ensure_listeners() -> None:
    global _listeners_registered
    with _monitors_lock:
        if _listeners_registered:
            return
        import jax.monitoring

        jax.monitoring.register_event_listener(_fanout_event)
        jax.monitoring.register_event_duration_secs_listener(_fanout_duration)
        _listeners_registered = True


class CompileMonitor:
    """Forwards jax.monitoring compile/cache events into a registry.

    Counters written (all monotonic):
    - ``jax_backend_compiles_total`` / ``jax_backend_compiles_seconds_total``
    - ``jax_jaxpr_traces_total`` / ``jax_jaxpr_traces_seconds_total``
    - ``jax_mlir_lowerings_total`` / ``jax_mlir_lowerings_seconds_total``
    - ``jax_persistent_cache_hits_total`` / ``..._misses_total``
    """

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self._installed = False

    def install(self) -> "CompileMonitor":
        _ensure_listeners()
        with _monitors_lock:
            if not self._installed:
                _monitors.append(self)
                self._installed = True
        return self

    def uninstall(self) -> None:
        with _monitors_lock:
            if self._installed:
                _monitors.remove(self)
                self._installed = False

    @property
    def installed(self) -> bool:
        return self._installed

    # fan-out targets ----------------------------------------------------
    def _on_event(self, event: str) -> None:
        name = _COUNT_EVENTS.get(event)
        if name is not None:
            self.registry.counter(name, help=f"jax.monitoring {event}").inc()

    def _on_duration(self, event: str, duration: float) -> None:
        base = _DURATION_EVENTS.get(event)
        if base is None:
            return
        self.registry.counter(
            f"{base}_total", help=f"count of jax.monitoring {event}"
        ).inc()
        self.registry.counter(
            f"{base}_seconds_total", help=f"seconds in jax.monitoring {event}"
        ).inc(max(0.0, float(duration)))

    def compile_count(self) -> float:
        return self.registry.counter("jax_backend_compiles_total").value

    def __enter__(self) -> "CompileMonitor":
        return self.install()

    def __exit__(self, *exc) -> bool:
        self.uninstall()
        return False


def synced(tree: Any, enabled: bool = True) -> tuple[Any, float]:
    """Fence ``tree`` with ``block_until_ready`` and return
    ``(tree, wait_seconds)``. With ``enabled=False`` this is a pure
    pass-through (``(tree, 0.0)``) — no sync, no clock read — so call sites
    can fence unconditionally and let the flag decide."""
    if not enabled:
        return tree, 0.0
    import jax

    t0 = time.perf_counter()
    jax.block_until_ready(tree)
    return tree, time.perf_counter() - t0


@contextlib.contextmanager
def profile_round(profile_dir: str | None):
    """Opt-in ``jax.profiler.trace`` capture of one block (one round).
    ``profile_dir=None`` is a no-op, so the call site stays unconditional."""
    if profile_dir is None:
        yield
        return
    import jax

    with jax.profiler.trace(profile_dir):
        yield

"""Postmortem bundles — self-contained evidence directories for abnormal ends.

When ``fit()`` dies — watchdog ``TrainingHealthError``, a failure policy's
``ClientFailuresError``, a cross-silo ``QuorumError``, a corrupt-checkpoint
restore, an unhandled exception, or a SIGTERM preemption —
:func:`dump_bundle` publishes everything a postmortem needs into ONE
atomically-renamed directory:

    postmortem_<ts>/
      ring.msgpack       the flight recorder's last-``window`` round records,
                         written through the checkpointing frame writer
                         (versioned header + msgpack blob + CRC32 footer —
                         corruption is DETECTED at read, like checkpoints)
      manifest.json      the run manifest (versions, chip, execution mode,
                         config hash) as served at /manifest
      trace.json         the span tracer's Chrome trace — properly
                         TERMINATED here, whatever state the live stream is in
      events.tail.jsonl  the JSONL event log still in memory (pre-rollover
                         history rides along as events.*.jsonl.gz when the
                         registry archives evicted segments)
      metrics.prom       a final Prometheus scrape of the registry
      fleet.json         the fleet ledger's lifetime snapshot (per-client
                         records + sketches), when a ledger was armed
      verdict.json       what killed the run: kind, round, clients (REGISTRY
                         ids under cohort-slot execution), check, message,
                         per-silo outcomes for quorum failures, and the
                         newest good checkpoint generation to resume from

``tools/postmortem.py`` renders a bundle into an incident report with no
access to the dead process; :func:`load_bundle` is the shared reader.

Atomicity: the directory is assembled under a ``.tmp`` sibling and
published with one ``os.rename`` — a crash mid-dump never leaves a
half-written ``postmortem_*`` directory for an operator to trust.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import shutil
import time
from typing import Any, Mapping

import numpy as np

from fl4health_tpu.core.io import atomic_write

BUNDLE_PREFIX = "postmortem_"
RING_FRAME = "ring.msgpack"
VERDICT_FILE = "verdict.json"
TRACE_FILE = "trace.json"
EVENTS_FILE = "events.tail.jsonl"
METRICS_FILE = "metrics.prom"
MANIFEST_FILE = "manifest.json"
FLEET_FILE = "fleet.json"


def _jsonable(obj: Any) -> Any:
    """Best-effort JSON coercion for verdict/header facts (numpy scalars,
    arrays, exceptions)."""
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if isinstance(obj, (np.integer,)):
        return int(obj)
    if isinstance(obj, (np.floating,)):
        return float(obj)
    if isinstance(obj, dict):
        return {str(k): _jsonable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_jsonable(v) for v in obj]
    if isinstance(obj, (str, int, float, bool)) or obj is None:
        return obj
    return str(obj)


def _registry_ids_for_round(recorder, round_idx: int):
    for entry in reversed(recorder.entries):
        if entry["round"] == int(round_idx):
            ids = entry.get("registry_ids")
            if ids is not None:
                return np.asarray(ids)
            return None
    return None


def verdict_from_exception(exc: BaseException, recorder=None) -> dict:
    """Classify an abnormal end into the ``verdict.json`` document.

    Typed failures keep their structure (round, clients, check, quorum
    silo outcomes, corrupt file); everything else lands as
    ``kind="exception"``. When the recorder maps the verdict round's slots
    to registry ids (cohort-slot execution), ``clients`` is translated to
    REGISTRY ids (``slot_clients`` keeps the raw positions)."""
    verdict: dict[str, Any] = {
        "exception": type(exc).__name__,
        "message": str(exc),
        "ts": time.time(),
    }
    # late imports: bundle must stay importable without the server package
    try:
        from fl4health_tpu.observability.health import TrainingHealthError
    except Exception:  # pragma: no cover - circular-import safety
        TrainingHealthError = ()  # type: ignore[assignment]
    from fl4health_tpu.observability.flightrec import SigtermShutdown

    if isinstance(exc, SigtermShutdown):
        verdict["kind"] = "sigterm"
        verdict["signal"] = "SIGTERM"
        # SystemExit's str() is its exit code — say what actually happened
        verdict["message"] = "SIGTERM received during fit()"
        if recorder is not None and recorder.last_round() is not None:
            verdict["round"] = recorder.last_round()
    elif TrainingHealthError and isinstance(exc, TrainingHealthError):
        verdict["kind"] = "training_health"
        verdict["round"] = exc.round
        verdict["clients"] = list(exc.clients)
        verdict["check"] = exc.check
    elif type(exc).__name__ == "ClientFailuresError":
        verdict["kind"] = "client_failures"
        if getattr(exc, "round", None) is not None:
            verdict["round"] = int(exc.round)
        elif recorder is not None and recorder.last_round() is not None:
            verdict["round"] = recorder.last_round()
        reg_clients = getattr(exc, "registry_clients", None)
        clients = getattr(exc, "clients", None)
        if reg_clients is not None:
            # cohort rounds: the epilogue already mapped slots -> ids
            verdict["clients"] = list(reg_clients)
            verdict["slot_clients"] = list(clients or [])
        elif clients:
            verdict["clients"] = list(clients)
    elif type(exc).__name__ == "QuorumError":
        verdict["kind"] = "quorum"
        verdict["required"] = getattr(exc, "required", None)
        verdict["succeeded"] = getattr(exc, "succeeded", None)
        verdict["failures"] = [
            list(f) for f in getattr(exc, "failures", [])
        ]
        report = getattr(exc, "report", None)
        if report is not None:
            # per-silo outcomes of the failed broadcast — who replied, who
            # timed out, after how many attempts (transport/coordinator.py)
            verdict["silos"] = [
                {
                    "silo": r.silo, "ok": r.ok, "reason": r.reason,
                    "attempts": r.attempts,
                    "elapsed_s": round(float(r.elapsed_s), 6),
                }
                for r in report.results
            ]
    elif type(exc).__name__ == "CheckpointCorruptError":
        verdict["kind"] = "checkpoint_corrupt"
        verdict["path"] = getattr(exc, "path", None)
        verdict["reason"] = getattr(exc, "reason", None)
    else:
        verdict["kind"] = "exception"
        if recorder is not None and recorder.last_round() is not None:
            verdict["round"] = recorder.last_round()
    if recorder is not None:
        ck = recorder.checkpoint
        if ck:
            # "what to resume from": the newest durable generation the dead
            # run published (the retention ring's newest-good fallback
            # covers it being damaged later)
            verdict["resume"] = {
                k: ck.get(k)
                for k in ("path", "generation", "round", "kind", "bytes")
                if k in ck
            }
        if (verdict.get("clients") and "slot_clients" not in verdict):
            # cohort rounds recorded registry ids for the verdict round:
            # translate slot positions into the ids operators know
            ids = _registry_ids_for_round(recorder, verdict.get("round", -1))
            if ids is not None:
                verdict["slot_clients"] = list(verdict["clients"])
                verdict["clients"] = [
                    int(ids[c]) for c in verdict["slot_clients"]
                    if 0 <= int(c) < len(ids)
                ]
    return _jsonable(verdict)


def dump_bundle(out_dir: str, verdict: Mapping[str, Any], *,
                recorder=None, tracer=None, registry=None,
                manifest: Mapping[str, Any] | None = None,
                fleet: Mapping[str, Any] | None = None,
                timestamp: float | None = None) -> str:
    """Assemble and atomically publish one ``postmortem_<ts>/`` directory
    under ``out_dir``; returns its path. Never raises into the caller's
    (already failing) control flow beyond filesystem errors — callers wrap
    it (``FederatedSimulation._dump_postmortem`` logs and continues)."""
    ts = time.strftime("%Y%m%d_%H%M%S",
                       time.localtime(timestamp or time.time()))
    final = os.path.join(out_dir, f"{BUNDLE_PREFIX}{ts}")
    n = 0
    while os.path.exists(final):  # two abnormal ends in one second
        n += 1
        final = os.path.join(out_dir, f"{BUNDLE_PREFIX}{ts}_{n}")
    tmp = f"{final}.tmp.{os.getpid()}"
    os.makedirs(tmp, exist_ok=True)
    try:
        with atomic_write(os.path.join(tmp, VERDICT_FILE)) as f:
            json.dump(_jsonable(dict(verdict)), f, indent=2, default=str)
        if recorder is not None:
            # frame-writer reuse (checkpointing/state.py): versioned header
            # + msgpack blob + CRC32 footer, read back by load_bundle
            from fl4health_tpu.checkpointing.state import write_frame

            write_frame(
                os.path.join(tmp, RING_FRAME),
                {"rounds": {str(i): e for i, e
                            in enumerate(recorder.entries)}},
                host_header={
                    "window": recorder.window,
                    "rounds": recorder.rounds,
                    "checkpoint": _jsonable(recorder.checkpoint),
                    "run": _jsonable(recorder.run_facts),
                },
                meta={"kind": "flightrec"},
            )
        if manifest:
            with atomic_write(os.path.join(tmp, MANIFEST_FILE)) as f:
                json.dump(_jsonable(dict(manifest)), f, indent=2,
                          default=str)
        if fleet:
            # the fleet ledger's lifetime snapshot (observability/fleet.py)
            # — repeat-offender evidence for the suspect ranking, beyond
            # the ring's 16-round window
            with atomic_write(os.path.join(tmp, FLEET_FILE)) as f:
                json.dump(_jsonable(dict(fleet)), f, default=str)
        if tracer is not None:
            # a COMPLETE Chrome trace envelope, whatever state the live
            # stream file is in — the bundle's copy always json.load()s
            with atomic_write(os.path.join(tmp, TRACE_FILE)) as f:
                json.dump(tracer.to_chrome_trace(), f)
        if registry is not None:
            with atomic_write(os.path.join(tmp, EVENTS_FILE)) as f:
                for rec in registry.events:
                    f.write(json.dumps(rec, default=str) + "\n")
            with atomic_write(os.path.join(tmp, METRICS_FILE)) as f:
                f.write(registry.to_prometheus())
            for seg in getattr(registry, "archive_paths", lambda: [])():
                # pre-rollover history the archive rollover preserved
                shutil.copy2(seg, os.path.join(tmp, os.path.basename(seg)))
        os.rename(tmp, final)  # single atomic publish
    except BaseException:
        shutil.rmtree(tmp, ignore_errors=True)
        raise
    return final


def _unflax(obj: Any) -> Any:
    """Undo flax serialization's list->{"0": ..} dict convention so the
    restored ring reads like the recorder's entries."""
    if isinstance(obj, dict):
        out = {k: _unflax(v) for k, v in obj.items()}
        keys = list(out.keys())
        if keys and all(isinstance(k, str) and k.isdigit() for k in keys):
            idx = sorted(int(k) for k in keys)
            if idx == list(range(len(idx))):
                return [out[str(i)] for i in idx]
        return out
    return obj


def list_bundles(out_dir: str) -> list[str]:
    """Published bundle directories under ``out_dir``, oldest first."""
    return sorted(
        p for p in glob.glob(os.path.join(out_dir, f"{BUNDLE_PREFIX}*"))
        if os.path.isdir(p) and ".tmp." not in os.path.basename(p)
    )


def load_bundle(path: str) -> dict:
    """Read one bundle directory -> ``{verdict, ring, ring_header,
    manifest, events, trace, metrics_prom, archives}``. CRC-verifies the
    ring frame (raises ``CheckpointCorruptError`` on damage); absent
    artifacts load as None/empty. Standalone: needs nothing from the
    process that wrote the bundle."""
    out: dict[str, Any] = {"path": path}
    vpath = os.path.join(path, VERDICT_FILE)
    with open(vpath) as f:
        out["verdict"] = json.load(f)
    ring_path = os.path.join(path, RING_FRAME)
    out["ring"], out["ring_header"] = [], {}
    if os.path.exists(ring_path):
        from flax import serialization

        from fl4health_tpu.checkpointing.state import read_frame

        header, meta, blob = read_frame(ring_path)
        out["ring_header"] = header
        out["ring_meta"] = meta
        rounds = _unflax(serialization.msgpack_restore(blob)).get("rounds")
        if isinstance(rounds, dict):  # zero/one-entry rings stay dicts
            rounds = [rounds[k] for k in sorted(rounds, key=int)]
        out["ring"] = rounds or []
    mpath = os.path.join(path, MANIFEST_FILE)
    out["manifest"] = None
    if os.path.exists(mpath):
        with open(mpath) as f:
            out["manifest"] = json.load(f)
    fpath = os.path.join(path, FLEET_FILE)
    out["fleet"] = None
    if os.path.exists(fpath):
        with open(fpath) as f:
            out["fleet"] = json.load(f)
    out["events"] = []
    epath = os.path.join(path, EVENTS_FILE)
    archives = sorted(glob.glob(os.path.join(path, "*.jsonl.gz")))
    out["archives"] = archives
    for seg in archives:  # archived (pre-rollover) events first: oldest
        with gzip.open(seg, "rt") as f:
            for line in f:
                line = line.strip()
                if line:
                    out["events"].append(json.loads(line))
    if os.path.exists(epath):
        with open(epath) as f:
            for line in f:
                line = line.strip()
                if line:
                    out["events"].append(json.loads(line))
    tpath = os.path.join(path, TRACE_FILE)
    out["trace"] = None
    if os.path.exists(tpath):
        from fl4health_tpu.observability.spans import load_trace

        out["trace"] = load_trace(tpath)
    ppath = os.path.join(path, METRICS_FILE)
    out["metrics_prom"] = None
    if os.path.exists(ppath):
        with open(ppath) as f:
            out["metrics_prom"] = f.read()
    return out

"""Bounded round time-series — the serving KPIs behind the operations plane.

Role: ROADMAP item 3 frames production federation as a *service* with
service-level indicators — sustained rounds/hour, wire bytes per client,
straggler tail, recovery MTTR — not a ``fit()`` call an operator watches.
This module turns the per-round summaries the RoundConsumer / chunked
epilogues already computed (``_record_round_metrics`` — host floats, zero
extra device syncs) into those KPIs.

Memory discipline: a ``deque(maxlen=window)`` of small dicts plus KLL
quantile sketches (``sketches.QuantileSketch``, PR 16) for the lifetime
round-duration distribution — O(window + k log n) total, invariant in both
registry size and run length. No JAX imports; every input is a host float
the epilogue already held.

Threading: ``observe_round`` runs on whichever thread owns the epilogue
(consumer thread on pipelined runs, main thread on chunked/cohort/async);
``note_recovery`` arrives via ``Observability.log_event`` from the
supervisor, and ``kpis()`` is read by the HTTP handler thread serving
``GET /admin/slo``. One lock covers all three.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Any, Callable, Mapping

from fl4health_tpu.observability.sketches import QuantileSketch

__all__ = ["RoundTimeSeries"]


class RoundTimeSeries:
    """Sliding-window KPIs over the per-round summaries the epilogue emits.

    ``observe_round(summary, ...)`` ingests one round summary (the dict
    ``_record_round_metrics`` logs as a ``round`` event) and returns the
    current KPI dict; ``note_recovery(phase)`` folds the supervisor's
    ``recovery`` events into an MTTR estimate (engage → probation_passed
    wall-clock, the time the run spent limping before the ladder repaired
    it). ``clock`` is injectable so tests pin wall-time KPIs exactly.
    """

    def __init__(self, window: int = 256,
                 clock: Callable[[], float] = time.time):
        if window < 2:
            raise ValueError(f"RoundTimeSeries window must be >= 2; got {window}")
        self.window = int(window)
        self._clock = clock
        self._lock = threading.Lock()
        self._points: deque[dict[str, Any]] = deque(maxlen=self.window)
        self._round_s = QuantileSketch()  # lifetime round-duration sketch
        self._mttr_s: deque[float] = deque(maxlen=self.window)
        self._incident_t0: float | None = None  # first engage of open incident
        self.rounds_seen = 0
        self.recoveries = 0
        self.halts = 0

    # ------------------------------------------------------------------ feed
    def observe_round(self, summary: Mapping[str, Any], *,
                      fit_loss: float | None = None,
                      eval_loss: float | None = None,
                      ts: float | None = None) -> dict[str, Any]:
        """Ingest one epilogue summary; returns the refreshed KPI dict."""
        now = float(ts if ts is not None else self._clock())
        wall = float(summary.get("fit_s") or 0.0) + float(summary.get("eval_s") or 0.0)
        participants = summary.get("participants")
        # prefer post-compression wire bytes when the wire path recorded them
        gather = summary.get("gather_bytes_wire", summary.get("gather_bytes"))
        wire = None
        if gather is not None or summary.get("broadcast_bytes") is not None:
            wire = float(gather or 0.0) + float(summary.get("broadcast_bytes") or 0.0)
        fleet = summary.get("fleet") or {}
        point = {
            "round": summary.get("round"),
            "ts": now,
            "wall_s": wall,
            "participants": participants,
            "wire_bytes": wire,
            "straggler_p99": fleet.get("straggler_p99"),
            "fit_loss": None if fit_loss is None else float(fit_loss),
            "eval_loss": None if eval_loss is None else float(eval_loss),
        }
        with self._lock:
            self._points.append(point)
            if wall > 0.0:
                self._round_s.add(wall)
            self.rounds_seen += 1
            return self._kpis_locked()

    def note_recovery(self, phase: Any, *, ts: float | None = None) -> None:
        """Fold one supervisor ``recovery`` event into the MTTR estimate.

        An incident opens at its FIRST ``engage`` (re-engages while open
        are the same outage escalating rungs, not a new one) and closes at
        ``probation_passed``; ``halt`` closes it unrepaired.
        """
        now = float(ts if ts is not None else self._clock())
        with self._lock:
            if phase == "engage":
                if self._incident_t0 is None:
                    self._incident_t0 = now
            elif phase == "probation_passed":
                if self._incident_t0 is not None:
                    self._mttr_s.append(max(0.0, now - self._incident_t0))
                    self._incident_t0 = None
                    self.recoveries += 1
            elif phase == "halt":
                self._incident_t0 = None
                self.halts += 1

    # ------------------------------------------------------------------ read
    def kpis(self) -> dict[str, Any]:
        """Current serving KPIs. Keys with insufficient signal are None."""
        with self._lock:
            return self._kpis_locked()

    def _kpis_locked(self) -> dict[str, Any]:
        pts = list(self._points)
        out: dict[str, Any] = {
            "window": self.window,
            "rounds_seen": self.rounds_seen,
            "rounds_per_hour": None,
            "round_s_p50": self._round_s.quantile(0.5),
            "round_s_p99": self._round_s.quantile(0.99),
            "bytes_per_client": None,
            "straggler_p99": None,
            "straggler_p99_trend": None,
            "eval_loss": None,
            "fit_loss": None,
            "mttr_s": None,
            "mttr_open_s": None,
            "recoveries": self.recoveries,
            "halts": self.halts,
        }
        if len(pts) >= 2:
            dt = pts[-1]["ts"] - pts[0]["ts"]
            if dt > 0.0:
                out["rounds_per_hour"] = (len(pts) - 1) / dt * 3600.0
        if pts:
            last = pts[-1]
            out["eval_loss"] = last["eval_loss"]
            out["fit_loss"] = last["fit_loss"]
            if last["wire_bytes"] is not None and last["participants"]:
                out["bytes_per_client"] = last["wire_bytes"] / float(last["participants"])
            tails = [p["straggler_p99"] for p in pts if p["straggler_p99"] is not None]
            if tails:
                out["straggler_p99"] = tails[-1]
                if len(tails) >= 2:
                    out["straggler_p99_trend"] = tails[-1] - tails[0]
        if self._mttr_s:
            out["mttr_s"] = sum(self._mttr_s) / len(self._mttr_s)
        if self._incident_t0 is not None:
            out["mttr_open_s"] = max(0.0, self._clock() - self._incident_t0)
        return out

    @property
    def nbytes(self) -> int:
        """Rough footprint — pinned O(window) regardless of registry size."""
        with self._lock:
            per_point = 8 * 16  # ~8 slots of float/ref per point
            return (len(self._points) * per_point
                    + len(self._mttr_s) * 8
                    + self._round_s.nbytes() + 128)

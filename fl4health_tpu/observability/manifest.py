"""Run manifest — the provenance record served next to the metrics.

A scraped ``/metrics`` page is only interpretable with its context: which
jax/jaxlib, which backend and chip, how many devices, which execution mode
``fit()`` chose (and why), whether buffer donation was gated off, and a
stable hash of the run configuration so two scrapes can be matched to one
experiment. ``bench.py`` embeds similar provenance in its artifacts; this
module is the one implementation both the live scrape endpoint
(``observability/exposition.py``) and artifact writers share.

Everything here is a plain-JSON dict of host facts — no device work, no
per-round cost. ``config_hash`` is order-insensitive (canonical JSON), so
logically-equal configs hash equal across processes.
"""

from __future__ import annotations

import hashlib
import json
import platform
from typing import Any, Mapping


def config_hash(config: Mapping[str, Any]) -> str:
    """Short stable digest of a JSON-able config mapping (sorted keys,
    non-JSON leaves stringified) — an experiment identity, not a secret."""
    canonical = json.dumps(config, sort_keys=True, default=str,
                           separators=(",", ":"))
    return hashlib.sha256(canonical.encode("utf-8")).hexdigest()[:16]


def device_facts() -> dict[str, Any]:
    """Backend/device identity from the live (already-initialized) jax
    runtime — ``utils/tpu_probe.live_device_summary`` (the one home of the
    "which chip, what peak" policy; its subprocess probes cover the
    pre-init case) plus the process-level facts only the manifest needs."""
    import jax

    from fl4health_tpu.utils.tpu_probe import live_device_summary

    return {
        "backend": jax.default_backend(),
        "process_count": jax.process_count(),
        **live_device_summary(),
    }


def run_manifest(
    *,
    execution_mode: str | None = None,
    execution_mode_reason: str | None = None,
    donation: bool | None = None,
    mesh: Any = None,
    config: Mapping[str, Any] | None = None,
    extra: Mapping[str, Any] | None = None,
) -> dict[str, Any]:
    """Assemble the run manifest dict.

    ``donation``: whether the round programs donate their state buffers
    (False on CPU — see ``simulation._donate_argnums``). ``mesh``: a
    ``jax.sharding.Mesh`` (described via ``parallel.mesh.mesh_descriptor``)
    or an already-built descriptor dict. ``config``: JSON-able run config;
    stored hashed (``config_hash``) plus inline for human readers.
    """
    import jax
    import jaxlib

    mani: dict[str, Any] = {
        "jax_version": jax.__version__,
        "jaxlib_version": jaxlib.__version__,
        "python_version": platform.python_version(),
        **device_facts(),
    }
    if execution_mode is not None:
        mani["execution_mode"] = execution_mode
    if execution_mode_reason is not None:
        mani["execution_mode_reason"] = execution_mode_reason
    if donation is not None:
        mani["donation"] = bool(donation)
    if mesh is not None:
        if isinstance(mesh, Mapping):
            mani["mesh"] = dict(mesh)
        else:
            from fl4health_tpu.parallel.mesh import mesh_descriptor

            mani["mesh"] = mesh_descriptor(mesh)
    if config is not None:
        mani["config"] = dict(config)
        mani["config_hash"] = config_hash(config)
    if extra:
        mani.update(extra)
    return mani

"""Live scrape endpoint — pull-based exposition of the metrics registry.

Until now the Prometheus text rendering only landed on disk at
``Observability.export()`` (end of ``fit()``), so a multi-hour run was a
black box while it mattered most. This module serves the SAME registry
over a stdlib-only HTTP endpoint so a live ``fit()`` can be scraped
mid-run by an actual Prometheus (or ``curl``):

- ``GET /metrics``  — ``MetricsRegistry.to_prometheus()``, text
  exposition format 0.0.4 (the conformance rules ``registry.py`` already
  enforces: ``_total`` suffixes, one HELP/TYPE per family, escaping);
- ``GET /manifest`` — the run manifest JSON
  (``observability/manifest.py``): versions, backend, device kind/count,
  execution mode + reason, donation gating, config hash;
- ``GET /healthz``  — liveness probe. Goes **503** once the run is marked
  unhealthy (a watchdog halt or a postmortem bundle dump —
  ``Observability.mark_unhealthy``), with the verdict summary as the
  body, so an orchestrator's health check stops reporting a run healthy
  mid-``TrainingHealthError`` teardown;
- ``GET /fleet``    — fleet-ledger summary JSON
  (``observability/fleet.py``): clients seen, participation skew (gini),
  loss/staleness/participation-gap distributions from the streaming
  sketches, quarantine standing, top-k stragglers and suspects;
- ``GET /clients/<id>`` — one client's lifetime record by REGISTRY id
  (participation count, last-seen round, EMAs, quarantine strikes, wire
  bytes), 404 for a client the ledger has never seen.

Zero third-party deps (zero-egress box) and zero cost on the round hot
path: a scrape reads host-side floats under the registry lock — it never
touches the device, so it cannot add a sync or perturb the trajectory.

Wired by ``Observability(http_port=...)``; ``port=0`` binds an
OS-assigned port (tests), a fixed port for real deployments. The server
runs on daemon threads and is torn down by ``Observability.shutdown()``.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

from fl4health_tpu.observability.registry import MetricsRegistry

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


class ScrapeServer:
    """Threaded HTTP server over one registry + manifest provider.

    ``manifest_provider`` is called per ``/manifest`` request so the
    served document tracks live updates (e.g. the execution mode chosen
    by the current ``fit()``), not a bind-time snapshot.
    ``health_provider`` is called per ``/healthz`` request and returns
    None while healthy, or a verdict-summary string once the run halted —
    the endpoint then answers 503 with that summary as the body.
    ``fleet_provider``/``client_provider`` back ``/fleet`` and
    ``/clients/<id>``; without them those routes answer 404 like any
    unknown path (a server without a ledger has no fleet to serve).
    """

    def __init__(
        self,
        registry: MetricsRegistry,
        manifest_provider: Callable[[], dict[str, Any]] | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        health_provider: Callable[[], str | None] | None = None,
        fleet_provider: Callable[[], dict[str, Any]] | None = None,
        client_provider: "Callable[[int], dict[str, Any] | None] | None" = None,
    ):
        registry_ref = registry
        provider = manifest_provider
        health = health_provider
        fleet = fleet_provider
        client_lookup = client_provider

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802 (http.server API)
                path = self.path.split("?", 1)[0]
                if path in ("/metrics", "/"):
                    body = registry_ref.to_prometheus().encode("utf-8")
                    self._send(200, body, PROM_CONTENT_TYPE)
                elif path == "/manifest":
                    mani = provider() if provider is not None else {}
                    self._send(200, json.dumps(mani, default=str).encode(),
                               "application/json")
                elif path == "/healthz":
                    verdict = health() if health is not None else None
                    if verdict is None:
                        self._send(200, b"ok\n", "text/plain; charset=utf-8")
                    else:
                        body = f"unhealthy: {verdict}\n".encode("utf-8")
                        self._send(503, body, "text/plain; charset=utf-8")
                elif path == "/fleet" and fleet is not None:
                    self._send(
                        200,
                        json.dumps(fleet(), default=str).encode(),
                        "application/json",
                    )
                elif (path.startswith("/clients/")
                      and client_lookup is not None):
                    raw = path[len("/clients/"):]
                    try:
                        cid = int(raw)
                    except ValueError:
                        self._send(400, b"client id must be an integer\n",
                                   "text/plain; charset=utf-8")
                        return
                    doc = client_lookup(cid)
                    if doc is None:
                        self._send(404, b"unknown client\n",
                                   "text/plain; charset=utf-8")
                    else:
                        self._send(200,
                                   json.dumps(doc, default=str).encode(),
                                   "application/json")
                else:
                    self._send(404, b"not found\n",
                               "text/plain; charset=utf-8")

            def log_message(self, *args):  # no stderr spam per scrape
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fl4h-scrape", daemon=True
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)

"""Live scrape endpoint — pull-based exposition of the metrics registry.

Until now the Prometheus text rendering only landed on disk at
``Observability.export()`` (end of ``fit()``), so a multi-hour run was a
black box while it mattered most. This module serves the SAME registry
over a stdlib-only HTTP endpoint so a live ``fit()`` can be scraped
mid-run by an actual Prometheus (or ``curl``):

- ``GET /metrics``  — ``MetricsRegistry.to_prometheus()``, text
  exposition format 0.0.4 (the conformance rules ``registry.py`` already
  enforces: ``_total`` suffixes, one HELP/TYPE per family, escaping);
- ``GET /manifest`` — the run manifest JSON
  (``observability/manifest.py``): versions, backend, device kind/count,
  execution mode + reason, donation gating, config hash;
- ``GET /healthz``  — liveness probe with THREE answers: 200 ``ok``, 200
  ``degraded: <slo>`` while an SLO objective stands in breach
  (``observability/slo.py`` — the run is limping, not dead), and **503**
  once the run is marked unhealthy (a watchdog halt or a postmortem
  bundle dump — ``Observability.mark_unhealthy``) so an orchestrator's
  health check can distinguish all three;
- ``GET /fleet``    — fleet-ledger summary JSON (``observability/fleet.py``);
- ``GET /clients/<id>`` — one client's lifetime record by REGISTRY id,
  404 for a client the ledger has never seen;
- ``GET /admin/slo`` — current SLO standing (policy, per-objective burn
  rates, KPIs) when an SLO engine is armed;
- ``POST /admin/scalars`` — the admin plane (``observability/
  adminplane.py``): live retunes of PR 11 hoisted scalars. OFF by
  default; armed only by ``Observability(admin_token=...)`` and guarded
  by that shared secret in the ``X-Admin-Token`` header. The handler
  thread only validates + enqueues; the round loop applies at the next
  boundary.

Protocol hygiene (scrapers are not polite): every GET route answers
``HEAD`` too; unsupported methods on known routes answer 405 with an
``Allow`` header (not the stdlib 501 path); disconnecting scrapers
(``BrokenPipeError``/``ConnectionResetError``) are swallowed so a flaky
Prometheus cannot spam stderr.

Zero third-party deps (zero-egress box) and zero cost on the round hot
path: a scrape reads host-side floats under the registry lock — it never
touches the device, so it cannot add a sync or perturb the trajectory.

Wired by ``Observability(http_port=...)``; ``port=0`` binds an
OS-assigned port (tests), a fixed port for real deployments. The server
runs on daemon threads and is torn down by ``Observability.shutdown()``.
"""

from __future__ import annotations

import json
import sys
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable

from fl4health_tpu.observability.registry import MetricsRegistry

PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

_DISCONNECTS = (BrokenPipeError, ConnectionResetError)


class _QuietThreadingHTTPServer(ThreadingHTTPServer):
    """Swallows client-disconnect errors instead of printing tracebacks."""

    daemon_threads = True

    def handle_error(self, request, client_address):  # noqa: D102
        exc = sys.exc_info()[1]
        if isinstance(exc, _DISCONNECTS):
            return
        super().handle_error(request, client_address)


class ScrapeServer:
    """Threaded HTTP server over one registry + manifest provider.

    ``manifest_provider`` is called per ``/manifest`` request so the
    served document tracks live updates (e.g. the execution mode chosen
    by the current ``fit()``), not a bind-time snapshot.
    ``health_provider`` is called per ``/healthz`` request and returns
    None while healthy, or a verdict-summary string once the run halted —
    the endpoint then answers 503 with that summary as the body.
    ``degraded_provider`` returns the name of a breaching SLO (or None);
    it only matters while ``health_provider`` says alive — dead beats
    limping. ``fleet_provider``/``client_provider`` back ``/fleet`` and
    ``/clients/<id>``; without them those routes answer 404 like any
    unknown path (a server without a ledger has no fleet to serve).
    ``slo_provider`` backs ``GET /admin/slo``; ``admin_plane`` (an
    ``adminplane.AdminPlane``) backs ``POST /admin/scalars`` — both 404
    when unarmed, so the default surface is exactly the read-only one.
    """

    def __init__(
        self,
        registry: MetricsRegistry,
        manifest_provider: Callable[[], dict[str, Any]] | None = None,
        host: str = "127.0.0.1",
        port: int = 0,
        health_provider: Callable[[], str | None] | None = None,
        fleet_provider: Callable[[], dict[str, Any]] | None = None,
        client_provider: "Callable[[int], dict[str, Any] | None] | None" = None,
        degraded_provider: Callable[[], str | None] | None = None,
        slo_provider: Callable[[], dict[str, Any]] | None = None,
        admin_plane=None,
    ):
        registry_ref = registry
        provider = manifest_provider
        health = health_provider
        degraded = degraded_provider
        fleet = fleet_provider
        client_lookup = client_provider
        slo = slo_provider
        admin = admin_plane

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, body: bytes, ctype: str,
                      include_body: bool = True,
                      extra_headers: dict[str, str] | None = None) -> None:
                try:
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    for k, v in (extra_headers or {}).items():
                        self.send_header(k, v)
                    self.end_headers()
                    if include_body:
                        self.wfile.write(body)
                except _DISCONNECTS:
                    pass  # scraper hung up mid-response; nothing to salvage

            def _send_json(self, code: int, doc: Any,
                           include_body: bool = True) -> None:
                self._send(code, json.dumps(doc, default=str).encode(),
                           "application/json", include_body)

            # -------------------------------------------------- GET routing
            def _get_response(self, path: str):
                """(code, body, ctype) for a GET-able path, else None."""
                if path in ("/metrics", "/"):
                    body = registry_ref.to_prometheus().encode("utf-8")
                    return 200, body, PROM_CONTENT_TYPE
                if path == "/manifest":
                    mani = provider() if provider is not None else {}
                    return (200, json.dumps(mani, default=str).encode(),
                            "application/json")
                if path == "/healthz":
                    verdict = health() if health is not None else None
                    if verdict is not None:
                        return (503, f"unhealthy: {verdict}\n".encode(),
                                "text/plain; charset=utf-8")
                    limping = degraded() if degraded is not None else None
                    if limping is not None:
                        return (200, f"degraded: {limping}\n".encode(),
                                "text/plain; charset=utf-8")
                    return 200, b"ok\n", "text/plain; charset=utf-8"
                if path == "/fleet" and fleet is not None:
                    return (200, json.dumps(fleet(), default=str).encode(),
                            "application/json")
                if path == "/admin/slo" and slo is not None:
                    return (200, json.dumps(slo(), default=str).encode(),
                            "application/json")
                if path.startswith("/clients/") and client_lookup is not None:
                    raw = path[len("/clients/"):]
                    try:
                        cid = int(raw)
                    except ValueError:
                        return (400, b"client id must be an integer\n",
                                "text/plain; charset=utf-8")
                    doc = client_lookup(cid)
                    if doc is None:
                        return (404, b"unknown client\n",
                                "text/plain; charset=utf-8")
                    return (200, json.dumps(doc, default=str).encode(),
                            "application/json")
                return None

            def _is_known(self, path: str) -> bool:
                return (self._get_response(path) is not None
                        or (path == "/admin/scalars" and admin is not None))

            def do_GET(self):  # noqa: N802 (http.server API)
                self._answer_read(include_body=True)

            def do_HEAD(self):  # noqa: N802
                self._answer_read(include_body=False)

            def _answer_read(self, include_body: bool) -> None:
                path = self.path.split("?", 1)[0]
                resp = self._get_response(path)
                if resp is not None:
                    code, body, ctype = resp
                    self._send(code, body, ctype, include_body)
                elif path == "/admin/scalars" and admin is not None:
                    self._send(405, b"method not allowed\n",
                               "text/plain; charset=utf-8", include_body,
                               {"Allow": "POST"})
                else:
                    self._send(404, b"not found\n",
                               "text/plain; charset=utf-8", include_body)

            # ------------------------------------------------------- admin
            def do_POST(self):  # noqa: N802
                path = self.path.split("?", 1)[0]
                if path != "/admin/scalars" or admin is None:
                    if self._is_known(path):
                        self._send(405, b"method not allowed\n",
                                   "text/plain; charset=utf-8",
                                   extra_headers={"Allow": "GET, HEAD"})
                    else:
                        self._send(404, b"not found\n",
                                   "text/plain; charset=utf-8")
                    return
                from fl4health_tpu.observability.adminplane import (
                    AdminRejection,
                )
                try:
                    admin.authorize(self.headers.get(admin.AUTH_HEADER))
                    length = int(self.headers.get("Content-Length") or 0)
                    raw = self.rfile.read(length) if length > 0 else b""
                    try:
                        scalars = json.loads(raw.decode("utf-8") or "null")
                    except (ValueError, UnicodeDecodeError):
                        raise AdminRejection(
                            400, "bad_request",
                            "body must be valid JSON") from None
                    self._send_json(200, admin.submit(scalars))
                except AdminRejection as rej:
                    self._send_json(rej.status, rej.doc())

            # ------------------------------------------- other verbs -> 405
            def _reject_method(self):
                path = self.path.split("?", 1)[0]
                if self._is_known(path):
                    allow = ("POST" if path == "/admin/scalars"
                             else "GET, HEAD")
                    self._send(405, b"method not allowed\n",
                               "text/plain; charset=utf-8",
                               extra_headers={"Allow": allow})
                else:
                    self._send(404, b"not found\n",
                               "text/plain; charset=utf-8")

            do_PUT = _reject_method    # noqa: N815
            do_DELETE = _reject_method  # noqa: N815
            do_PATCH = _reject_method  # noqa: N815

            def log_message(self, *args):  # no stderr spam per scrape
                pass

        self._httpd = _QuietThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, name="fl4h-scrape", daemon=True
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=2)

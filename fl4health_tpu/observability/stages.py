"""Stage naming for the aggregation spine — ``fl_stage::<name>`` scopes.

ROADMAP item 5 gates every fused-kernel investment on profiles showing
*which* stage of the clip -> quantize -> top-k -> robust-aggregate ->
server-update spine XLA leaves on the table. Whole-program
``cost_analysis()`` (observability/introspect.py) cannot answer that; this
module gives each spine stage a name that survives into the compiled
program, so ``observability/hloscan.py`` can attribute per-op flops/bytes
back to it and ``tools/roofline_report.py`` can rank stages by fusion
headroom.

Mechanism: :func:`stage` wraps a code region in ``jax.named_scope`` with
the ``fl_stage::`` prefix. Named scopes are **metadata only** — they land
in each HLO op's ``op_name`` path and in XProf trace op names, and change
neither the math nor XLA's optimization decisions, so attribution-on
trajectories stay bit-identical to attribution-off on every execution mode
(pinned by tests/observability/test_stage_attribution.py). Autodiff and
``vmap``/``scan`` transforms preserve the name stack, so a stage's
backward-pass ops attribute to the same stage as its forward ops.

The canonical spine stages (:data:`SPINE_STAGES`):

- ``local_train``   — the engine's train-step scan (clients/engine.py)
- ``dp_clip``       — fused per-example clip+reduce (kernels/dp_clip.py)
- ``rotation``      — randomized-Hadamard encode/decode (compression/codecs.py)
- ``topk``          — global magnitude top-k selection (compression/codecs.py)
- ``quantize``      — stochastic uniform quantization (compression/codecs.py)
- ``robust_aggregate`` — Byzantine-robust combinators (resilience/aggregators.py)
- ``server_update`` — the strategy's aggregate/server step, broken out
  explicitly since it is what cross-replica weight-update sharding
  optimizes (Xu et al., arXiv:2004.13336)
- ``cohort_exchange`` — the in-graph cohort gather/scatter of the chunked
  registry window (server/simulation.py)

Toggle: attribution defaults ON (zero runtime cost). Set
``FL4HEALTH_STAGE_ATTRIBUTION=0`` in the environment, call
:func:`set_enabled`, or use the :func:`disabled` context manager to turn
the scopes (and hloscan's per-stage reports) off; the off path is the
byte-exact legacy program.
"""

from __future__ import annotations

import contextlib
import os
import re
from typing import Iterator

# The marker hloscan greps for in HLO op_name metadata paths and
# roofline_report greps for in XProf trace op names. "::" cannot appear in
# a user module/function name the way "/" separators do, so the prefix
# never collides with ordinary scope components.
STAGE_PREFIX = "fl_stage::"

# Canonical spine stage names, in pipeline order (the order the roofline
# ledger lists them when headrooms tie).
SPINE_STAGES = (
    "local_train",
    "dp_clip",
    "rotation",
    "topk",
    "quantize",
    "robust_aggregate",
    "server_update",
    "cohort_exchange",
)

# Ops outside any fl_stage scope attribute here (still real work — the
# conservation check needs them on the ledger, never silently dropped).
UNATTRIBUTED = "_unattributed"

_STAGE_RE = re.compile(re.escape(STAGE_PREFIX) + r"([A-Za-z0-9_.\-]+)")

_enabled = os.environ.get("FL4HEALTH_STAGE_ATTRIBUTION", "1") != "0"


def enabled() -> bool:
    """True when stage scopes are being applied (process-wide toggle)."""
    return _enabled


def set_enabled(on: bool) -> None:
    """Flip stage attribution process-wide. Affects programs traced AFTER
    the call — already-compiled programs keep whatever metadata they were
    traced with."""
    global _enabled
    _enabled = bool(on)


@contextlib.contextmanager
def disabled() -> Iterator[None]:
    """Temporarily trace without stage scopes (the bit-identity tests'
    off arm)."""
    global _enabled
    prev = _enabled
    _enabled = False
    try:
        yield
    finally:
        _enabled = prev


@contextlib.contextmanager
def stage(name: str) -> Iterator[None]:
    """Scope a traced code region as spine stage ``name``.

    A no-op (and zero-overhead at run time either way — named scopes are
    trace-time metadata) when attribution is disabled. ``jax`` is imported
    lazily so tools can import this module's parsing helpers without a
    backend."""
    if not _enabled:
        yield
        return
    import jax

    with jax.named_scope(STAGE_PREFIX + name):
        yield


def stage_of(op_name: str | None) -> str | None:
    """The spine stage an HLO/trace ``op_name`` path belongs to, or None.

    Takes the LAST ``fl_stage::`` component on the path — scopes nest
    (``server_update`` wraps ``robust_aggregate`` wraps nothing), and the
    innermost name is the most specific attribution."""
    if not op_name:
        return None
    hits = _STAGE_RE.findall(op_name)
    return hits[-1] if hits else None

"""Declarative SLOs over the round time-series — the "is the service OK" layer.

Role: an orchestrator probing ``/healthz`` can tell *dead* (503) from
*alive* (200), but not *limping* — a run that still completes rounds while
its cadence collapses, its eval loss stalls, or its wire budget blows out.
``SLOPolicy`` declares the service levels ROADMAP item 3 names (round-cadence
floor, eval-loss ceiling/stall, bytes-per-client budget, MTTR target,
straggler-p99 bound) and ``SLOEngine`` evaluates them each round in the
epilogue against the KPIs ``timeseries.RoundTimeSeries`` computed — still
zero extra device syncs.

Burn-rate semantics (the SRE multi-window idiom): each objective keeps a
bounded window of per-round pass/fail samples; the *burn rate* over a window
is ``violating_fraction / error_budget``. Sustained burn >= 1 over BOTH the
short and long window means the error budget is being spent faster than
allowed — standing ``breach`` (run degraded); short-window burn alone is
``warn`` (blip, don't page). Transitions emit ``slo`` JSONL events and every
evaluation refreshes ``fl_slo_*`` gauges, so both the log and the scrape
surface carry the verdicts ``tools/run_diff.py`` compares across runs.
"""

from __future__ import annotations

import dataclasses
import threading
from collections import deque
from typing import Any, Mapping

__all__ = ["SLOPolicy", "SLOEngine", "SLO_OBJECTIVES"]

# Declared order doubles as severity tie-break: when several objectives
# breach at once, /healthz names the first.
SLO_OBJECTIVES = (
    "round_cadence",
    "eval_loss",
    "eval_stall",
    "bytes_per_client",
    "mttr",
    "straggler_p99",
)


@dataclasses.dataclass(frozen=True)
class SLOPolicy:
    """Service-level objectives for a federated run. ``None`` disables one.

    - ``min_rounds_per_hour``: cadence floor (windowed wall-clock rate).
    - ``max_eval_loss``: ceiling on the checkpoint eval loss.
    - ``stall_rounds`` / ``stall_min_delta``: eval loss must improve by at
      least ``stall_min_delta`` within any ``stall_rounds`` consecutive
      evaluated rounds.
    - ``max_bytes_per_client``: per-round wire budget (broadcast + gather,
      post-compression when the wire path recorded it).
    - ``max_mttr_s``: recovery MTTR target — mean engage→probation_passed
      wall time, and any still-open incident older than this violates too.
    - ``max_straggler_p99``: bound on the fleet straggler p99 (needs the
      fleet ledger; unevaluated otherwise).
    - ``error_budget``: allowed violating fraction of rounds per window.
    - ``short_window`` / ``long_window``: burn-rate windows, in rounds.
    """

    min_rounds_per_hour: float | None = None
    max_eval_loss: float | None = None
    stall_rounds: int | None = None
    stall_min_delta: float = 0.0
    max_bytes_per_client: float | None = None
    max_mttr_s: float | None = None
    max_straggler_p99: float | None = None
    error_budget: float = 0.1
    short_window: int = 5
    long_window: int = 30

    def __post_init__(self):
        if not (0.0 < self.error_budget <= 1.0):
            raise ValueError(
                f"error_budget must be in (0, 1]; got {self.error_budget}")
        if self.short_window < 1 or self.long_window < self.short_window:
            raise ValueError(
                "windows must satisfy 1 <= short_window <= long_window; "
                f"got short={self.short_window} long={self.long_window}")
        if self.stall_rounds is not None and self.stall_rounds < 1:
            raise ValueError(f"stall_rounds must be >= 1; got {self.stall_rounds}")
        for name in ("min_rounds_per_hour", "max_eval_loss",
                     "max_bytes_per_client", "max_mttr_s",
                     "max_straggler_p99"):
            v = getattr(self, name)
            if v is not None and float(v) <= 0.0:
                raise ValueError(f"{name} must be positive; got {v}")

    def objectives(self) -> tuple[str, ...]:
        """Objective names this policy actually arms, in severity order."""
        armed = {
            "round_cadence": self.min_rounds_per_hour is not None,
            "eval_loss": self.max_eval_loss is not None,
            "eval_stall": self.stall_rounds is not None,
            "bytes_per_client": self.max_bytes_per_client is not None,
            "mttr": self.max_mttr_s is not None,
            "straggler_p99": self.max_straggler_p99 is not None,
        }
        return tuple(n for n in SLO_OBJECTIVES if armed[n])

    def describe(self) -> dict[str, Any]:
        return dataclasses.asdict(self)


class SLOEngine:
    """Evaluates an ``SLOPolicy`` per round; tracks burn-rate standing.

    ``evaluate`` runs on the epilogue thread; ``standing()`` is read by the
    HTTP handler serving ``GET /admin/slo`` — one lock covers both.
    """

    def __init__(self, policy: SLOPolicy, registry=None):
        self.policy = policy
        self._registry = registry
        self._lock = threading.Lock()
        self._samples: dict[str, deque[bool]] = {
            n: deque(maxlen=policy.long_window) for n in policy.objectives()
        }
        self._standing: dict[str, str] = {n: "ok" for n in self._samples}
        self._best_eval: float | None = None
        self._since_improve = 0
        self._last_verdict: dict[str, Any] | None = None
        self._last_kpis: dict[str, Any] | None = None

    # ------------------------------------------------------------ evaluation
    def _violations(self, kpis: Mapping[str, Any]) -> dict[str, bool | None]:
        """Per-objective violation this round; None = signal absent, skip."""
        p = self.policy
        out: dict[str, bool | None] = {}
        if "round_cadence" in self._samples:
            rph = kpis.get("rounds_per_hour")
            out["round_cadence"] = (
                None if rph is None else rph < p.min_rounds_per_hour)
        eval_loss = kpis.get("eval_loss")
        if "eval_loss" in self._samples:
            out["eval_loss"] = (
                None if eval_loss is None else eval_loss > p.max_eval_loss)
        if "eval_stall" in self._samples:
            if eval_loss is None:
                out["eval_stall"] = None
            else:
                if (self._best_eval is None
                        or eval_loss < self._best_eval - p.stall_min_delta):
                    self._best_eval = eval_loss
                    self._since_improve = 0
                else:
                    self._since_improve += 1
                out["eval_stall"] = self._since_improve >= p.stall_rounds
        if "bytes_per_client" in self._samples:
            bpc = kpis.get("bytes_per_client")
            out["bytes_per_client"] = (
                None if bpc is None else bpc > p.max_bytes_per_client)
        if "mttr" in self._samples:
            mttr, open_s = kpis.get("mttr_s"), kpis.get("mttr_open_s")
            if mttr is None and open_s is None:
                out["mttr"] = None  # no incident ever — nothing to judge
            else:
                out["mttr"] = ((mttr is not None and mttr > p.max_mttr_s)
                               or (open_s is not None and open_s > p.max_mttr_s))
        if "straggler_p99" in self._samples:
            tail = kpis.get("straggler_p99")
            out["straggler_p99"] = (
                None if tail is None else tail > p.max_straggler_p99)
        return out

    @staticmethod
    def _burn(samples: deque[bool], window: int, budget: float) -> float:
        recent = list(samples)[-window:]
        if not recent:
            return 0.0
        return (sum(recent) / len(recent)) / budget

    def evaluate(self, rnd: int, kpis: Mapping[str, Any]) -> dict[str, Any]:
        """Fold one round of KPIs in; returns the verdict for this round.

        Verdict: ``{"round", "state", "degraded_slo", "objectives": {name:
        {"violated", "burn_short", "burn_long", "standing"}}}``. Emits an
        ``slo`` JSONL event per standing *transition* (logs stay quiet on
        healthy runs) and refreshes ``fl_slo_*`` gauges every round.
        """
        p = self.policy
        with self._lock:
            violations = self._violations(kpis)
            objectives: dict[str, dict[str, Any]] = {}
            degraded: str | None = None
            transitions: list[tuple[str, str, dict[str, Any]]] = []
            for name in self._samples:
                v = violations.get(name)
                if v is not None:
                    self._samples[name].append(bool(v))
                burn_short = self._burn(self._samples[name], p.short_window,
                                        p.error_budget)
                burn_long = self._burn(self._samples[name], p.long_window,
                                       p.error_budget)
                if burn_short >= 1.0 and burn_long >= 1.0:
                    standing = "breach"
                elif burn_short >= 1.0:
                    standing = "warn"
                else:
                    standing = "ok"
                obj = {
                    "violated": v,
                    "burn_short": round(burn_short, 4),
                    "burn_long": round(burn_long, 4),
                    "standing": standing,
                }
                objectives[name] = obj
                if standing == "breach" and degraded is None:
                    degraded = name
                if standing != self._standing[name]:
                    transitions.append((name, standing, obj))
                    self._standing[name] = standing
            state = ("breach" if degraded is not None
                     else "warn" if any(o["standing"] == "warn"
                                        for o in objectives.values())
                     else "ok")
            verdict = {"round": int(rnd), "state": state,
                       "degraded_slo": degraded, "objectives": objectives}
            self._last_verdict = verdict
            self._last_kpis = dict(kpis)
        reg = self._registry
        if reg is not None:
            for name, standing, obj in transitions:
                reg.log_event("slo", round=int(rnd), slo=name,
                              standing=standing, violated=obj["violated"],
                              burn_short=obj["burn_short"],
                              burn_long=obj["burn_long"], state=state)
            for name, obj in objectives.items():
                reg.gauge("fl_slo_burn_rate",
                          help="error-budget burn rate over the short window "
                               "(>=1 means burning faster than budgeted)",
                          labels={"slo": name, "window": "short"},
                          ).set(obj["burn_short"])
                reg.gauge("fl_slo_burn_rate",
                          help="error-budget burn rate over the short window "
                               "(>=1 means burning faster than budgeted)",
                          labels={"slo": name, "window": "long"},
                          ).set(obj["burn_long"])
                if obj["violated"]:
                    reg.counter("fl_slo_violations",
                                help="rounds that violated an SLO objective",
                                labels={"slo": name}).inc()
            reg.gauge("fl_slo_degraded",
                      help="1 while any SLO objective stands in breach "
                           "(healthz answers 'degraded: <slo>')",
                      ).set(1.0 if degraded is not None else 0.0)
        return verdict

    # ----------------------------------------------------------------- reads
    @property
    def degraded_slo(self) -> str | None:
        with self._lock:
            v = self._last_verdict
            return None if v is None else v["degraded_slo"]

    def standing(self) -> dict[str, Any]:
        """The JSON document ``GET /admin/slo`` serves."""
        with self._lock:
            v = self._last_verdict
            return {
                "policy": self.policy.describe(),
                "objectives_armed": list(self.policy.objectives()),
                "state": "ok" if v is None else v["state"],
                "degraded_slo": None if v is None else v["degraded_slo"],
                "round": None if v is None else v["round"],
                "objectives": {} if v is None else v["objectives"],
                "kpis": dict(self._last_kpis or {}),
            }

"""Metrics registry — process-wide counters/gauges/histograms.

Role: the per-round byte/time accounting that communication-efficiency work
treats as a first-class experimental output (arXiv:1610.05492 reports
per-round upload bytes; FedJAX logs simulation timing). Two exposition
surfaces:

- ``to_prometheus()`` — the Prometheus text format (``# HELP``/``# TYPE`` +
  samples), scrapable or diffable in tests;
- ``log_event()`` + ``dump_jsonl()`` — an append-only JSONL event log (one
  JSON object per line) that ``tools/perf_report.py`` renders into a
  per-round summary table.

All instruments are host-side Python on plain floats: no device syncs, no
JAX imports — safe to call from transport code and the round loop alike.
Thread-safe via one registry lock (instrument mutation is a dict update;
contention is negligible next to an XLA dispatch).
"""

from __future__ import annotations

import glob
import gzip
import json
import math
import os
import re
import threading
import time
from typing import Any, Iterable, Mapping

from fl4health_tpu.core.io import atomic_write

DEFAULT_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0, 60.0, math.inf,
)


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if v == -math.inf:
        return "-Inf"
    if math.isnan(v):
        return "NaN"  # exposition-format canonical spelling
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


def _escape_label(v: Any) -> str:
    return str(v).replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _escape_help(v: str) -> str:
    # Exposition-format 0.0.4: HELP text escapes backslash and newline
    # (quotes are NOT escaped in HELP, unlike label values).
    return str(v).replace("\\", "\\\\").replace("\n", "\\n")


def _label_str(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class Counter:
    """Monotonic counter. ``inc`` with a negative amount raises — a counter
    that can decrease silently corrupts rate() math downstream."""

    __slots__ = ("name", "help", "labels", "_value", "_lock")

    def __init__(self, name: str, help: str = "", labels: Mapping[str, str] | None = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError(f"counter {self.name}: negative increment {amount}")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    @property
    def exposition_name(self) -> str:
        # Prometheus conformance: counters MUST carry the _total suffix in
        # the text exposition. Registry names stay as-given (snapshot() and
        # the programmatic API are unchanged); only the exposed family name
        # gains the suffix when the caller omitted it.
        return self.name if self.name.endswith("_total") else f"{self.name}_total"

    def expose(self) -> list[str]:
        return [
            f"{self.exposition_name}{_label_str(self.labels)} "
            f"{_fmt_value(self._value)}"
        ]

    def snapshot(self) -> float:
        return self._value

    prom_type = "counter"


class Gauge:
    """Last-write-wins instantaneous value; supports inc/dec for level
    tracking (in-flight RPCs)."""

    __slots__ = ("name", "help", "labels", "_value", "_lock")

    def __init__(self, name: str, help: str = "", labels: Mapping[str, str] | None = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value

    @property
    def exposition_name(self) -> str:
        return self.name

    def expose(self) -> list[str]:
        return [f"{self.name}{_label_str(self.labels)} {_fmt_value(self._value)}"]

    def snapshot(self) -> float:
        return self._value

    prom_type = "gauge"


class Histogram:
    """Cumulative-bucket histogram (Prometheus semantics: each ``le`` bucket
    counts observations <= bound; ``+Inf`` equals ``_count``)."""

    __slots__ = ("name", "help", "labels", "buckets", "_counts", "_sum", "_count", "_lock")

    def __init__(
        self,
        name: str,
        help: str = "",
        labels: Mapping[str, str] | None = None,
        buckets: Iterable[float] = DEFAULT_BUCKETS,
    ):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        bs = sorted(set(float(b) for b in buckets) | {math.inf})
        self.buckets = tuple(bs)
        self._counts = [0] * len(self.buckets)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._sum += v
            self._count += 1
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    @property
    def exposition_name(self) -> str:
        return self.name

    def expose(self) -> list[str]:
        lines = []
        for b, c in zip(self.buckets, self._counts):
            lbl = _label_str({**self.labels, "le": _fmt_value(b)})
            lines.append(f"{self.name}_bucket{lbl} {c}")
        lines.append(f"{self.name}_sum{_label_str(self.labels)} {_fmt_value(self._sum)}")
        lines.append(f"{self.name}_count{_label_str(self.labels)} {self._count}")
        return lines

    def snapshot(self) -> dict:
        return {
            "count": self._count,
            "sum": self._sum,
            "buckets": {_fmt_value(b): c for b, c in zip(self.buckets, self._counts)},
        }

    prom_type = "histogram"


DEFAULT_MAX_EVENTS = 100_000


class MetricsRegistry:
    """Names + label sets -> instruments. Getter-or-create semantics: the
    same (name, labels) always returns the same instrument, so call sites
    never coordinate registration. Re-requesting a name as a different
    instrument kind raises (a counter silently shadowed by a gauge is the
    classic metrics-soup bug).

    The JSONL event log is CAPPED at ``max_events`` records (rollover:
    oldest dropped first, counted by ``fl_events_dropped_total``) so a
    multi-thousand-round run — a few events per round plus per-client
    telemetry vectors — cannot grow host memory and the dumped log without
    bound. ``max_events=None`` disables the cap.

    ``rollover="archive"`` (opt-in; requires ``archive_path``) preserves
    evicted history instead of dropping it: evictions happen in segments of
    ~10% of the cap, each gzipped to ``<archive_path>.NNNN.jsonl.gz`` next
    to where the log will be dumped, retaining at most ``max_archives``
    segments (oldest deleted first) — so postmortem bundles can include
    pre-rollover events while disk stays bounded. The default
    (``rollover="drop"``) is byte-identical to the legacy behavior."""

    def __init__(self, max_events: int | None = DEFAULT_MAX_EVENTS,
                 rollover: str = "drop", archive_path: str | None = None,
                 max_archives: int = 8):
        if max_events is not None and max_events < 1:
            raise ValueError(f"max_events must be >= 1 or None, got {max_events}")
        if rollover not in ("drop", "archive"):
            raise ValueError(
                f"rollover must be 'drop' or 'archive'; got {rollover!r}"
            )
        if rollover == "archive" and not archive_path:
            raise ValueError("rollover='archive' requires archive_path")
        if max_archives < 1:
            raise ValueError(f"max_archives must be >= 1; got {max_archives}")
        self.max_events = max_events
        self.rollover = rollover
        self.archive_path = archive_path
        self.max_archives = int(max_archives)
        # resume the sequence past any segments already on disk — a new
        # registry reusing an archive_path must not overwrite history
        self._archive_seq = self._existing_archive_seq()
        self._metrics: dict[tuple[str, tuple], Any] = {}
        self._helps: dict[str, str] = {}
        self._events: list[dict] = []
        self._lock = threading.Lock()

    # -- instruments -----------------------------------------------------
    def _get(self, cls, name, help, labels, **kwargs):
        key = (name, tuple(sorted((labels or {}).items())))
        with self._lock:
            existing = self._metrics.get(key)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise TypeError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}, requested {cls.__name__}"
                    )
                if help:
                    # a metric first touched help-lessly (e.g. a baseline
                    # read) still earns its # HELP line from a later caller
                    self._helps.setdefault(name, help)
                return existing
            m = cls(name, help=help, labels=labels, **kwargs)
            self._metrics[key] = m
            if help:
                self._helps.setdefault(name, help)
            return m

    def counter(self, name: str, help: str = "", labels: Mapping[str, str] | None = None) -> Counter:
        return self._get(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "", labels: Mapping[str, str] | None = None) -> Gauge:
        return self._get(Gauge, name, help, labels)

    def histogram(
        self,
        name: str,
        help: str = "",
        labels: Mapping[str, str] | None = None,
        buckets: Iterable[float] = DEFAULT_BUCKETS,
    ) -> Histogram:
        return self._get(Histogram, name, help, labels, buckets=buckets)

    # -- event log -------------------------------------------------------
    def log_event(self, event: str, **fields: Any) -> dict:
        """Append one structured event (stamped with wall time) to the JSONL
        log. Returns the record for immediate reuse (reporter bridging).
        Past ``max_events`` the log rolls over (oldest records dropped,
        visible in ``fl_events_dropped_total``)."""
        rec = {"ts": time.time(), "event": event, **fields}
        dropped = 0
        evicted: list[dict] | None = None
        with self._lock:
            self._events.append(rec)
            if self.max_events is not None and len(self._events) > self.max_events:
                if self.rollover == "archive":
                    # evict a SEGMENT (~10% of the cap) so the gzip cost
                    # amortizes instead of landing on every append
                    n = max(len(self._events) - self.max_events,
                            max(self.max_events // 10, 1))
                    n = min(n, len(self._events) - 1)  # keep the new record
                    evicted = self._events[:n]
                    del self._events[:n]
                else:
                    dropped = len(self._events) - self.max_events
                    del self._events[:dropped]
        if dropped:
            # outside the registry lock: counter() re-acquires it
            self.counter(
                "fl_events_dropped_total",
                help="JSONL event-log records dropped by size rollover",
            ).inc(dropped)
        if evicted:
            self._archive_segment(evicted)
        return rec

    def _archive_segment(self, records: list[dict]) -> None:
        """Gzip one evicted segment next to the (future) log dump and prune
        the archive set to ``max_archives``. Archive failures degrade to
        drop semantics — the log must never take down the run."""
        try:
            with self._lock:
                # seq/path allocation under the registry lock: concurrent
                # evicting threads (round consumer + checkpoint on_save)
                # must not collide on one segment path
                self._archive_seq += 1
                path = (f"{self.archive_path}."
                        f"{self._archive_seq:04d}.jsonl.gz")
            with atomic_write(path, "wb") as f:
                with gzip.GzipFile(fileobj=f, mode="wb") as gz:
                    for rec in records:
                        gz.write((json.dumps(rec, default=str) + "\n")
                                 .encode("utf-8"))
            segs = self.archive_paths()
            for old in segs[:max(len(segs) - self.max_archives, 0)]:
                try:
                    os.remove(old)
                except OSError:
                    pass
            self.counter(
                "fl_events_archived_total",
                help="JSONL event-log records preserved to gzip archive "
                     "segments by rollover",
            ).inc(len(records))
        except Exception:
            self.counter(
                "fl_events_dropped_total",
                help="JSONL event-log records dropped by size rollover",
            ).inc(len(records))

    def archive_paths(self) -> list[str]:
        """Existing archive segments, oldest first (empty without
        ``rollover='archive'``)."""
        if not self.archive_path:
            return []
        # escape the base: a path with glob metacharacters ([run-v4] ...)
        # must still discover/prune its own segments
        return sorted(glob.glob(f"{glob.escape(self.archive_path)}"
                                ".*.jsonl.gz"))

    def _existing_archive_seq(self) -> int:
        best = 0
        for p in self.archive_paths():
            m = re.search(r"\.(\d+)\.jsonl\.gz$", p)
            if m:
                best = max(best, int(m.group(1)))
        return best

    @property
    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def dump_jsonl(self, path: str) -> str:
        """Atomic JSONL dump of the event log."""
        with atomic_write(path) as f:
            for rec in self.events:
                f.write(json.dumps(rec) + "\n")
        return path

    # -- exposition ------------------------------------------------------
    def snapshot(self) -> dict:
        """{name: value | {labels...} | histogram-dict} — the programmatic
        view tests and the reporter bridge consume."""
        out: dict[str, Any] = {}
        with self._lock:
            items = list(self._metrics.items())
        for (name, labels), m in items:
            val = m.snapshot()
            if labels:
                slot = out.setdefault(name, {})
                slot[_label_str(dict(labels))] = val
            else:
                out[name] = val
        return out

    def to_prometheus(self) -> str:
        """Prometheus text exposition format 0.0.4: families grouped by
        EXPOSITION name (counters gain the mandatory ``_total`` suffix if
        registered without it), one ``# HELP``/``# TYPE`` pair per family,
        HELP text escaped per the spec."""
        with self._lock:
            items = list(self._metrics.items())
            helps = dict(self._helps)
        by_name: dict[str, list] = {}
        raw_names: dict[str, str] = {}
        for (name, _), m in items:
            by_name.setdefault(m.exposition_name, []).append(m)
            raw_names.setdefault(m.exposition_name, name)
        lines: list[str] = []
        for name in sorted(by_name):
            ms = by_name[name]
            help_text = helps.get(raw_names[name], "")
            if help_text:
                lines.append(f"# HELP {name} {_escape_help(help_text)}")
            lines.append(f"# TYPE {name} {ms[0].prom_type}")
            for m in ms:
                lines.extend(m.expose())
        return "\n".join(lines) + ("\n" if lines else "")

    def export_prometheus(self, path: str) -> str:
        with atomic_write(path) as f:
            f.write(self.to_prometheus())
        return path

    def clear_events(self) -> None:
        """Drop the event log only (instruments keep their process-lifetime
        counter semantics) — called after a run's JSONL dump so a second run
        in the same process doesn't re-dump round records it didn't own."""
        with self._lock:
            self._events.clear()

    def clear(self) -> None:
        with self._lock:
            self._metrics.clear()
            self._helps.clear()
            self._events.clear()


# ---------------------------------------------------------------------------
# Process-wide default registry: transport counters and the simulation's
# round accounting land in ONE snapshot unless a caller wires a private one.
# ---------------------------------------------------------------------------

_default_registry = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _default_registry


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    """Install ``registry`` as the process default; returns the previous one
    (tests swap in a private registry and restore)."""
    global _default_registry
    prev = _default_registry
    _default_registry = registry
    return prev

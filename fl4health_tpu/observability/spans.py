"""Span tracer — Chrome trace-event JSON on monotonic clocks.

The reference instruments rounds with coarse ``time.time()`` deltas fed to
reporters (base_server.py:288-300 wall-clock accounting). On the TPU build a
round is two XLA dispatches, so the interesting structure is *inside* a
round: configure_fit vs. device execute vs. host aggregation vs. checkpoint.
This tracer records nested context-manager spans on ``perf_counter_ns`` and
exports the Chrome trace-event format (``{"traceEvents": [...]}``) that
Perfetto / ``chrome://tracing`` render as a per-round flame timeline — the
FedJAX-style built-in simulation timing (arXiv:2108.02117 §4) without any
external dependency.

Disabled-path contract: a disabled tracer's ``span()`` returns a shared
no-op context manager — no allocation, no locking, no clock reads — so the
round hot loop pays nothing when observability is off.

Crash safety: ``export()`` publishes a complete ``{"traceEvents": [...]}``
envelope atomically at shutdown, but a process that DIES mid-run never
reaches it. ``stream_to(path)`` additionally appends each event to ``path``
as it is recorded, in the Chrome trace *JSON Array Format* — whose closing
``]`` is optional per the trace-event spec, so the file stays loadable in
Perfetto even after a SIGKILL mid-run. An ``atexit`` hook terminates the
array on any orderly interpreter exit, and :func:`load_trace` is the
tolerant reader (complete envelope, terminated array, or a stream torn
mid-line) the postmortem tooling uses.
"""

from __future__ import annotations

import atexit
import json
import os
import threading
import time
from typing import Any

from fl4health_tpu.core.io import atomic_write


def load_trace(path: str) -> dict:
    """Load a Chrome trace written by this module — the complete
    ``{"traceEvents": [...]}`` envelope, a bare event array, or an
    UNTERMINATED streamed array (the crash case: trailing comma, or a
    partial final line torn by the kill). Returns the envelope form;
    raises ``ValueError`` when nothing parseable remains."""
    with open(path) as f:
        text = f.read()
    doc = None
    try:
        doc = json.loads(text)
    except json.JSONDecodeError:
        # streamed array killed mid-run: strip any torn final line, close
        # the array ourselves
        body = text.strip()
        while body:
            candidate = body.rstrip().rstrip(",")
            try:
                doc = json.loads(candidate + "]")
                break
            except json.JSONDecodeError:
                # drop the last (possibly partial) line and retry
                cut = body.rfind("\n")
                if cut < 0:
                    break
                body = body[:cut]
    if doc is None:
        raise ValueError(f"{path}: no parseable trace content")
    if isinstance(doc, list):
        events = [e for e in doc if e]  # drop the {} terminator sentinel
        return {"traceEvents": events, "displayTimeUnit": "ms"}
    return doc


class _NullSpan:
    """Shared no-op span: reentrant, stateless, free."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args: Any) -> None:
        pass


_NULL_SPAN = _NullSpan()


class Span:
    """One live span; records a complete ("ph": "X") trace event on exit."""

    __slots__ = ("tracer", "name", "cat", "args", "_start_ns", "_depth")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: dict):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._start_ns = 0
        self._depth = 0

    def set(self, **args: Any) -> None:
        """Attach/override args mid-span (e.g. measured byte counts)."""
        self.args.update(args)

    def __enter__(self) -> "Span":
        self._depth = self.tracer._enter_depth()
        self._start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        end_ns = time.perf_counter_ns()
        self.tracer._exit_depth()
        if exc_type is not None:
            self.args.setdefault("error", exc_type.__name__)
        self.tracer._record(
            self.name, self.cat, self._start_ns, end_ns, self._depth, self.args
        )
        return False


class Tracer:
    """Collects spans; thread-safe; exports Chrome trace-event JSON.

    Timestamps are microseconds since tracer construction (monotonic clock),
    so traces from one process align across threads. ``depth`` is recorded in
    each event's args for programmatic nesting assertions; the viewer derives
    visual nesting from ts/dur containment on its own.
    """

    def __init__(self, enabled: bool = True, process_name: str = "fl4health_tpu"):
        self.enabled = enabled
        self.process_name = process_name
        # Two clocks sampled back-to-back: event timestamps stay on the
        # monotonic clock (cheap, never steps backwards), while the wall
        # anchor lets tools/trace_merge.py place this process's ts=0 on a
        # cross-process wall-clock axis.
        self._t0_ns = time.perf_counter_ns()
        self._wall0_ns = time.time_ns()
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._local = threading.local()
        self._thread_names: dict[int, str] = {}
        self._stream = None
        self._stream_path: str | None = None
        self._atexit_registered = False

    # -- cross-process metadata ------------------------------------------
    @property
    def wall0_ns(self) -> int:
        """Wall-clock time (``time.time_ns()``) at tracer construction —
        the instant all event ``ts`` values are relative to."""
        return self._wall0_ns

    def set_process_name(self, name: str) -> None:
        """Rename the process lane (e.g. ``coordinator`` vs ``silo:1``)
        shown in Perfetto. Takes effect in subsequent exports; a live
        stream gets a fresh ``process_name`` metadata event immediately."""
        self.process_name = name
        evt = {
            "name": "process_name", "ph": "M", "pid": os.getpid(),
            "tid": 0, "args": {"name": name},
        }
        with self._lock:
            self._stream_event(evt)

    def _clock_sync_event(self) -> dict:
        # a pinned instant at ts=0 carrying the wall anchor; trace_merge
        # shifts each process's events by the wall delta between anchors
        return {
            "name": "clock_sync", "cat": "__metadata", "ph": "i", "s": "p",
            "ts": 0.0, "pid": os.getpid(), "tid": 0,
            "args": {"wall_ns": self._wall0_ns},
        }

    def _thread_meta_locked(self, tid: int) -> None:
        # caller holds self._lock; first sighting of a thread emits its
        # thread_name metadata event so merged timelines label lanes
        if tid in self._thread_names:
            return
        name = threading.current_thread().name
        self._thread_names[tid] = name
        evt = {
            "name": "thread_name", "ph": "M", "pid": os.getpid(),
            "tid": tid, "args": {"name": name},
        }
        self._events.append(evt)
        self._stream_event(evt)

    # -- crash-safe streaming -------------------------------------------
    def stream_to(self, path: str) -> str | None:
        """Mirror every recorded event to ``path`` as it happens, in the
        Chrome JSON Array Format (loadable even unterminated — the spec
        makes the closing ``]`` optional, and :func:`load_trace` tolerates
        a torn final line). Events are flushed per record: span volume is a
        handful per round, so durability costs nothing measurable. Returns
        the path, or None when a different stream is already open (the
        first owner wins — a second Observability handle must not redirect
        a shared tracer's black box)."""
        with self._lock:
            if self._stream is not None:
                return path if self._stream_path == path else None
            os.makedirs(os.path.dirname(os.path.abspath(path)),
                        exist_ok=True)
            self._stream = open(path, "w")
            self._stream_path = path
            self._stream.write("[\n")
            self._stream.write(json.dumps({
                "name": "process_name", "ph": "M", "pid": os.getpid(),
                "tid": 0, "args": {"name": self.process_name},
            }) + ",\n")
            self._stream.write(json.dumps(self._clock_sync_event()) + ",\n")
            self._stream.flush()
            # replay whatever was recorded before the stream opened, so a
            # tracer enabled earlier than Observability.start() loses
            # nothing
            for evt in self._events:
                self._stream.write(json.dumps(evt) + ",\n")
            self._stream.flush()
        if not self._atexit_registered:
            # orderly exits (incl. unhandled exceptions) terminate the
            # array; a SIGKILL can't run this, which is why the format is
            # chosen to stay loadable without it
            atexit.register(self.close_stream)
            self._atexit_registered = True
        return path

    @property
    def stream_path(self) -> str | None:
        return self._stream_path

    def _stream_event(self, evt: dict) -> None:
        # caller holds self._lock
        if self._stream is not None:
            try:
                self._stream.write(json.dumps(evt, default=str) + ",\n")
                self._stream.flush()
            except (OSError, ValueError):  # closed/readonly fs: stop trying
                self._stream = None

    def close_stream(self) -> None:
        """Terminate the streamed array (``{}]`` — the empty object is the
        terminator sentinel ``load_trace`` drops) and close the file.
        Idempotent; safe from ``atexit``."""
        with self._lock:
            stream, self._stream = self._stream, None
            self._stream_path = None
        if stream is not None:
            try:
                stream.write("{}]\n")
                stream.close()
            except (OSError, ValueError):
                pass

    # -- depth bookkeeping (thread-local; tests assert nesting) ----------
    def _enter_depth(self) -> int:
        d = getattr(self._local, "depth", 0)
        self._local.depth = d + 1
        return d

    def _exit_depth(self) -> None:
        self._local.depth = max(0, getattr(self._local, "depth", 1) - 1)

    # -- recording -------------------------------------------------------
    def span(self, name: str, cat: str = "round", **args: Any):
        """Context manager timing a block. No-op (shared instance) when
        disabled — zero overhead on the hot path."""
        if not self.enabled:
            return _NULL_SPAN
        return Span(self, name, cat, dict(args))

    def instant(self, name: str, cat: str = "event", **args: Any) -> None:
        """A zero-duration marker ("ph": "i")."""
        if not self.enabled:
            return
        ts = (time.perf_counter_ns() - self._t0_ns) / 1000.0
        tid = threading.get_ident()
        evt = {
            "name": name, "cat": cat, "ph": "i", "s": "t",
            "ts": ts, "pid": os.getpid(), "tid": tid,
            "args": dict(args),
        }
        with self._lock:
            self._thread_meta_locked(tid)
            self._events.append(evt)
            self._stream_event(evt)

    def flow(self, ph: str, name: str, flow_id: int,
             cat: str = "flow", **args: Any) -> None:
        """A Chrome flow event: ``ph`` is ``"s"`` (start), ``"t"`` (step)
        or ``"f"`` (end). Events sharing ``flow_id`` are drawn as arrows
        between the slices that enclose them — across threads in one
        trace, and across processes once ``tools/trace_merge.py`` has put
        the traces on a shared clock."""
        if not self.enabled:
            return
        if ph not in ("s", "t", "f"):
            raise ValueError(f"flow ph must be 's'/'t'/'f', got {ph!r}")
        ts = (time.perf_counter_ns() - self._t0_ns) / 1000.0
        tid = threading.get_ident()
        evt = {
            "name": name, "cat": cat, "ph": ph, "id": flow_id,
            "ts": ts, "pid": os.getpid(), "tid": tid,
            "args": dict(args),
        }
        if ph == "f":
            evt["bp"] = "e"  # bind to the enclosing slice, not the next one
        with self._lock:
            self._thread_meta_locked(tid)
            self._events.append(evt)
            self._stream_event(evt)

    def counter(self, name: str, **series: float) -> None:
        """A Chrome counter track sample ("ph": "C")."""
        if not self.enabled:
            return
        ts = (time.perf_counter_ns() - self._t0_ns) / 1000.0
        tid = threading.get_ident()
        evt = {
            "name": name, "cat": "counter", "ph": "C",
            "ts": ts, "pid": os.getpid(), "tid": tid,
            "args": {k: float(v) for k, v in series.items()},
        }
        with self._lock:
            self._thread_meta_locked(tid)
            self._events.append(evt)
            self._stream_event(evt)

    def _record(self, name, cat, start_ns, end_ns, depth, args) -> None:
        tid = threading.get_ident()
        evt = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": (start_ns - self._t0_ns) / 1000.0,
            "dur": (end_ns - start_ns) / 1000.0,
            "pid": os.getpid(),
            "tid": tid,
            "args": {**args, "depth": depth},
        }
        with self._lock:
            self._thread_meta_locked(tid)
            self._events.append(evt)
            self._stream_event(evt)

    # -- introspection / export -----------------------------------------
    @property
    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def spans_named(self, name: str) -> list[dict]:
        return [e for e in self.events if e["ph"] == "X" and e["name"] == name]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def to_chrome_trace(self) -> dict:
        """The Chrome trace-event envelope Perfetto expects."""
        meta = {
            "name": "process_name", "ph": "M", "pid": os.getpid(), "tid": 0,
            "args": {"name": self.process_name},
        }
        sync = self._clock_sync_event()
        return {"traceEvents": [meta, sync, *self.events],
                "displayTimeUnit": "ms"}

    def export(self, path: str) -> str:
        """Atomically write the trace JSON (a crash mid-dump never leaves a
        truncated, unloadable trace at the published path). When a live
        stream targets the same path it is closed first, so the complete
        envelope REPLACES the streamed array at shutdown."""
        if self._stream_path == path:
            self.close_stream()
        with atomic_write(path) as f:
            json.dump(self.to_chrome_trace(), f, default=str)
        return path


# ---------------------------------------------------------------------------
# Process-wide default tracer: free functions (transport/codec.py,
# transport/coordinator.py) trace through this without threading a handle.
# Starts disabled; Observability(enabled=True) flips it on.
# ---------------------------------------------------------------------------

_default_tracer = Tracer(enabled=False)


def get_tracer() -> Tracer:
    return _default_tracer


def set_tracer(tracer: Tracer) -> Tracer:
    """Install ``tracer`` as the process default; returns the previous one
    (tests swap in a private tracer and restore)."""
    global _default_tracer
    prev = _default_tracer
    _default_tracer = tracer
    return prev

"""Span tracer — Chrome trace-event JSON on monotonic clocks.

The reference instruments rounds with coarse ``time.time()`` deltas fed to
reporters (base_server.py:288-300 wall-clock accounting). On the TPU build a
round is two XLA dispatches, so the interesting structure is *inside* a
round: configure_fit vs. device execute vs. host aggregation vs. checkpoint.
This tracer records nested context-manager spans on ``perf_counter_ns`` and
exports the Chrome trace-event format (``{"traceEvents": [...]}``) that
Perfetto / ``chrome://tracing`` render as a per-round flame timeline — the
FedJAX-style built-in simulation timing (arXiv:2108.02117 §4) without any
external dependency.

Disabled-path contract: a disabled tracer's ``span()`` returns a shared
no-op context manager — no allocation, no locking, no clock reads — so the
round hot loop pays nothing when observability is off.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any

from fl4health_tpu.core.io import atomic_write


class _NullSpan:
    """Shared no-op span: reentrant, stateless, free."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set(self, **args: Any) -> None:
        pass


_NULL_SPAN = _NullSpan()


class Span:
    """One live span; records a complete ("ph": "X") trace event on exit."""

    __slots__ = ("tracer", "name", "cat", "args", "_start_ns", "_depth")

    def __init__(self, tracer: "Tracer", name: str, cat: str, args: dict):
        self.tracer = tracer
        self.name = name
        self.cat = cat
        self.args = args
        self._start_ns = 0
        self._depth = 0

    def set(self, **args: Any) -> None:
        """Attach/override args mid-span (e.g. measured byte counts)."""
        self.args.update(args)

    def __enter__(self) -> "Span":
        self._depth = self.tracer._enter_depth()
        self._start_ns = time.perf_counter_ns()
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        end_ns = time.perf_counter_ns()
        self.tracer._exit_depth()
        if exc_type is not None:
            self.args.setdefault("error", exc_type.__name__)
        self.tracer._record(
            self.name, self.cat, self._start_ns, end_ns, self._depth, self.args
        )
        return False


class Tracer:
    """Collects spans; thread-safe; exports Chrome trace-event JSON.

    Timestamps are microseconds since tracer construction (monotonic clock),
    so traces from one process align across threads. ``depth`` is recorded in
    each event's args for programmatic nesting assertions; the viewer derives
    visual nesting from ts/dur containment on its own.
    """

    def __init__(self, enabled: bool = True, process_name: str = "fl4health_tpu"):
        self.enabled = enabled
        self.process_name = process_name
        self._t0_ns = time.perf_counter_ns()
        self._events: list[dict] = []
        self._lock = threading.Lock()
        self._local = threading.local()

    # -- depth bookkeeping (thread-local; tests assert nesting) ----------
    def _enter_depth(self) -> int:
        d = getattr(self._local, "depth", 0)
        self._local.depth = d + 1
        return d

    def _exit_depth(self) -> None:
        self._local.depth = max(0, getattr(self._local, "depth", 1) - 1)

    # -- recording -------------------------------------------------------
    def span(self, name: str, cat: str = "round", **args: Any):
        """Context manager timing a block. No-op (shared instance) when
        disabled — zero overhead on the hot path."""
        if not self.enabled:
            return _NULL_SPAN
        return Span(self, name, cat, dict(args))

    def instant(self, name: str, cat: str = "event", **args: Any) -> None:
        """A zero-duration marker ("ph": "i")."""
        if not self.enabled:
            return
        ts = (time.perf_counter_ns() - self._t0_ns) / 1000.0
        evt = {
            "name": name, "cat": cat, "ph": "i", "s": "t",
            "ts": ts, "pid": os.getpid(), "tid": threading.get_ident(),
            "args": dict(args),
        }
        with self._lock:
            self._events.append(evt)

    def counter(self, name: str, **series: float) -> None:
        """A Chrome counter track sample ("ph": "C")."""
        if not self.enabled:
            return
        ts = (time.perf_counter_ns() - self._t0_ns) / 1000.0
        evt = {
            "name": name, "cat": "counter", "ph": "C",
            "ts": ts, "pid": os.getpid(), "tid": threading.get_ident(),
            "args": {k: float(v) for k, v in series.items()},
        }
        with self._lock:
            self._events.append(evt)

    def _record(self, name, cat, start_ns, end_ns, depth, args) -> None:
        evt = {
            "name": name,
            "cat": cat,
            "ph": "X",
            "ts": (start_ns - self._t0_ns) / 1000.0,
            "dur": (end_ns - start_ns) / 1000.0,
            "pid": os.getpid(),
            "tid": threading.get_ident(),
            "args": {**args, "depth": depth},
        }
        with self._lock:
            self._events.append(evt)

    # -- introspection / export -----------------------------------------
    @property
    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def spans_named(self, name: str) -> list[dict]:
        return [e for e in self.events if e["ph"] == "X" and e["name"] == name]

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def to_chrome_trace(self) -> dict:
        """The Chrome trace-event envelope Perfetto expects."""
        meta = {
            "name": "process_name", "ph": "M", "pid": os.getpid(), "tid": 0,
            "args": {"name": self.process_name},
        }
        return {"traceEvents": [meta, *self.events], "displayTimeUnit": "ms"}

    def export(self, path: str) -> str:
        """Atomically write the trace JSON (a crash mid-dump never leaves a
        truncated, unloadable trace at the published path)."""
        with atomic_write(path) as f:
            json.dump(self.to_chrome_trace(), f)
        return path


# ---------------------------------------------------------------------------
# Process-wide default tracer: free functions (transport/codec.py,
# transport/coordinator.py) trace through this without threading a handle.
# Starts disabled; Observability(enabled=True) flips it on.
# ---------------------------------------------------------------------------

_default_tracer = Tracer(enabled=False)


def get_tracer() -> Tracer:
    return _default_tracer


def set_tracer(tracer: Tracer) -> Tracer:
    """Install ``tracer`` as the process default; returns the previous one
    (tests swap in a private tracer and restore)."""
    global _default_tracer
    prev = _default_tracer
    _default_tracer = tracer
    return prev

"""Fleet ledger: per-client lifetime records at registry scale.

Every observability layer before this one sees a single round window —
in-graph telemetry is per-round, the flight recorder keeps a 16-round
ring, postmortems render what the ring held. The questions a long-lived
federation actually asks are per-client over a LIFETIME: which clients
are chronic stragglers, repeat poisoners, never sampled? This module is
that memory.

Design constraints (mirroring PR 13's registry-row discipline):

- **Zero extra device syncs.** ``absorb_round`` consumes only host data
  the RoundConsumer / chunked epilogues already pulled (the fused
  ``device_get``, the telemetry dict, the quarantine mask, the cached
  payload byte counts). No jax imports, no device_get, no RNG — which is
  what makes ledger-on trajectories bit-identical to ledger-off by
  construction on every execution mode.
- **O(participated) host memory.** Records exist only for clients that
  have actually appeared (participated, or been named by quarantine /
  fault evidence). A 10M-client registry with 50 sampled per round costs
  50·rounds records, not 10M. Fleet-level distributions live in
  streaming sketches (``observability/sketches.py``) at
  registry-size-invariant memory.
- **Checkpoint-durable.** ``snapshot()`` is a JSON-safe dict the
  simulation folds into the PR 12 frame writer's host header, so the
  ledger rides the checkpoint ring: resume restores it as-of the
  restored round, and a supervisor rollback cannot double-count the
  rolled-back rounds (they re-absorb exactly once on replay).

Thread-safety follows ``flightrec.FlightRecorder``: one lock around all
mutation, scrape-side readers (``/fleet``, ``/clients/<id>``) take the
same lock and copy out.
"""

from __future__ import annotations

import threading
from typing import Any, Iterable, Mapping, Sequence

import numpy as np

from fl4health_tpu.observability.sketches import (
    FixedHistogram,
    QuantileSketch,
    gini,
)

# EMA horizon for per-client loss / update-norm (≈ last 10 appearances)
_EMA_ALPHA = 0.2

# staleness measured in server versions (async modes); bytes in powers of 2
_STALENESS_BOUNDS = (0, 1, 2, 4, 8, 16, 32, 64)
_BYTES_BOUNDS = tuple(float(1 << s) for s in range(10, 34, 2))

# lifetime suspect scoring — deliberately the same vocabulary as
# resilience/suspects.py's ring scoring so the two rankings compose
_W_NONFINITE = 4.0
_W_STRIKE = 3.0
_W_FAULT = 2.0
_W_FAILED = 1.0


def _iter(x) -> Any:
    """None -> (); anything else passes through. ``x or ()`` would choke
    on numpy arrays (ambiguous truth value), which the simulation's
    slot->registry id mapping hands in."""
    return () if x is None else x


def _jsonable(v: Any) -> Any:
    if isinstance(v, (np.integer,)):
        return int(v)
    if isinstance(v, (np.floating,)):
        return float(v)
    return v


class ClientRecord:
    """One client's lifetime, stored sparsely. ``__slots__`` because a
    long run holds one of these per participated client."""

    __slots__ = (
        "client_id", "rounds_participated", "first_seen_round",
        "last_seen_round", "loss_ema", "update_norm_ema", "nonfinite_rounds",
        "failed_rounds", "staleness_sum", "staleness_max",
        "quarantine_strikes", "quarantine_releases", "quarantined",
        "fault_rounds", "bytes_down", "bytes_up",
    )

    def __init__(self, client_id: int):
        self.client_id = int(client_id)
        self.rounds_participated = 0
        self.first_seen_round = -1
        self.last_seen_round = -1
        self.loss_ema: float | None = None
        self.update_norm_ema: float | None = None
        self.nonfinite_rounds = 0
        self.failed_rounds = 0
        self.staleness_sum = 0.0
        self.staleness_max = 0.0
        self.quarantine_strikes = 0
        self.quarantine_releases = 0
        self.quarantined = False
        self.fault_rounds = 0
        self.bytes_down = 0
        self.bytes_up = 0

    # -- derived ----------------------------------------------------------
    def suspect_score(self) -> float:
        return (self.nonfinite_rounds * _W_NONFINITE
                + self.quarantine_strikes * _W_STRIKE
                + self.fault_rounds * _W_FAULT
                + self.failed_rounds * _W_FAILED)

    def straggler_score(self, current_round: int) -> float:
        """Rounds of silence + lifetime mean staleness — high for clients
        the sampler keeps missing AND clients whose updates arrive stale."""
        gap = max(0, int(current_round) - self.last_seen_round)
        mean_stale = (self.staleness_sum / self.rounds_participated
                      if self.rounds_participated else 0.0)
        return float(gap + mean_stale)

    def to_doc(self) -> dict:
        return {k: _jsonable(getattr(self, k)) for k in self.__slots__}

    @classmethod
    def from_doc(cls, doc: Mapping[str, Any]) -> "ClientRecord":
        rec = cls(int(doc["client_id"]))
        for k in cls.__slots__:
            if k == "client_id" or k not in doc:
                continue
            setattr(rec, k, doc[k])
        return rec


class FleetLedger:
    """Registry-scale per-client lifetime ledger + fleet sketches."""

    def __init__(self, *, sketch_k: int = 128):
        self._lock = threading.Lock()
        self._records: dict[int, ClientRecord] = {}
        self._sketch_k = int(sketch_k)
        self._loss_sketch = QuantileSketch(k=self._sketch_k)
        self._gap_sketch = QuantileSketch(k=self._sketch_k)
        self._staleness_hist = FixedHistogram(_STALENESS_BOUNDS)
        self._bytes_hist = FixedHistogram(_BYTES_BOUNDS)
        self.rounds_absorbed = 0
        self.last_round = -1
        self.registry_size: int | None = None

    # -- ingestion --------------------------------------------------------
    def absorb_round(
        self,
        rnd: int,
        participants: Sequence[int],
        *,
        losses: "Sequence[float] | None" = None,
        update_norms: "Sequence[float] | None" = None,
        nonfinite: "Sequence[float] | None" = None,
        staleness: "Sequence[float] | None" = None,
        staleness_pool: "Sequence[float] | None" = None,
        failed_ids: "Iterable[int] | None" = None,
        quarantined_ids: "Iterable[int] | None" = None,
        unquarantined_ids: "Iterable[int] | None" = None,
        fault_ids: "Iterable[int] | None" = None,
        bytes_down_per_client: int = 0,
        bytes_up_per_client: int = 0,
        registry_size: "int | None" = None,
    ) -> dict:
        """Fold one completed round into the ledger. All vector args are
        aligned with ``participants`` (registry ids). Returns the round's
        fleet facts (``participants_new``, ``participation_gini``,
        ``straggler_p99``) for the round summary. Pure host work.

        Idempotence across resume/rollback is positional, not internal:
        the caller absorbs BEFORE the round's checkpoint is written, so a
        restored ledger is always as-of its frame's round and re-run
        rounds absorb exactly once.
        """
        rnd = int(rnd)
        ids = [int(c) for c in participants]
        with self._lock:
            if registry_size is not None:
                self.registry_size = int(registry_size)
            new = 0
            for i, cid in enumerate(ids):
                rec = self._records.get(cid)
                if rec is None:
                    rec = self._records[cid] = ClientRecord(cid)
                    rec.first_seen_round = rnd
                    new += 1
                else:
                    # participation gap feeds the straggler distribution
                    self._gap_sketch.add(float(rnd - rec.last_seen_round))
                rec.rounds_participated += 1
                rec.last_seen_round = rnd
                if losses is not None:
                    v = float(losses[i])
                    if v == v:  # not NaN
                        self._loss_sketch.add(v)
                        rec.loss_ema = (v if rec.loss_ema is None else
                                        (1 - _EMA_ALPHA) * rec.loss_ema
                                        + _EMA_ALPHA * v)
                if update_norms is not None:
                    v = float(update_norms[i])
                    if v == v:
                        rec.update_norm_ema = (
                            v if rec.update_norm_ema is None else
                            (1 - _EMA_ALPHA) * rec.update_norm_ema
                            + _EMA_ALPHA * v)
                if nonfinite is not None and float(nonfinite[i]) > 0:
                    rec.nonfinite_rounds += 1
                if staleness is not None:
                    s = float(staleness[i])
                    rec.staleness_sum += s
                    rec.staleness_max = max(rec.staleness_max, s)
                    self._staleness_hist.observe(s)
                if bytes_down_per_client:
                    rec.bytes_down += int(bytes_down_per_client)
                if bytes_up_per_client:
                    rec.bytes_up += int(bytes_up_per_client)
                    self._bytes_hist.observe(float(bytes_up_per_client))
            # fleet-level staleness with no per-client alignment (the
            # buffered-async event's consumed-update staleness list)
            for s in _iter(staleness_pool):
                self._staleness_hist.observe(float(s))
            for cid in _iter(failed_ids):
                rec = self._records.get(int(cid))
                if rec is not None:
                    rec.failed_rounds += 1
            # quarantine standing: a strike is the False->True transition,
            # a release the True->False one (matching the simulation's own
            # entered/released diffing)
            for cid in _iter(quarantined_ids):
                cid = int(cid)
                rec = self._records.get(cid)
                if rec is None:
                    rec = self._records[cid] = ClientRecord(cid)
                    rec.first_seen_round = rnd
                if not rec.quarantined:
                    rec.quarantined = True
                    rec.quarantine_strikes += 1
            for cid in _iter(unquarantined_ids):
                rec = self._records.get(int(cid))
                if rec is not None and rec.quarantined:
                    rec.quarantined = False
                    rec.quarantine_releases += 1
            for cid in _iter(fault_ids):
                rec = self._records.get(int(cid))
                if rec is not None:
                    rec.fault_rounds += 1
            self.rounds_absorbed += 1
            self.last_round = max(self.last_round, rnd)
            facts = {
                "participants_new": new,
                "participation_gini": gini(
                    [r.rounds_participated for r in self._records.values()]
                ),
                "straggler_p99": self._gap_sketch.quantile(0.99),
            }
        return facts

    # -- queries ----------------------------------------------------------
    def __len__(self) -> int:
        with self._lock:
            return len(self._records)

    def get(self, client_id: int) -> "dict | None":
        """One client's lifetime record (JSON-safe), or None if never seen
        — backs the ``/clients/<id>`` endpoint."""
        with self._lock:
            rec = self._records.get(int(client_id))
            if rec is None:
                return None
            doc = rec.to_doc()
            doc["suspect_score"] = rec.suspect_score()
            doc["straggler_score"] = rec.straggler_score(self.last_round)
            return doc

    def top_stragglers(self, k: int = 5) -> list[dict]:
        with self._lock:
            ranked = sorted(
                self._records.values(),
                key=lambda r: (-r.straggler_score(self.last_round),
                               r.client_id),
            )[:max(0, int(k))]
            return [
                {"client": r.client_id,
                 "score": round(r.straggler_score(self.last_round), 3),
                 "last_seen_round": r.last_seen_round,
                 "rounds_participated": r.rounds_participated}
                for r in ranked
            ]

    def top_suspects(self, k: int = 5) -> list[dict]:
        with self._lock:
            ranked = sorted(
                (r for r in self._records.values() if r.suspect_score() > 0),
                key=lambda r: (-r.suspect_score(), r.client_id),
            )[:max(0, int(k))]
            return [
                {"client": r.client_id,
                 "score": round(r.suspect_score(), 3),
                 "nonfinite_rounds": r.nonfinite_rounds,
                 "quarantine_strikes": r.quarantine_strikes,
                 "fault_rounds": r.fault_rounds,
                 "quarantined": r.quarantined}
                for r in ranked
            ]

    def summary(self, top: int = 5) -> dict:
        """The ``/fleet`` endpoint body: fleet-level standing at a glance."""
        with self._lock:
            counts = [r.rounds_participated for r in self._records.values()]
            quarantined = sum(1 for r in self._records.values()
                              if r.quarantined)
            never_sampled = (None if self.registry_size is None
                             else max(0, self.registry_size
                                      - len(self._records)))
            out = {
                "rounds_absorbed": self.rounds_absorbed,
                "last_round": self.last_round,
                "clients_seen": len(self._records),
                "registry_size": self.registry_size,
                "never_sampled": never_sampled,
                "quarantined_now": quarantined,
                "participation": {
                    "gini": gini(counts),
                    "mean_rounds": (float(np.mean(counts)) if counts
                                    else None),
                    "max_rounds": (int(max(counts)) if counts else None),
                },
                "loss": self._loss_sketch.summary(),
                "participation_gap_rounds": self._gap_sketch.summary(),
                "staleness": self._staleness_hist.summary(),
                "update_bytes": self._bytes_hist.summary(),
                "ledger_bytes": self._nbytes_locked(),
            }
        # ranked views take the lock themselves
        out["top_stragglers"] = self.top_stragglers(top)
        out["top_suspects"] = self.top_suspects(top)
        return out

    # -- memory accounting -------------------------------------------------
    def _nbytes_locked(self) -> int:
        per_rec = 16 * len(ClientRecord.__slots__) + 64
        return (len(self._records) * per_rec
                + self._loss_sketch.nbytes() + self._gap_sketch.nbytes()
                + self._staleness_hist.nbytes() + self._bytes_hist.nbytes())

    def nbytes(self) -> int:
        """Approximate host bytes held — O(participated), pinned
        registry-size-invariant by the fleet tests."""
        with self._lock:
            return self._nbytes_locked()

    # -- durability --------------------------------------------------------
    def snapshot(self) -> dict:
        """JSON-safe state for the checkpoint frame's host header."""
        with self._lock:
            return {
                "version": 1,
                "rounds_absorbed": self.rounds_absorbed,
                "last_round": self.last_round,
                "registry_size": self.registry_size,
                "clients": [r.to_doc() for r in self._records.values()],
                "sketches": {
                    "loss": self._loss_sketch.snapshot(),
                    "gap": self._gap_sketch.snapshot(),
                    "staleness": self._staleness_hist.snapshot(),
                    "bytes": self._bytes_hist.snapshot(),
                },
            }

    def restore(self, doc: "Mapping[str, Any] | None") -> None:
        """Adopt a ``snapshot()`` dict (checkpoint resume / rollback).
        ``None`` or a legacy frame without fleet state clears the ledger."""
        with self._lock:
            self._restore_locked(doc)

    def _restore_locked(self, doc: "Mapping[str, Any] | None") -> None:
        self._records = {}
        self._loss_sketch = QuantileSketch(k=self._sketch_k)
        self._gap_sketch = QuantileSketch(k=self._sketch_k)
        self._staleness_hist = FixedHistogram(_STALENESS_BOUNDS)
        self._bytes_hist = FixedHistogram(_BYTES_BOUNDS)
        self.rounds_absorbed = 0
        self.last_round = -1
        self.registry_size = None
        if not doc:
            return
        self.rounds_absorbed = int(doc.get("rounds_absorbed", 0))
        self.last_round = int(doc.get("last_round", -1))
        rs = doc.get("registry_size")
        self.registry_size = None if rs is None else int(rs)
        for cd in doc.get("clients") or []:
            rec = ClientRecord.from_doc(cd)
            self._records[rec.client_id] = rec
        sk = doc.get("sketches") or {}
        if sk.get("loss"):
            self._loss_sketch = QuantileSketch.restore(sk["loss"])
        if sk.get("gap"):
            self._gap_sketch = QuantileSketch.restore(sk["gap"])
        if sk.get("staleness"):
            self._staleness_hist = FixedHistogram.restore(sk["staleness"])
        if sk.get("bytes"):
            self._bytes_hist = FixedHistogram.restore(sk["bytes"])

    def clear(self) -> None:
        with self._lock:
            self._restore_locked(None)

"""Streaming sketches for fleet-scale distributions.

The fleet ledger (``observability/fleet.py``) answers per-client
questions; the questions that need a DISTRIBUTION over the whole fleet
("what does the p99 participation gap look like?", "how skewed are the
per-client losses?") must not cost O(registry) host memory in a 1M–10M
client regime (ROADMAP items 1 and 3; FedJAX's stated scale,
arXiv:2108.02117). This module holds the two primitives that keep those
answers registry-size-invariant:

- :class:`QuantileSketch` — a deterministic KLL-style compacting sketch
  (Karnin–Lang–Liberty, arXiv:1603.05346 in spirit; simplified fixed-``k``
  levels). Every level holds at most ``k`` values; a full level sorts,
  keeps alternating survivors (offset flips per compaction — deterministic,
  no RNG so two identical streams produce bit-identical sketches) and
  promotes them one level up at double weight. Memory is
  O(k · log(n / k)); quantile error is a few percent at the default
  ``k=128``, which is diagnostic-grade, not billing-grade.
- :class:`FixedHistogram` — plain fixed-bucket counting (Prometheus
  semantics: cumulative-free bucket counts + a +Inf overflow), for
  distributions whose interesting range is known a priori (bytes,
  staleness in rounds).

Both are JSON-snapshot round-trippable (``snapshot()`` / ``restore()``)
so the fleet ledger can carry them through the PR 12 frame writer's
host header, and mergeable (``merge()``) so multi-process fleets can be
unioned offline. Pure host-side stdlib + numpy — nothing here touches a
device.
"""

from __future__ import annotations

import math
from typing import Iterable, Sequence

import numpy as np

DEFAULT_K = 128


class QuantileSketch:
    """Deterministic streaming quantile sketch with bounded memory.

    ``add`` is O(1) amortized; ``quantile`` is O(stored · log stored)
    where ``stored ≤ k · levels``. Two sketches fed the same value
    sequence are bit-identical (compaction survivors are chosen by a
    per-level parity counter, never by randomness), which is what lets
    the fleet ledger stay inside the simulation's ledger-on ==
    ledger-off bit-identity pin.
    """

    def __init__(self, k: int = DEFAULT_K):
        if k < 8:
            raise ValueError(f"QuantileSketch k must be >= 8; got {k}")
        self.k = int(k)
        # levels[i] holds values of weight 2**i, unsorted until compaction
        self._levels: list[list[float]] = [[]]
        # per-level compaction parity: which alternation offset survives
        self._parity: list[int] = [0]
        self.count = 0
        self._min = math.inf
        self._max = -math.inf

    def add(self, value: float) -> None:
        v = float(value)
        if math.isnan(v):
            return
        self.count += 1
        if v < self._min:
            self._min = v
        if v > self._max:
            self._max = v
        self._levels[0].append(v)
        if len(self._levels[0]) >= self.k:
            self._compact(0)

    def extend(self, values: Iterable[float]) -> None:
        for v in values:
            self.add(v)

    def _compact(self, level: int) -> None:
        while level < len(self._levels) and len(self._levels[level]) >= self.k:
            buf = sorted(self._levels[level])
            offset = self._parity[level] & 1
            self._parity[level] += 1
            survivors = buf[offset::2]
            self._levels[level] = []
            if level + 1 == len(self._levels):
                self._levels.append([])
                self._parity.append(0)
            self._levels[level + 1].extend(survivors)
            level += 1

    def quantile(self, q: float) -> float | None:
        """Approximate ``q``-quantile of everything added so far."""
        if self.count == 0:
            return None
        q = min(1.0, max(0.0, float(q)))
        pairs: list[tuple[float, int]] = []
        for lvl, buf in enumerate(self._levels):
            w = 1 << lvl
            pairs.extend((v, w) for v in buf)
        pairs.sort(key=lambda p: p[0])
        total = sum(w for _, w in pairs)
        target = q * total
        acc = 0
        for v, w in pairs:
            acc += w
            if acc >= target:
                return v
        return pairs[-1][0]

    def quantiles(self, qs: Sequence[float]) -> list[float | None]:
        return [self.quantile(q) for q in qs]

    @property
    def min(self) -> float | None:
        return None if self.count == 0 else self._min

    @property
    def max(self) -> float | None:
        return None if self.count == 0 else self._max

    def stored(self) -> int:
        """Values held right now — the memory bound under test."""
        return sum(len(buf) for buf in self._levels)

    def nbytes(self) -> int:
        return self.stored() * 8 + len(self._levels) * 16 + 64

    def merge(self, other: "QuantileSketch") -> None:
        """Fold ``other`` into this sketch (level-wise union + recompact)."""
        for lvl, buf in enumerate(other._levels):
            while lvl >= len(self._levels):
                self._levels.append([])
                self._parity.append(0)
            self._levels[lvl].extend(buf)
            if len(self._levels[lvl]) >= self.k:
                self._compact(lvl)
        self.count += other.count
        self._min = min(self._min, other._min)
        self._max = max(self._max, other._max)

    def snapshot(self) -> dict:
        return {
            "k": self.k,
            "count": self.count,
            "min": None if self.count == 0 else self._min,
            "max": None if self.count == 0 else self._max,
            "levels": [list(buf) for buf in self._levels],
            "parity": list(self._parity),
        }

    @classmethod
    def restore(cls, doc: dict) -> "QuantileSketch":
        sk = cls(k=int(doc.get("k", DEFAULT_K)))
        sk.count = int(doc.get("count", 0))
        levels = doc.get("levels") or [[]]
        sk._levels = [[float(v) for v in buf] for buf in levels]
        sk._parity = [int(p) for p in (doc.get("parity") or [0] * len(sk._levels))]
        while len(sk._parity) < len(sk._levels):
            sk._parity.append(0)
        sk._min = math.inf if doc.get("min") is None else float(doc["min"])
        sk._max = -math.inf if doc.get("max") is None else float(doc["max"])
        return sk

    def summary(self) -> dict:
        """The JSON shape the ``/fleet`` endpoint serves for a metric."""
        if self.count == 0:
            return {"count": 0}
        p50, p90, p99 = self.quantiles((0.5, 0.9, 0.99))
        return {
            "count": self.count,
            "min": self._min,
            "max": self._max,
            "p50": p50,
            "p90": p90,
            "p99": p99,
        }


class FixedHistogram:
    """Fixed-bucket histogram: O(buckets) memory, exact counts.

    ``bounds`` are upper bucket edges (ascending); values above the last
    edge land in the +Inf overflow bucket. Counts are exact (unlike the
    sketch) so it suits ranges that are known up front — wire bytes,
    staleness measured in rounds.
    """

    def __init__(self, bounds: Sequence[float]):
        b = [float(x) for x in bounds]
        if not b or any(b[i] >= b[i + 1] for i in range(len(b) - 1)):
            raise ValueError(f"histogram bounds must be ascending; got {bounds}")
        self.bounds = b
        self.counts = [0] * (len(b) + 1)  # last = +Inf overflow
        self.total = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        v = float(value)
        if math.isnan(v):
            return
        self.counts[self._bucket(v)] += 1
        self.total += 1
        self.sum += v

    def _bucket(self, v: float) -> int:
        # Prometheus "le" semantics: a value equal to an edge belongs to
        # that edge's bucket, so search with bisect_left on the edges.
        for i, edge in enumerate(self.bounds):
            if v <= edge:
                return i
        return len(self.bounds)

    def quantile(self, q: float) -> float | None:
        """Bucket-resolution quantile (upper edge of the target bucket)."""
        if self.total == 0:
            return None
        target = min(1.0, max(0.0, float(q))) * self.total
        acc = 0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= target:
                return self.bounds[i] if i < len(self.bounds) else math.inf
        return math.inf

    def nbytes(self) -> int:
        return (len(self.bounds) + len(self.counts)) * 8 + 64

    def merge(self, other: "FixedHistogram") -> None:
        if other.bounds != self.bounds:
            raise ValueError("cannot merge histograms with different bounds")
        self.counts = [a + b for a, b in zip(self.counts, other.counts)]
        self.total += other.total
        self.sum += other.sum

    def snapshot(self) -> dict:
        return {
            "bounds": list(self.bounds),
            "counts": list(self.counts),
            "total": self.total,
            "sum": self.sum,
        }

    @classmethod
    def restore(cls, doc: dict) -> "FixedHistogram":
        h = cls(doc["bounds"])
        counts = [int(c) for c in doc.get("counts", [])]
        if len(counts) == len(h.counts):
            h.counts = counts
        h.total = int(doc.get("total", 0))
        h.sum = float(doc.get("sum", 0.0))
        return h

    def summary(self) -> dict:
        if self.total == 0:
            return {"count": 0}
        return {
            "count": self.total,
            "mean": self.sum / self.total,
            "p50": self.quantile(0.5),
            "p99": self.quantile(0.99),
        }


def gini(counts: "Sequence[int] | np.ndarray") -> float | None:
    """Gini coefficient of a participation-count vector (0 = perfectly
    even, →1 = one client does everything). Computed over the SEEN
    clients only — never-sampled clients are reported as their own count
    by the ledger, not folded in here (that would make the coefficient
    O(registry) to even define)."""
    arr = np.asarray(counts, dtype=np.float64)
    if arr.size == 0:
        return None
    total = arr.sum()
    if total <= 0:
        return 0.0
    arr = np.sort(arr)
    n = arr.size
    # standard rank formulation: G = (2·Σ i·x_i)/(n·Σ x) − (n+1)/n
    ranks = np.arange(1, n + 1, dtype=np.float64)
    return float((2.0 * (ranks * arr).sum()) / (n * total) - (n + 1.0) / n)

"""In-graph round telemetry — training-health metrics that ride the round
programs.

PR 1's observability is host-side (spans, fences, compile counters), which
is why enabling it used to force ``fit()`` off the chunked-scan fast path:
per-round spans only mean something with per-round dispatch. The FedJAX
lesson (PAPERS.md, arXiv:2108.02117) is that federated *diagnostics* belong
INSIDE the compiled computation, as extra outputs of the round function —
then observability is a property of the program, not a tax on the driver
loop:

- on the pipelined path the :class:`RoundTelemetry` pytree rides the
  ``RoundConsumer``'s existing fused device->host transfer (zero extra
  syncs);
- on the chunked path it is a stacked per-round ``lax.scan`` output,
  materialized by the run's single fused pull.

Everything here is computed from values the round program already holds
(losses, gradients, parameter stacks), so a telemetry-on run's loss
trajectory is BIT-IDENTICAL to a telemetry-off run
(tests/observability/test_telemetry.py pins this on both execution modes).

Field provenance:

- ``train_loss`` / ``train_loss_min`` / ``train_loss_max`` — per-client
  backward-loss mean over local steps (the meter value) and the in-scan
  min/max accumulated by ``clients/engine.py`` when telemetry is on;
- ``grad_norm_mean`` / ``grad_norm_max`` — per-client global norm of the
  post-``transform_gradients`` gradient (what the optimizer actually sees,
  SCAFFOLD correction included), accumulated across local steps;
- ``update_norm`` — ``||params_after_finalize - pulled_globals||`` per
  client (the SCAFFOLD-style drift statistic; near-zero flags a dead
  client);
- ``clip_fraction`` — fraction of examples clipped by the DP path
  (exported by ``kernels/dp_clip.py`` / ``privacy/dpsgd.py``); NaN when the
  client logic has no DP clipping;
- ``nonfinite_params`` / ``nonfinite_loss`` — per-client counts of
  non-finite (NaN/Inf) entries in the post-fit parameter stack and the
  per-client training losses;
- ``divergence`` — ``||client_params - global||`` of each client's stack
  from the freshly aggregated global (the strategy's
  ``divergence_reference``), including never-exchanged personal subtrees
  (personalization drift is signal, not noise);
- ``nonfinite_eval_loss`` — per-client count of non-finite evaluation
  losses, filled in by the eval round program.

The :class:`~fl4health_tpu.observability.health.HealthWatchdog` consumes
the host copy of this pytree in the consumer thread.
"""

from __future__ import annotations

from typing import Any, Mapping

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

# Per-client [C] fields a RoundTelemetry always carries, in a stable order
# (the JSONL `telemetry` event and the host summaries iterate this).
TELEMETRY_FIELDS = (
    "train_loss",
    "train_loss_min",
    "train_loss_max",
    "grad_norm_mean",
    "grad_norm_max",
    "update_norm",
    "clip_fraction",
    "nonfinite_params",
    "nonfinite_loss",
    "divergence",
    "nonfinite_eval_loss",
)


@struct.dataclass
class RoundTelemetry:
    """Per-client ([clients]-shaped) training-health metrics for one round.

    A plain pytree: rides ``jax.device_get`` / ``lax.scan`` stacking
    unchanged. Fields for statistics a particular training path cannot
    produce (e.g. grad norms under the flash early-stop train, clip
    fraction without DP) are NaN, never absent — the pytree structure is
    static for the life of the compiled program.
    """

    train_loss: jax.Array
    train_loss_min: jax.Array
    train_loss_max: jax.Array
    grad_norm_mean: jax.Array
    grad_norm_max: jax.Array
    update_norm: jax.Array
    clip_fraction: jax.Array
    nonfinite_params: jax.Array
    nonfinite_loss: jax.Array
    divergence: jax.Array
    nonfinite_eval_loss: jax.Array
    # [C] cumulative loss-scale skipped-step counts — present only when the
    # precision policy scales (fp16 dynamic/static); None is an empty
    # pytree node, so legacy telemetry records keep their exact structure
    loss_scale_skips: Any = None

    def as_dict(self) -> dict[str, Any]:
        d = {k: getattr(self, k) for k in TELEMETRY_FIELDS}
        if self.loss_scale_skips is not None:
            d["loss_scale_skips"] = self.loss_scale_skips
        return d


# ---------------------------------------------------------------------------
# In-graph helpers (jit-traceable; called from the round programs)
# ---------------------------------------------------------------------------

def per_client_nonfinite(stacked_tree: Any) -> jax.Array:
    """[C]-leading pytree -> [C] count of non-finite entries.

    Integer/bool leaves cannot be non-finite and are skipped (``isfinite``
    is undefined for them in jax)."""
    total = None
    for leaf in jax.tree_util.tree_leaves(stacked_tree):
        if not jnp.issubdtype(leaf.dtype, jnp.inexact):
            continue
        bad = jnp.sum(
            (~jnp.isfinite(leaf)).reshape(leaf.shape[0], -1).astype(jnp.float32),
            axis=1,
        )
        total = bad if total is None else total + bad
    if total is None:
        raise ValueError("per_client_nonfinite: tree has no floating leaves")
    return total


def nonfinite_in_losses(losses: Mapping[str, jax.Array]) -> jax.Array:
    """Dict of [C] loss arrays -> [C] count of non-finite values."""
    vals = [jnp.asarray(v, jnp.float32) for v in losses.values()]
    stacked = jnp.stack(vals) if vals else jnp.zeros((1, 1), jnp.float32)
    return jnp.sum((~jnp.isfinite(stacked)).astype(jnp.float32), axis=0)


def per_client_divergence(stacked_params: Any, ref_params: Any) -> jax.Array:
    """[C]-leading client param stack vs an unstacked reference ->
    [C] global l2 distance. Non-float leaves (integer masks) are cast to
    f32 so e.g. FedPM score trees still measure."""
    total = jnp.zeros((), jnp.float32)
    for leaf, ref in zip(
        jax.tree_util.tree_leaves(stacked_params),
        jax.tree_util.tree_leaves(ref_params),
    ):
        d = leaf.astype(jnp.float32) - ref.astype(jnp.float32)[None]
        total = total + jnp.sum(
            jnp.square(d).reshape(d.shape[0], -1), axis=1
        )
    return jnp.sqrt(total)


def global_norm_diff(a: Any, b: Any) -> jax.Array:
    """||a - b|| over two same-structure pytrees (scalar). Used per client
    (inside vmap) for the update-norm statistic."""
    total = jnp.zeros((), jnp.float32)
    for la, lb in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        d = la.astype(jnp.float32) - lb.astype(jnp.float32)
        total = total + jnp.sum(jnp.square(d))
    return jnp.sqrt(total)


def nan_engine_telemetry() -> dict[str, jax.Array]:
    """Engine-stat placeholder for train paths that cannot accumulate them
    (the flash early-stop train): structure-stable NaNs."""
    nan = jnp.asarray(jnp.nan, jnp.float32)
    return {
        "train_loss_min": nan,
        "train_loss_max": nan,
        "grad_norm_mean": nan,
        "grad_norm_max": nan,
    }


# ---------------------------------------------------------------------------
# Host-side summaries (consumer thread / chunked epilogue; pure numpy)
# ---------------------------------------------------------------------------

def _participating(values: np.ndarray, mask: np.ndarray) -> np.ndarray:
    v = np.asarray(values, np.float64)
    return v[np.asarray(mask) > 0]


def _nan_stat(fn, values: np.ndarray) -> float:
    """Reduce ignoring NaN; empty/all-NaN -> nan (never a numpy warning)."""
    v = values[np.isfinite(values)]
    return float(fn(v)) if v.size else float("nan")


def summarize_host(telemetry: Mapping[str, np.ndarray], mask) -> dict[str, float]:
    """Scalar summary of a host-side telemetry dict over PARTICIPATING
    clients — the fields merged into the per-round JSONL ``round`` event
    and rendered by ``tools/perf_report.py``."""
    t = {k: _participating(np.asarray(v), mask) for k, v in telemetry.items()}
    nonfinite = (
        float(np.sum(t["nonfinite_params"]))
        + float(np.sum(t["nonfinite_loss"]))
        + float(np.sum(t["nonfinite_eval_loss"]))
    )
    out = {
        "train_loss_min": _nan_stat(np.min, t["train_loss_min"]),
        "train_loss_max": _nan_stat(np.max, t["train_loss_max"]),
        "grad_norm_mean": _nan_stat(np.mean, t["grad_norm_mean"]),
        "grad_norm_max": _nan_stat(np.max, t["grad_norm_max"]),
        "update_norm_mean": _nan_stat(np.mean, t["update_norm"]),
        "update_norm_min": _nan_stat(np.min, t["update_norm"]),
        "clip_fraction": _nan_stat(np.mean, t["clip_fraction"]),
        "nonfinite": nonfinite,
        "divergence_mean": _nan_stat(np.mean, t["divergence"]),
        "divergence_max": _nan_stat(np.max, t["divergence"]),
    }
    if "loss_scale_skips" in telemetry:
        # fp16 loss-scaling runs only (key absent otherwise, so legacy
        # round events keep their exact shape). Summed over ALL clients,
        # NOT the participating filter: the per-client counters are
        # cumulative, so the all-client sum is monotone and its last value
        # IS the run-wide skipped-step total — a participant-filtered sum
        # would re-count or drop history as the sampled cohort changes.
        out["loss_scale_skips"] = float(np.sum(
            np.asarray(telemetry["loss_scale_skips"], np.float64)
        ))
    return out

"""Cross-silo trace context — correlate coordinator and silo spans.

The coordinator (``transport/coordinator.py``) and each silo run in
separate processes with separate :class:`~fl4health_tpu.observability.spans.Tracer`
instances, so their per-process traces are disjoint timelines. This
module carries a tiny trace context *inside* the RPC frame header
(``transport/codec.py`` adds a ``"trace"`` key next to ``"leaves"``)
so a silo's handler spans can be stamped with the coordinator's trace
id and round, and both sides can emit Chrome *flow events* sharing a
deterministic id. ``tools/trace_merge.py`` then stitches the per-process
trace files onto one wall-clock axis and Perfetto draws arrows
broadcast → silo handler → reply across the process boundary.

Design constraints honoured here:

- **Byte-stable when unused.** ``encode(tree)`` without a trace emits
  exactly the frames it always did; the context only rides along when
  the coordinator's tracer is enabled.
- **Deterministic flow ids.** The coordinator encodes each round's
  broadcast frame ONCE for all silos, so the flow id cannot vary per
  silo; it is a stable hash of ``(trace_id, round)``. All silos' reply
  arrows share the round's flow, which is exactly the fan-out/fan-in
  structure being visualised.
- **Stdlib only.** Ids come from ``os.urandom`` (no RNG state touched —
  trajectory bit-identity is unaffected).
"""

from __future__ import annotations

import hashlib
import os
from dataclasses import dataclass
from typing import Any, Callable, Mapping

from fl4health_tpu.observability.spans import get_tracer

__all__ = [
    "TraceContext",
    "flow_id",
    "new_trace_id",
    "traced_handler",
]


def new_trace_id() -> str:
    """A fresh 64-bit trace id as 16 hex chars. ``os.urandom`` keeps the
    simulation's seeded RNG streams untouched."""
    return os.urandom(8).hex()


@dataclass(frozen=True)
class TraceContext:
    """What travels in the frame header: enough to correlate, nothing
    more. ``trace_id`` names the run (one per coordinator process),
    ``span_id`` names the emitting operation, ``round`` the FL round the
    frame belongs to."""

    trace_id: str
    span_id: str
    round: int

    @classmethod
    def fresh(cls, round: int, trace_id: str | None = None) -> "TraceContext":
        return cls(
            trace_id=trace_id if trace_id is not None else new_trace_id(),
            span_id=new_trace_id(),
            round=int(round),
        )

    def child(self) -> "TraceContext":
        """Same trace, new span id — what a handler stamps on its reply."""
        return TraceContext(self.trace_id, new_trace_id(), self.round)

    # -- wire form (JSON-safe dict inside the codec header) --------------
    def to_header(self) -> dict[str, Any]:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "round": self.round}

    @classmethod
    def from_header(cls, doc: Mapping[str, Any] | None) -> "TraceContext | None":
        """Parse the header dict; tolerant of absent/malformed input
        (an untraced or foreign frame simply yields no context)."""
        if not isinstance(doc, Mapping):
            return None
        try:
            return cls(str(doc["trace_id"]), str(doc["span_id"]),
                       int(doc["round"]))
        except (KeyError, TypeError, ValueError):
            return None


def flow_id(trace_id: str, round: int) -> int:
    """Deterministic Chrome flow-event id for one round of one trace.
    Both sides of the RPC derive the same id from header fields alone, so
    no extra bytes travel on the wire. 63-bit to stay a positive JSON
    int."""
    digest = hashlib.blake2b(
        f"{trace_id}:{round}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big") & 0x7FFFFFFFFFFFFFFF


def traced_handler(
    handler: Callable[[bytes], bytes], name: str = "silo_handle"
) -> Callable[[bytes], bytes]:
    """Wrap a silo-side ``bytes -> bytes`` RPC handler (the callable a
    ``LoopbackServer`` serves) so each request runs inside a tracer span
    stamped with the coordinator's trace context, emitting the flow-step
    (``"t"``) event that links the coordinator's broadcast arrow into
    this process's timeline.

    Frames without a trace header (tracer disabled coordinator-side, or
    a non-codec payload) run the handler untraced — the wrapper never
    changes the reply bytes either way."""
    from fl4health_tpu.transport.codec import frame_trace

    def wrapped(data: bytes) -> bytes:
        ctx = TraceContext.from_header(frame_trace(data))
        tracer = get_tracer()
        if ctx is None or not tracer.enabled:
            return handler(data)
        with tracer.span(
            name, cat="transport", trace_id=ctx.trace_id,
            parent_span=ctx.span_id, round=ctx.round,
            request_bytes=len(data),
        ) as sp:
            tracer.flow("t", "rpc_flow", flow_id(ctx.trace_id, ctx.round),
                        round=ctx.round)
            reply = handler(data)
            sp.set(reply_bytes=len(reply))
            return reply

    return wrapped

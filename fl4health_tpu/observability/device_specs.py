"""Published per-chip peak specs — the denominators for MFU and roofline.

XLA's ``cost_analysis()`` gives the numerator (FLOPs, bytes accessed per
compiled program); turning that into "how close to the hardware are we"
needs the chip's peak matmul throughput, HBM capacity and HBM bandwidth.
This table holds the published numbers keyed by JAX's ``device_kind``
string, normalized so v5e/"v5 lite"-style aliases resolve to one entry.

Capacity prefers the *live* number: ``device.memory_stats()["bytes_limit"]``
is what the runtime will actually let a program allocate (it accounts for
reserved framework memory); the spec byte count is the fallback when the
backend exposes no stats (CPU, some plugin builds).

No module-level ``jax`` import: ``bench.py`` and the exposition endpoint
import this before/without touching the backend.
"""

from __future__ import annotations

import dataclasses

GIB = 1024**3


@dataclasses.dataclass(frozen=True)
class DeviceSpec:
    """Published per-chip peaks (dense, per-device)."""

    peak_bf16_flops: float  # matmul peak, FLOP/s
    hbm_bytes: int          # on-chip high-bandwidth memory capacity
    hbm_bw_bytes_per_s: float  # HBM bandwidth (roofline ridge denominator)


# Keyed by normalized device_kind (see _normalize). Sources: published TPU
# spec sheets; the bf16 peaks match the table bench.py has carried since r1.
DEVICE_SPECS: dict[str, DeviceSpec] = {
    "TPU v2": DeviceSpec(45e12, 8 * GIB, 700e9),
    "TPU v3": DeviceSpec(123e12, 16 * GIB, 900e9),
    "TPU v4": DeviceSpec(275e12, 32 * GIB, 1228e9),
    "TPU v5e": DeviceSpec(197e12, 16 * GIB, 819e9),
    "TPU v5p": DeviceSpec(459e12, 95 * GIB, 2765e9),
    "TPU v6e": DeviceSpec(918e12, 32 * GIB, 1640e9),
}

# device_kind spellings observed in the wild -> canonical table key.
_ALIASES = {
    "TPU v5 lite": "TPU v5e",
    "TPU v5litepod": "TPU v5e",
    "TPU v5": "TPU v5p",
    "TPU v6 lite": "TPU v6e",
    "TPU v6": "TPU v6e",
}


def _normalize(device_kind: str | None) -> str | None:
    if not device_kind:
        return None
    kind = device_kind.strip()
    return _ALIASES.get(kind, kind)


def lookup(device_kind: str | None) -> DeviceSpec | None:
    """Spec for a ``device_kind`` string, or None when unknown (CPU, new
    chips the table hasn't learned yet — callers must treat peaks as
    unavailable rather than guessing)."""
    kind = _normalize(device_kind)
    return DEVICE_SPECS.get(kind) if kind else None


def peak_bf16_flops(device_kind: str | None) -> float | None:
    spec = lookup(device_kind)
    return spec.peak_bf16_flops if spec else None


def device_memory_bytes(device=None) -> int | None:
    """Usable device memory in bytes: the runtime's live ``bytes_limit``
    when exposed, else the spec-table capacity, else None (CPU)."""
    if device is None:
        import jax

        device = jax.devices()[0]
    try:
        stats = device.memory_stats()
    except Exception:
        stats = None
    if stats and stats.get("bytes_limit"):
        return int(stats["bytes_limit"])
    spec = lookup(getattr(device, "device_kind", None))
    return spec.hbm_bytes if spec else None


def mfu_pct(achieved_flops_per_s: float, device_kind: str | None) -> float | None:
    """Achieved FLOP/s as a percent of the chip's bf16 peak; None when the
    peak is unknown (never report a made-up MFU)."""
    peak = peak_bf16_flops(device_kind)
    if not peak or achieved_flops_per_s is None:
        return None
    return 100.0 * achieved_flops_per_s / peak


def roofline(flops: float | None, bytes_accessed: float | None,
             device_kind: str | None) -> dict | None:
    """Roofline position of one program: arithmetic intensity (FLOPs per
    HBM byte) against the chip's ridge point (peak FLOP/s ÷ HBM BW). A
    program left of the ridge is bandwidth-bound — more MFU requires less
    memory traffic, not more compute. Returns None without both numerators.
    """
    if not flops or not bytes_accessed:
        return None
    intensity = flops / bytes_accessed
    spec = lookup(device_kind)
    out = {"intensity_flops_per_byte": intensity}
    if spec:
        ridge = spec.peak_bf16_flops / spec.hbm_bw_bytes_per_s
        out["ridge_flops_per_byte"] = ridge
        out["compute_bound"] = intensity >= ridge
    return out

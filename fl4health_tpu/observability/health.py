"""Training-health watchdog — declarative policy over in-graph telemetry.

The reference stack surfaces training failures as log lines (or not at
all: a silently-diverging client just degrades the aggregate). Here the
:class:`HealthWatchdog` consumes each round's host copy of
:class:`~fl4health_tpu.observability.telemetry.RoundTelemetry` — in the
``RoundConsumer`` thread on the pipelined path, in the post-run epilogue
on the chunked path — evaluates a :class:`HealthPolicy`, and:

- sets per-check Prometheus gauges / counters in the run's registry,
- appends one ``health`` event per round to the JSONL log,
- bridges the health summary to every reporter,
- and, for checks whose action is ``"halt"``, terminates ``fit()`` with a
  :class:`TrainingHealthError` naming the round and the offending clients.

On the chunked path the whole run has already executed on device when the
watchdog sees round *r*'s telemetry (one dispatch covers every round), so
"halt" there means "fail the fit() call loudly with the first offending
round" rather than "stop mid-run" — the structured error is identical.
Host-side only, pure numpy: safe on the consumer thread.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
from typing import Any, Mapping, Sequence

import numpy as np

logger = logging.getLogger(__name__)

_ACTIONS = ("halt", "warn", "mitigate", "off")

HALT = "halt"
WARN = "warn"
MITIGATE = "mitigate"
OFF = "off"


class TrainingHealthError(RuntimeError):
    """Raised by the watchdog when a ``halt`` check trips.

    Attributes: ``round`` (1-based federated round), ``clients`` (offending
    client indices; empty for cohort-level checks), ``check`` (policy check
    name).
    """

    def __init__(self, message: str, *, round: int, clients: Sequence[int],
                 check: str):
        super().__init__(message)
        self.round = int(round)
        self.clients = [int(c) for c in clients]
        self.check = check


@dataclasses.dataclass(frozen=True)
class HealthPolicy:
    """Declarative thresholds; each check carries its own action
    (``"halt"`` | ``"warn"`` | ``"mitigate"`` | ``"off"``).

    ``"mitigate"`` (resilience subsystem) masks the offending clients out
    of subsequent rounds instead of halting: the watchdog quarantines them
    for ``quarantine_rounds`` rounds and ``FederatedSimulation`` multiplies
    its sampling mask by :meth:`HealthWatchdog.quarantine_keep_mask` on the
    pipelined path (probation served, the client is re-admitted; a
    re-offender re-enters). Cohort-level checks with no client attribution
    (loss divergence) degrade mitigate to warn. On the chunked path the
    run has already executed when telemetry is screened — use the in-graph
    ``resilience.QuarantiningStrategy`` there.

    - **non-finite** (``on_nonfinite``): a participating client produced
      NaN/Inf in its training loss, parameter stack, or eval loss.
    - **loss divergence** (``loss_divergence_window`` > 0 enables): the
      aggregate training loss exceeded ``loss_divergence_factor`` x the
      best loss seen so far for that many CONSECUTIVE rounds.
    - **dead clients** (``dead_client_norm`` > 0 enables): a participating
      client's update norm stayed <= the threshold for
      ``dead_client_rounds`` consecutive participations (a client that
      pulls the global model and pushes it back unchanged).
    - **contribution skew** (``skew_ratio`` > 0 enables): max participating
      update norm exceeded ``skew_ratio`` x the median — one client
      dominating the aggregate (poisoning / LR misconfiguration proxy).
    """

    on_nonfinite: str = HALT
    loss_divergence_window: int = 0
    loss_divergence_factor: float = 2.0
    on_loss_divergence: str = HALT
    dead_client_norm: float = 0.0
    dead_client_rounds: int = 3
    on_dead_client: str = WARN
    skew_ratio: float = 0.0
    on_skew: str = WARN
    quarantine_rounds: int = 5

    def __post_init__(self):
        for field in ("on_nonfinite", "on_loss_divergence", "on_dead_client",
                      "on_skew"):
            v = getattr(self, field)
            if v not in _ACTIONS:
                raise ValueError(
                    f"HealthPolicy.{field} must be one of {_ACTIONS}; got {v!r}"
                )
        if self.loss_divergence_window < 0 or self.dead_client_rounds < 1:
            raise ValueError("HealthPolicy windows must be positive")
        if self.quarantine_rounds < 1:
            raise ValueError("HealthPolicy.quarantine_rounds must be >= 1")


class HealthWatchdog:
    """Stateful per-run evaluator of a :class:`HealthPolicy`.

    ``FederatedSimulation`` calls :meth:`reset` at each ``fit()`` entry and
    :meth:`observe` once per round with the host telemetry. State (loss
    best/streak, per-client dead streaks) is per-run; observation order is
    guaranteed by the single consumer thread / the chunked epilogue loop.
    """

    def __init__(self, policy: HealthPolicy | None = None):
        self.policy = policy or HealthPolicy()
        # producer thread reads the quarantine while the consumer thread
        # writes it (pipelined path) — one lock covers both
        self._quarantine_lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        self._best_loss = float("inf")
        self._divergent_rounds = 0
        self._dead_streak: dict[int, int] = {}
        with self._quarantine_lock:
            # client -> first round at which it is released again
            self._quarantine: dict[int, int] = {}

    # -- mitigation (action="mitigate") --------------------------------
    def active_quarantine(self) -> list[int]:
        """Clients currently quarantined by mitigate checks (sorted)."""
        with self._quarantine_lock:
            return sorted(self._quarantine)

    def quarantine_keep_mask(self, n_clients: int) -> "np.ndarray | None":
        """[n_clients] keep-mask (0.0 = quarantined), or None while nothing
        is quarantined — the caller's fast path multiplies nothing."""
        with self._quarantine_lock:
            if not self._quarantine:
                return None
            keep = np.ones((n_clients,), np.float32)
            for c in self._quarantine:
                if 0 <= c < n_clients:
                    keep[c] = 0.0
            return keep

    # ------------------------------------------------------------------
    def observe(
        self,
        round_idx: int,
        telemetry: Mapping[str, np.ndarray],
        mask: np.ndarray,
        agg_train_loss: float,
        obs: Any = None,
        reporters: Sequence[Any] = (),
    ) -> dict:
        """Evaluate every enabled check against one round's telemetry.

        Emits gauges + a ``health`` JSONL event through ``obs`` (an
        :class:`~fl4health_tpu.observability.Observability`, optional) and a
        ``{"health": ...}`` payload to each reporter, THEN raises
        :class:`TrainingHealthError` if any halt check tripped — the round's
        own record always lands before the run dies."""
        pol = self.policy
        mask = np.asarray(mask)
        participants = np.nonzero(mask > 0)[0]
        summary: dict[str, Any] = {"round": int(round_idx), "status": "ok"}
        problems: list[tuple[str, str, list[int], str]] = []

        # -- probation expiry (mitigate recovery) -----------------------
        released: list[int] = []
        with self._quarantine_lock:
            for c, until in list(self._quarantine.items()):
                if until <= round_idx:
                    del self._quarantine[c]
                    released.append(c)
        if released:
            logger.info(
                "health: clients %s released from quarantine at round %d "
                "(probation served)", sorted(released), round_idx,
            )

        # -- non-finite --------------------------------------------------
        if pol.on_nonfinite != OFF:
            bad_count = (
                np.asarray(telemetry["nonfinite_loss"], np.float64)
                + np.asarray(telemetry["nonfinite_params"], np.float64)
                + np.asarray(telemetry["nonfinite_eval_loss"], np.float64)
            )
            loss_mean = np.asarray(telemetry["train_loss"], np.float64)
            bad = (bad_count > 0) | ~np.isfinite(loss_mean)
            clients = [int(c) for c in participants if bad[c]]
            summary["nonfinite_clients"] = clients
            if clients:
                problems.append((
                    "nonfinite", pol.on_nonfinite, clients,
                    f"non-finite training state (NaN/Inf) in clients {clients}",
                ))

        # -- loss divergence window -------------------------------------
        if pol.loss_divergence_window > 0:
            loss = float(agg_train_loss)
            if np.isfinite(loss):
                if loss > pol.loss_divergence_factor * self._best_loss:
                    self._divergent_rounds += 1
                else:
                    self._divergent_rounds = 0
                self._best_loss = min(self._best_loss, loss)
            summary["divergent_rounds"] = self._divergent_rounds
            if self._divergent_rounds >= pol.loss_divergence_window:
                problems.append((
                    "loss_divergence", pol.on_loss_divergence, [],
                    f"aggregate train loss {loss:.4g} > "
                    f"{pol.loss_divergence_factor}x best {self._best_loss:.4g} "
                    f"for {self._divergent_rounds} consecutive rounds",
                ))

        # -- dead clients ------------------------------------------------
        if pol.dead_client_norm > 0:
            upd = np.asarray(telemetry["update_norm"], np.float64)
            dead_now = []
            for c in participants:
                c = int(c)
                if np.isfinite(upd[c]) and upd[c] <= pol.dead_client_norm:
                    self._dead_streak[c] = self._dead_streak.get(c, 0) + 1
                else:
                    self._dead_streak.pop(c, None)
                if self._dead_streak.get(c, 0) >= pol.dead_client_rounds:
                    dead_now.append(c)
            summary["dead_clients"] = dead_now
            if dead_now:
                problems.append((
                    "dead_client", pol.on_dead_client, dead_now,
                    f"clients {dead_now} pushed near-zero updates "
                    f"(norm <= {pol.dead_client_norm}) for "
                    f"{pol.dead_client_rounds} consecutive rounds",
                ))

        # -- contribution skew ------------------------------------------
        if pol.skew_ratio > 0:
            upd = np.asarray(telemetry["update_norm"], np.float64)
            live = upd[participants][np.isfinite(upd[participants])]
            if live.size >= 2:
                med = float(np.median(live))
                peak = float(np.max(live))
                # peak==0 means nobody moved — no outlier, whatever the
                # median; a zero median under a positive peak IS maximal skew
                if med > 0:
                    ratio = peak / med
                else:
                    ratio = float("inf") if peak > 0 else 0.0
                summary["update_norm_skew"] = ratio
                if ratio > pol.skew_ratio:
                    worst = [int(participants[int(np.argmax(
                        np.where(np.isfinite(upd[participants]),
                                 upd[participants], -np.inf)))])]
                    problems.append((
                        "contribution_skew", pol.on_skew, worst,
                        f"client {worst[0]} update norm {peak:.4g} is "
                        f"{ratio:.1f}x the cohort median {med:.4g} "
                        f"(> skew_ratio={pol.skew_ratio})",
                    ))

        halts = [p for p in problems if p[1] == HALT]
        warns = [p for p in problems if p[1] == WARN]
        mitigations = [p for p in problems if p[1] == MITIGATE]
        # -- mitigation: quarantine offenders instead of halting --------
        entered: list[int] = []
        for check, _action, clients, msg in mitigations:
            if not clients:
                # cohort-level checks carry no client attribution; masking
                # "nobody in particular" is a warn, not a mitigation
                logger.warning(
                    "health[%s] round %d: %s (mitigate has no client "
                    "attribution for this check — treated as warn)",
                    check, round_idx, msg,
                )
                continue
            with self._quarantine_lock:
                for c in clients:
                    c = int(c)
                    if c not in self._quarantine:
                        entered.append(c)
                    self._quarantine[c] = round_idx + pol.quarantine_rounds
            logger.warning(
                "health[%s] round %d: %s — quarantining clients %s for "
                "%d rounds", check, round_idx, msg, clients,
                pol.quarantine_rounds,
            )
        if problems:
            summary["status"] = ("halt" if halts
                                 else "mitigate" if mitigations else "warn")
            summary["checks_tripped"] = [p[0] for p in problems]
        if entered or released or self._quarantine:
            summary["quarantined_clients"] = self.active_quarantine()
            summary["released_clients"] = sorted(released)
        for check, _action, clients, msg in warns:
            logger.warning("health[%s] round %d: %s", check, round_idx, msg)

        # -- export: gauges, JSONL, reporters ---------------------------
        if obs is not None and getattr(obs, "enabled", False):
            obs.gauge(
                "fl_health_nonfinite_clients",
                help="participating clients with non-finite training state",
            ).set(float(len(summary.get("nonfinite_clients", []))))
            obs.gauge(
                "fl_health_dead_clients",
                help="clients flagged dead (near-zero update norm streak)",
            ).set(float(len(summary.get("dead_clients", []))))
            obs.gauge(
                "fl_health_divergent_rounds",
                help="consecutive rounds over the loss-divergence threshold",
            ).set(float(summary.get("divergent_rounds", 0)))
            if warns:
                obs.counter(
                    "fl_health_warnings_total",
                    help="health checks that tripped with action=warn",
                ).inc(len(warns))
            if entered or released or self._quarantine:
                # guarded like the counters below: a halt/warn-only policy
                # must not grow a new always-zero metric family
                obs.gauge(
                    "fl_quarantine_active_clients",
                    help="clients currently masked out of aggregation by "
                         "quarantine",
                ).set(float(len(self.active_quarantine())))
            if entered:
                obs.counter(
                    "fl_quarantine_entries_total",
                    help="clients entering quarantine",
                ).inc(len(entered))
            if released:
                obs.counter(
                    "fl_quarantine_releases_total",
                    help="clients released from quarantine (probation "
                         "served)",
                ).inc(len(released))
            if entered or released:
                obs.log_event(
                    "quarantine", round=int(round_idx), source="watchdog",
                    active=self.active_quarantine(),
                    entered=sorted(entered), released=sorted(released),
                )
            obs.log_event("health", **summary)
        for rep in reporters:
            rep.report({"health": dict(summary)}, round=int(round_idx))

        if halts:
            check, _action, clients, msg = halts[0]
            err = TrainingHealthError(
                f"HealthWatchdog[{check}] halted training at round "
                f"{round_idx}: {msg}",
                round=round_idx, clients=clients, check=check,
            )
            if obs is not None and getattr(obs, "enabled", False):
                # flip the live /healthz probe to 503 BEFORE the raise
                # unwinds fit() — an orchestrator polling the armed scrape
                # endpoint must not see "ok" mid-teardown
                mark = getattr(obs, "mark_unhealthy", None)
                if mark is not None:
                    mark(str(err))
            raise err
        return summary

"""Compiled-program introspection — what each XLA round program actually is.

The observability PRs so far measure the round loop from the *outside*
(wall clocks, fences, compile counters) and from *inside the graph*
(RoundTelemetry). What's still missing is the compiled program itself: how
many FLOPs does one ``fit_round`` executable perform, how many HBM bytes
does it touch, how much device memory does it pin — the per-program
accounting FedJAX (arXiv:2108.02117) treats as table stakes for credible
JAX FL simulation, and the numbers the sharding roadmap (arXiv:2004.13336)
needs before splitting those programs across replicas.

XLA exposes both through the AOT API at **build time** — zero per-round
cost:

- ``compiled.cost_analysis()``: flops, transcendentals, bytes accessed;
- ``compiled.memory_analysis()``: argument/output/temp/generated-code
  bytes (the program's device-memory footprint).

:class:`ProgramIntrospector` wraps ``jit.lower(...).compile()`` around
abstract (``ShapeDtypeStruct``) arguments, times the compile, attributes
persistent-cache hits/misses via the counters the installed
:class:`~fl4health_tpu.observability.jaxmon.CompileMonitor` already
maintains, and lands each :class:`ProgramReport` in the metrics registry
(``fl_program_*`` gauges labeled by program), the JSONL event log (one
``program`` event, rendered by ``tools/perf_report.py``), and the
``fl_hbm_headroom_bytes`` gauge (device memory minus the largest program
footprint — how much model growth fits before the next OOM).

From a report plus a measured round wall time, measured MFU is
``flops / wall / peak`` — a hardware-grounded number, unlike the analytic
formula ``bench.py`` used to report. Caveat carried over from the flash
work: a Pallas custom call's FLOPs are invisible to ``cost_analysis`` —
the analytic numerator stays the honest one for those configs.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any

from fl4health_tpu.observability import device_specs, hloscan
from fl4health_tpu.observability import stages as stage_attr
from fl4health_tpu.observability.registry import MetricsRegistry

logger = logging.getLogger(__name__)

_CACHE_HITS = "jax_persistent_cache_hits_total"
_CACHE_MISSES = "jax_persistent_cache_misses_total"


@dataclasses.dataclass
class ProgramReport:
    """One compiled XLA program's cost/memory/compile accounting.

    ``None`` fields mean the backend did not expose that analysis — callers
    must propagate the absence (a ``null`` in artifacts), never substitute
    a zero that reads as "measured: nothing"."""

    name: str
    backend: str
    device_kind: str
    # cost_analysis — WHOLE-program logical work: XLA reports per-partition
    # numbers for SPMD-partitioned (mesh) executables, so capture scales
    # them by the partition count (collective traffic is not modeled; the
    # scaled bytes are an approximation)
    flops: float | None = None
    transcendentals: float | None = None
    bytes_accessed: float | None = None
    # memory_analysis (device-memory footprint components) — deliberately
    # PER-PARTITION on a mesh: peak_hbm_bytes is each chip's footprint,
    # which is what HBM-headroom accounting needs
    argument_bytes: int | None = None
    output_bytes: int | None = None
    temp_bytes: int | None = None
    generated_code_bytes: int | None = None
    # compile accounting
    compile_seconds: float | None = None
    cache_hits: int = 0
    cache_misses: int = 0
    # a multi-round scan program executes this many rounds per dispatch
    rounds_per_dispatch: int = 1
    # cohort-draw site of a registry program ("in_graph" for the chunked
    # cohort scan); None on dense / host-drawn programs (omitted from
    # as_dict/events like ``mesh``, so legacy program records keep their
    # exact shape)
    cohort_draw: str | None = None
    # mesh/sharding descriptor (parallel.program.RoundProgramBuilder
    # .descriptor()) when the program was built for a device mesh; None on
    # single-chip builds (and omitted from as_dict/events, so legacy
    # program records keep their exact shape)
    mesh: dict | None = None
    # precision-policy descriptor (precision.PrecisionConfig.describe())
    # when the program was compiled under an active mixed-precision policy;
    # None on f32 builds (omitted from as_dict/events like ``mesh``) — the
    # dtype a program's flops/MFU numbers are attributable to
    precision: dict | None = None
    # per-stage cost attribution rows (observability/hloscan.py) when
    # fl_stage attribution is enabled and the backend exposes HLO text;
    # None otherwise (omitted from as_dict/events like ``mesh``, keeping
    # attribution-off program records byte-identical to legacy)
    stages: list | None = None

    @property
    def peak_hbm_bytes(self) -> int | None:
        """Conservative device-memory footprint of one dispatch: arguments
        + outputs + temporaries + generated code. Donated (aliased) buffers
        are counted on the argument side, so this is an upper bound."""
        parts = (self.argument_bytes, self.output_bytes, self.temp_bytes,
                 self.generated_code_bytes)
        if all(p is None for p in parts):
            return None
        return int(sum(p or 0 for p in parts))

    @property
    def flops_per_round(self) -> float | None:
        if self.flops is None:
            return None
        return self.flops / max(self.rounds_per_dispatch, 1)

    @property
    def cache_hit(self) -> bool | None:
        """True when the compile was served from the persistent cache,
        False on a real backend compile, None when no cache event fired
        (cache disabled, or the in-memory jit cache absorbed it)."""
        if self.cache_hits == 0 and self.cache_misses == 0:
            return None
        return self.cache_misses == 0

    def roofline(self) -> dict | None:
        return device_specs.roofline(self.flops, self.bytes_accessed,
                                     self.device_kind)

    def as_dict(self) -> dict[str, Any]:
        d = {f.name: getattr(self, f.name) for f in dataclasses.fields(self)}
        if d.get("mesh") is None:
            del d["mesh"]
        if d.get("precision") is None:
            del d["precision"]
        if d.get("cohort_draw") is None:
            del d["cohort_draw"]
        if d.get("stages") is None:
            del d["stages"]
        d["peak_hbm_bytes"] = self.peak_hbm_bytes
        d["cache_hit"] = self.cache_hit
        roof = self.roofline()
        if roof:
            d["roofline"] = roof
        return d


def analyze_compiled(compiled: Any, n_partitions: int = 1) -> dict[str, Any]:
    """Extract cost/memory analysis from a ``jax`` compiled executable,
    defensively: backends without a cost model yield ``None`` fields, never
    an exception (the caller may be mid-``fit``).

    ``n_partitions``: SPMD partition count of the executable (the mesh's
    device count). XLA's ``cost_analysis()`` reports ONE partition's
    flops/transcendentals/bytes for a partitioned program, so they are
    scaled back up to whole-program numbers here — otherwise every
    downstream per-chip division (MFU, tflops_per_chip) would divide by
    the device count a second time. ``memory_analysis`` is left
    per-partition on purpose (each chip's footprint)."""
    out: dict[str, Any] = {
        "flops": None, "transcendentals": None, "bytes_accessed": None,
        "argument_bytes": None, "output_bytes": None, "temp_bytes": None,
        "generated_code_bytes": None,
    }
    try:
        cost = compiled.cost_analysis()
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if cost:
            for field, key in (("flops", "flops"),
                               ("transcendentals", "transcendentals"),
                               ("bytes_accessed", "bytes accessed")):
                if key in cost:
                    out[field] = float(cost[key]) * max(n_partitions, 1)
    except Exception:
        logger.debug("cost_analysis unavailable", exc_info=True)
    try:
        mem = compiled.memory_analysis()
        if mem is not None:
            out["argument_bytes"] = int(mem.argument_size_in_bytes)
            out["output_bytes"] = int(mem.output_size_in_bytes)
            out["temp_bytes"] = int(mem.temp_size_in_bytes)
            out["generated_code_bytes"] = int(mem.generated_code_size_in_bytes)
    except Exception:
        logger.debug("memory_analysis unavailable", exc_info=True)
    return out


def abstractify(tree: Any) -> Any:
    """Concrete arrays -> ``ShapeDtypeStruct`` leaves, so ``jit.lower``
    traces without touching (or allocating on) the device. Leaves that are
    already abstract pass through."""
    import jax
    import jax.numpy as jnp

    def to_sds(x):
        if isinstance(x, jax.ShapeDtypeStruct):
            return x
        return jax.ShapeDtypeStruct(jnp.shape(x), jnp.result_type(x))

    return jax.tree_util.tree_map(to_sds, tree)


class ProgramIntrospector:
    """Collects :class:`ProgramReport`\\ s for a run's compiled programs.

    One instance per :class:`~fl4health_tpu.observability.Observability`
    handle; reports accumulate in ``.reports`` (last introspection of a
    name wins) and every capture lands in the registry + JSONL log."""

    def __init__(self, registry: MetricsRegistry):
        self.registry = registry
        self.reports: dict[str, ProgramReport] = {}

    # -- capture ---------------------------------------------------------
    def introspect_jit(self, name: str, jitted: Any, args: tuple,
                       rounds_per_dispatch: int = 1,
                       mesh: dict | None = None,
                       precision: dict | None = None,
                       cohort_draw: str | None = None
                       ) -> ProgramReport | None:
        """AOT-lower and compile ``jitted`` against (abstracted) ``args``
        and record the report. The compile goes through XLA's normal
        ``compile_or_get_cached`` path, so with the persistent compilation
        cache enabled the later jit dispatch of the SAME program is a disk
        hit, not a second backend compile. Returns None (after logging) on
        any failure — introspection must never take down a run."""
        import jax

        try:
            hits0 = self.registry.counter(_CACHE_HITS).value
            misses0 = self.registry.counter(_CACHE_MISSES).value
            t0 = time.perf_counter()
            compiled = jitted.lower(*abstractify(args)).compile()
            compile_s = time.perf_counter() - t0
            d = jax.devices()[0]
            report = ProgramReport(
                name=name,
                backend=d.platform,
                device_kind=getattr(d, "device_kind", "unknown"),
                compile_seconds=compile_s,
                cache_hits=int(self.registry.counter(_CACHE_HITS).value - hits0),
                cache_misses=int(
                    self.registry.counter(_CACHE_MISSES).value - misses0
                ),
                rounds_per_dispatch=rounds_per_dispatch,
                cohort_draw=cohort_draw,
                mesh=mesh,
                precision=precision,
                **analyze_compiled(
                    compiled,
                    n_partitions=int((mesh or {}).get("n_devices", 1)),
                ),
            )
            if stage_attr.enabled():
                report.stages = hloscan.analyze_compiled(
                    compiled,
                    device_kind=report.device_kind,
                    n_partitions=int((mesh or {}).get("n_devices", 1)),
                )
        except Exception:
            logger.warning("program introspection failed for %r", name,
                           exc_info=True)
            return None
        self.record(report)
        return report

    def record(self, report: ProgramReport) -> ProgramReport:
        """Register a report's numbers as ``fl_program_*`` gauges (labeled
        by program) plus one ``program`` JSONL event."""
        self.reports[report.name] = report
        reg = self.registry
        labels = {"program": report.name}
        gauges = (
            ("fl_program_flops",
             "XLA cost-model FLOPs of one compiled dispatch", report.flops),
            ("fl_program_bytes_accessed",
             "XLA cost-model bytes accessed by one dispatch",
             report.bytes_accessed),
            ("fl_program_transcendentals",
             "XLA cost-model transcendental ops per dispatch",
             report.transcendentals),
            ("fl_program_hbm_peak_bytes",
             "program device-memory footprint (args+outputs+temps+code)",
             report.peak_hbm_bytes),
            ("fl_program_compile_seconds",
             "wall time of this program's lower+compile",
             report.compile_seconds),
        )
        for gname, ghelp, value in gauges:
            if value is not None:
                reg.gauge(gname, help=ghelp, labels=labels).set(float(value))
        for row in report.stages or ():
            slabels = {"program": report.name, "stage": row["stage"]}
            reg.gauge(
                "fl_stage_flops",
                help="HLO-attributed FLOPs of one spine stage per dispatch",
                labels=slabels,
            ).set(float(row["flops"]))
            reg.gauge(
                "fl_stage_bytes",
                help="HLO-attributed HBM bytes of one spine stage per dispatch",
                labels=slabels,
            ).set(float(row["bytes_accessed"]))
            if "bound" in row:
                # only when the device roofline is known — never fabricated
                reg.gauge(
                    "fl_stage_bound",
                    help="1 = stage is compute-bound on this chip, 0 = HBM-bound",
                    labels=slabels,
                ).set(1.0 if row["bound"] == "compute" else 0.0)
            reg.log_event("stage", program=report.name, **row)
        reg.log_event("program", **report.as_dict())
        return report

    # -- derived numbers -------------------------------------------------
    def max_program_footprint(self) -> int | None:
        peaks = [r.peak_hbm_bytes for r in self.reports.values()
                 if r.peak_hbm_bytes is not None]
        return max(peaks) if peaks else None

    def hbm_headroom_bytes(self, device=None) -> int | None:
        """Device memory minus the largest program footprint — how much
        bigger the next model/cohort can get before OOM. Sets the
        ``fl_hbm_headroom_bytes`` gauge when computable (needs both a known
        device capacity and at least one memory-analyzed program)."""
        footprint = self.max_program_footprint()
        total = device_specs.device_memory_bytes(device)
        if footprint is None or total is None:
            return None
        headroom = int(total - footprint)
        self.registry.gauge(
            "fl_hbm_headroom_bytes",
            help="device memory minus peak compiled-program footprint",
        ).set(headroom)
        return headroom

    def round_flops(self, names: tuple[str, ...]) -> float | None:
        """Sum of per-round FLOPs over the named programs (the ones one
        federated round dispatches); None when none were cost-analyzed."""
        vals = [self.reports[n].flops_per_round for n in names
                if n in self.reports
                and self.reports[n].flops_per_round is not None]
        return sum(vals) if vals else None

    def clear(self) -> None:
        self.reports.clear()

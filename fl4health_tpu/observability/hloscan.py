"""Per-stage cost attribution from optimized-HLO text.

``compiled.cost_analysis()`` answers "how much work is the whole program" —
never "which spine stage is the work in". This module walks the compiled
executable's optimized-HLO text (``compiled.as_text()``, the same
build-time artifact the ``ProgramIntrospector`` hook already produces — no
device work), attributes each op's flops/bytes to the ``fl_stage::`` scope
on its ``op_name`` metadata path (observability/stages.py), and classifies
each stage against the device roofline (observability/device_specs.py).

Counting mirrors XLA's ``HloCostAnalysis`` conventions (validated against
live ``cost_analysis()`` totals):

- dot: ``2 * prod(result dims) * prod(contracting dims)`` — the single
  analytic numerator rule (observability/flops.py);
- convolution: ``2 * prod(output) * prod(kernel) / output_features``;
- reduce: one flop per reduced-away element (input elems − output elems);
- elementwise: one flop per output element, except transcendentals
  (exp/log/tanh/sqrt/...) which land in ``transcendentals``, not flops;
- bytes per op: operand bytes + result bytes; inside a fusion computation
  only the fusion's *boundary* operands/result count (the fused
  intermediates never touch HBM);
- ``to_apply`` reduction regions are not counted separately (their work is
  the reduce op's); while bodies count ONCE, trip-count-independent —
  exactly like ``cost_analysis`` on a scanned round program;
- a custom call (Pallas kernel) is a black box: 0 flops (the analytic
  numerator stays the honest one — see introspect.py's caveat), boundary
  bytes, and a per-stage ``custom_calls`` tally so the ledger shows where
  the cost model is blind.

Per-stage sums plus the ``_unattributed`` remainder equal this module's
own program totals *by construction*; :func:`conservation` then pins those
totals against the whole-program ``cost_analysis()`` numbers within
:data:`FLOPS_RTOL`/:data:`BYTES_RTOL` — the contract that no stage's cost
silently fell off the ledger.

Fusion headroom per stage: the gap between per-op bytes (every op reading
and writing HBM — the unfused worst case) and unique-buffer bytes (each
distinct buffer touched once — the perfectly-fused floor). A conservative
upper bound on what further fusion of that stage could save, and the
number ``tools/roofline_report.py`` ranks stages by.

Parsing is pure string work on the HLO text — importable without jax, so
CLI tools can re-analyze dumped programs on any box.
"""

from __future__ import annotations

import logging
import re
from math import prod
from typing import Any, Iterable

from fl4health_tpu.observability import device_specs, flops as flops_rules
from fl4health_tpu.observability.stages import SPINE_STAGES, UNATTRIBUTED, stage_of

logger = logging.getLogger(__name__)

# Conservation tolerances vs whole-program cost_analysis() totals. FLOPs
# reconcile tightly (same dot/reduce/elementwise rules); bytes are looser
# because XLA's buffer-level accounting sees layout/aliasing decisions the
# text walk approximates. Pinned by tests/observability/test_stage_attribution.py
# on the 4-client CIFAR CNN round programs.
FLOPS_RTOL = 0.15
BYTES_RTOL = 0.60

_ELEM_BYTES = {
    "pred": 1, "s4": 0.5, "u4": 0.5, "s8": 1, "u8": 1,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1,
    "s16": 2, "u16": 2, "f16": 2, "bf16": 2,
    "s32": 4, "u32": 4, "f32": 4,
    "s64": 8, "u64": 8, "f64": 8, "c64": 8, "c128": 16,
}

# One flop per output element (HloCostAnalysis's default elementwise rate).
_ELEMENTWISE = frozenset((
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "compare", "select", "and", "or", "xor", "not", "negate", "abs",
    "sign", "floor", "ceil", "round-nearest-afz", "round-nearest-even",
    "clamp", "convert", "remainder", "shift-left", "shift-right-logical",
    "shift-right-arithmetic", "is-finite", "popcnt", "clz",
    "stochastic-convert",
))

# Counted in the separate transcendentals bucket, mirroring cost_analysis.
_TRANSCENDENTAL = frozenset((
    "exponential", "exponential-minus-one", "log", "log-plus-one",
    "logistic", "tanh", "sqrt", "rsqrt", "cbrt", "power", "sine", "cosine",
    "tan", "atan2", "erf",
))

# Zero work, zero bytes: bookkeeping ops that allocate/alias, never move.
_FREE = frozenset((
    "parameter", "constant", "get-tuple-element", "tuple", "bitcast",
    "after-all", "opt-barrier", "partition-id", "replica-id",
))

# Region/control ops whose data motion is accounted inside their called
# computations (counted separately) — charging their full carry at the
# callsite would double-count every loop-carried buffer.
_CONTROL = frozenset(("while", "conditional", "call", "fusion"))

_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,<=\s]*)\]")
_METADATA_RE = re.compile(r"\s*,?\s*metadata=\{[^{}]*\}")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')
_ASSIGN_RE = re.compile(r"^\s*(?:ROOT\s+)?%?(?P<name>[\w.\-]+)\s*=\s*(?P<rest>.*)$")
_SCALAR_TYPE_RE = re.compile(r"^[a-zA-Z0-9]+\[[^\]]*\](?:\{[^{}]*\})?")
_OPCODE_RE = re.compile(r"^\s*(?P<opcode>[a-zA-Z][\w\-]*)\((?P<rest>.*)$")
_COMP_RE = re.compile(r"^\s*(?P<entry>ENTRY\s+)?%?(?P<name>[\w.\-]+)\s+\(.*->.*\{\s*$")
_REF_RE = re.compile(r"%([\w.\-]+)")
_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")
_WINDOW_SIZE_RE = re.compile(r"size=([0-9x]+)")
_CONTRACT_RE = re.compile(r"lhs_contracting_dims=\{([0-9,\s]*)\}")
_DIM_LABELS_RE = re.compile(r"dim_labels=([\w?]+)_([\w?]+)->([\w?]+)")


def _shapes(segment: str) -> list[tuple[str, tuple[int, ...]]]:
    """All ``dtype[d0,d1,...]`` shape tokens in a text segment."""
    out = []
    for dtype, dims in _SHAPE_RE.findall(segment):
        if dtype not in _ELEM_BYTES:
            continue
        parsed = tuple(
            int(d.replace("<=", "").strip())
            for d in dims.split(",") if d.strip()
        )
        out.append((dtype, parsed))
    return out


def _nbytes(shapes: Iterable[tuple[str, tuple[int, ...]]]) -> float:
    return float(sum(_ELEM_BYTES[dt] * prod(dims) for dt, dims in shapes))


def _elems(shapes: Iterable[tuple[str, tuple[int, ...]]]) -> int:
    return int(sum(prod(dims) for _, dims in shapes))


def _split_operands(rest: str) -> tuple[str, str]:
    """Split ``rest`` (text after the opcode's ``(``) into the operand
    segment and the trailing attributes, honoring nested parens (tuple-
    shaped operands)."""
    depth = 1
    for i, ch in enumerate(rest):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return rest[:i], rest[i + 1:]
    return rest, ""


def _split_result_type(rest: str) -> tuple[str, str] | None:
    """Split an instruction's text after ``=`` into (result type, rest).
    Tuple types need paren matching — big tuples carry ``/*index=N*/``
    comments and can nest, so no single regex is safe."""
    if rest.startswith("("):
        depth = 0
        for i, ch in enumerate(rest):
            if ch == "(":
                depth += 1
            elif ch == ")":
                depth -= 1
                if depth == 0:
                    return rest[: i + 1], rest[i + 1:]
        return None
    m = _SCALAR_TYPE_RE.match(rest)
    if not m:
        return None
    return m.group(0), rest[m.end():]


class _Op:
    __slots__ = ("name", "opcode", "result_shapes", "operand_segments",
                 "operand_shapes", "operand_names", "attrs", "op_name")

    def __init__(self, name, opcode, result_shapes, operand_shapes,
                 operand_names, attrs, op_name):
        self.name = name
        self.opcode = opcode
        self.result_shapes = result_shapes
        self.operand_shapes = operand_shapes
        self.operand_names = operand_names
        self.attrs = attrs
        self.op_name = op_name


def _parse_computations(text: str) -> tuple[dict[str, list[_Op]], str | None]:
    """HLO text -> {computation name: ops}, plus the ENTRY computation's
    name."""
    comps: dict[str, list[_Op]] = {}
    entry: str | None = None
    current: list[_Op] | None = None
    for line in text.splitlines():
        stripped = line.strip()
        if not stripped or stripped.startswith(("HloModule", "//", "}")):
            if stripped.startswith("}"):
                current = None
            continue
        header = _COMP_RE.match(line)
        if header and " = " not in line.split("->")[0]:
            name = header.group("name")
            comps[name] = []
            current = comps[name]
            if header.group("entry"):
                entry = name
            continue
        if current is None:
            continue
        op_name = None
        meta = _OPNAME_RE.search(line)
        if meta:
            op_name = meta.group(1)
        clean = _METADATA_RE.sub("", line)
        m = _ASSIGN_RE.match(clean)
        if not m:
            continue
        split = _split_result_type(m.group("rest"))
        if split is None:
            continue
        rtype, after = split
        mo = _OPCODE_RE.match(after)
        if not mo:
            continue
        operands, attrs = _split_operands(mo.group("rest"))
        current.append(_Op(
            name=m.group("name"),
            opcode=mo.group("opcode"),
            result_shapes=_shapes(rtype),
            operand_shapes=_shapes(operands),
            operand_names=_OPERAND_NAME_RE.findall(operands),
            attrs=attrs,
            op_name=op_name,
        ))
    return comps, entry


def _op_flops(op: _Op) -> tuple[float, float]:
    """(flops, transcendentals) of one HLO op, per the cost-model rules."""
    out_elems = _elems(op.result_shapes)
    if op.opcode == "dot":
        contract = _CONTRACT_RE.search(op.attrs)
        if not contract or not op.operand_shapes:
            return 0.0, 0.0
        lhs = op.operand_shapes[0][1]
        dims = [int(d) for d in contract.group(1).split(",") if d.strip()]
        contracted = [lhs[d] for d in dims if d < len(lhs)]
        # result may be tuple-free single shape; use all result elems
        return flops_rules.dot_flops((out_elems,), contracted), 0.0
    if op.opcode == "convolution":
        labels = _DIM_LABELS_RE.search(op.attrs)
        if not labels or len(op.operand_shapes) < 2:
            return 0.0, 0.0
        kernel_labels = labels.group(2)
        kernel = op.operand_shapes[1][1]
        o_idx = kernel_labels.find("o")
        out_features = kernel[o_idx] if 0 <= o_idx < len(kernel) else 1
        return 2.0 * out_elems * prod(kernel) / max(out_features, 1), 0.0
    if op.opcode == "reduce":
        # variadic reduce: operands are (inputs..., init values...); init
        # values are scalars, so input elems dominate — subtract outputs.
        in_elems = sum(
            prod(dims) for _, dims in op.operand_shapes if prod(dims) > 1
        )
        return float(max(in_elems - out_elems, 0)), 0.0
    if op.opcode == "reduce-window":
        size = _WINDOW_SIZE_RE.search(op.attrs)
        window = prod(int(x) for x in size.group(1).split("x")) if size else 1
        return float(out_elems * max(window - 1, 0)), 0.0
    if op.opcode in _TRANSCENDENTAL:
        return 0.0, float(out_elems)
    if op.opcode in _ELEMENTWISE:
        return float(out_elems), 0.0
    return 0.0, 0.0


def _op_bytes(op: _Op) -> float:
    return _nbytes(op.operand_shapes) + _nbytes(op.result_shapes)


def _classify_computations(
    comps: dict[str, list[_Op]], entry: str | None
) -> tuple[set[str], set[str], dict[str, str | None]]:
    """-> (countable computations, fusion computations, fusion -> callsite
    stage). ``to_apply`` regions are excluded; while/conditional/call
    bodies count once."""
    fusion: set[str] = set()
    control: set[str] = set()
    applied: set[str] = set()
    fusion_stage: dict[str, str | None] = {}
    for ops in comps.values():
        for op in ops:
            attrs = op.attrs
            if op.opcode == "fusion":
                m = re.search(r"calls=([^,]+)", attrs)
                if m:
                    for ref in _REF_RE.findall(m.group(1)):
                        fusion.add(ref)
                        fusion_stage.setdefault(ref, stage_of(op.op_name))
                continue
            for key in ("body=", "condition=", "branch_computations=",
                        "calls=", "called_computations="):
                idx = attrs.find(key)
                if idx < 0:
                    continue
                seg = attrs[idx + len(key):]
                seg = seg.split("}", 1)[0] if seg.startswith("{") else seg.split(",", 1)[0]
                control.update(_REF_RE.findall(seg))
            m = re.search(r"to_apply=%?([\w.\-]+)", attrs)
            if m:
                if op.opcode == "call":
                    # ``call`` names its target via to_apply, but the
                    # target is OUTLINED REAL CODE (XLA:CPU's parallel
                    # task assigner hoists heavy convolutions into such
                    # calls) — counted once like a while body, unlike the
                    # per-element apply lambdas of reduce/scatter/sort.
                    control.add(m.group(1))
                else:
                    applied.add(m.group(1))
    countable = {entry} if entry else set()
    # while/conditional/call bodies count once; fusion computations are
    # walked from their callsite instead; apply-lambda-only regions (the
    # reduce/scatter/sort combiners) never count
    countable |= control - fusion - (applied - control)
    return countable, fusion, fusion_stage


class _StageAcc:
    __slots__ = ("flops", "transcendentals", "bytes", "ops", "custom_calls",
                 "buffers")

    def __init__(self):
        self.flops = 0.0
        self.transcendentals = 0.0
        self.bytes = 0.0
        self.ops = 0
        self.custom_calls = 0
        self.buffers: dict[tuple[str, str], float] = {}


def analyze_text(
    text: str,
    device_kind: str | None = None,
    n_partitions: int = 1,
) -> list[dict[str, Any]]:
    """Attribute an optimized-HLO module's per-op costs to ``fl_stage::``
    stages. Returns one row per stage (spine order, then extras, then
    ``_unattributed`` last); rows follow the repo's None-means-unknown
    discipline — roofline keys appear only when classifiable."""
    comps, entry = _parse_computations(text)
    countable, fusion_comps, fusion_stage = _classify_computations(comps, entry)
    scale = float(max(n_partitions, 1))
    accs: dict[str, _StageAcc] = {}

    def acc(stage: str | None) -> _StageAcc:
        key = stage or UNATTRIBUTED
        if key not in accs:
            accs[key] = _StageAcc()
        return accs[key]

    def walk(comp: str, in_fusion: bool, fallback: str | None) -> None:
        for op in comps.get(comp, ()):
            if op.opcode in _FREE:
                continue
            stage = stage_of(op.op_name) or fallback
            a = acc(stage)
            f, t = _op_flops(op)
            a.flops += f
            a.transcendentals += t
            a.ops += 1
            if op.opcode == "custom-call":
                a.custom_calls += 1
            if not in_fusion and op.opcode not in _CONTROL:
                a.bytes += _op_bytes(op)
                for nm, shp in zip(op.operand_names, op.operand_shapes):
                    a.buffers[(comp, nm)] = _nbytes([shp])
                a.buffers[(comp, op.name)] = _nbytes(op.result_shapes)
            elif op.opcode == "fusion":
                # fused intermediates never reach HBM: only the fusion's
                # boundary operands/result move bytes
                a.bytes += _op_bytes(op)
                for nm, shp in zip(op.operand_names, op.operand_shapes):
                    a.buffers[(comp, nm)] = _nbytes([shp])
                a.buffers[(comp, op.name)] = _nbytes(op.result_shapes)
                m = re.search(r"calls=([^,]+)", op.attrs)
                for ref in _REF_RE.findall(m.group(1)) if m else ():
                    walk(ref, True, stage_of(op.op_name) or fallback)

    for comp in comps:
        if comp in countable and comp not in fusion_comps:
            walk(comp, False, None)

    rows = []
    for stage_name, a in accs.items():
        unique = sum(a.buffers.values())
        headroom = max(a.bytes - unique, 0.0)
        row: dict[str, Any] = {
            "stage": stage_name,
            "flops": a.flops * scale,
            "transcendentals": a.transcendentals * scale,
            "bytes_accessed": a.bytes * scale,
            "ops": a.ops,
            "custom_calls": a.custom_calls,
            "fusion_headroom_bytes": headroom * scale,
            "fusion_headroom_frac": (headroom / a.bytes) if a.bytes > 0 else None,
        }
        roof = device_specs.roofline(
            row["flops"], row["bytes_accessed"], device_kind or ""
        )
        if roof:
            row.update(roof)
            if "compute_bound" in roof:
                row["bound"] = "compute" if roof["compute_bound"] else "hbm"
        rows.append(row)

    def order(row: dict[str, Any]) -> tuple[int, str]:
        s = row["stage"]
        if s in SPINE_STAGES:
            return (0, f"{SPINE_STAGES.index(s):02d}")
        if s == UNATTRIBUTED:
            return (2, s)
        return (1, s)

    rows.sort(key=order)
    return rows


def analyze_compiled(
    compiled: Any,
    device_kind: str | None = None,
    n_partitions: int = 1,
) -> list[dict[str, Any]] | None:
    """Stage rows for a jax compiled executable, or None when the backend
    exposes no HLO text (never an exception — this runs inside
    ``introspect_jit``, which must not take down a run)."""
    try:
        text = compiled.as_text()
    except Exception:
        logger.debug("compiled.as_text() unavailable", exc_info=True)
        return None
    if not text or "ENTRY" not in text:
        return None
    try:
        return analyze_text(text, device_kind=device_kind,
                            n_partitions=n_partitions)
    except Exception:
        logger.warning("HLO stage scan failed", exc_info=True)
        return None


def totals(stages: list[dict[str, Any]]) -> dict[str, float]:
    """This module's own program totals (stage sums + _unattributed —
    exact by construction)."""
    return {
        "flops": sum(s["flops"] for s in stages),
        "transcendentals": sum(s["transcendentals"] for s in stages),
        "bytes_accessed": sum(s["bytes_accessed"] for s in stages),
    }


def conservation(
    stages: list[dict[str, Any]],
    program_flops: float | None,
    program_bytes: float | None,
    flops_rtol: float = FLOPS_RTOL,
    bytes_rtol: float = BYTES_RTOL,
) -> dict[str, Any]:
    """Reconcile per-stage sums with whole-program ``cost_analysis()``
    totals. Relative errors are None when the program total is unknown
    (no cost model on this backend) — absence, never a fake zero."""
    own = totals(stages)

    def rel(mine: float, theirs: float | None) -> float | None:
        if theirs is None:
            return None
        denom = max(abs(theirs), 1.0)
        return abs(mine - theirs) / denom

    flops_err = rel(own["flops"], program_flops)
    bytes_err = rel(own["bytes_accessed"], program_bytes)
    checked = [e <= t for e, t in ((flops_err, flops_rtol),
                                   (bytes_err, bytes_rtol)) if e is not None]
    return {
        "flops_rel_err": flops_err,
        "bytes_rel_err": bytes_err,
        "ok": all(checked) if checked else None,
    }

"""Flight recorder — bounded black-box capture of the last ``window`` rounds.

The live observability stack (spans, Prometheus/JSONL, in-graph
``RoundTelemetry``, ``/metrics`` + ``/manifest``) tells you what a healthy
run is doing — but when a run ends abnormally (watchdog halt, quorum loss,
SIGTERM preemption) the richest evidence dies with the process: the JSONL
log may be mid-rollover, the Chrome trace unterminated, and nobody
snapshots the last rounds' per-client telemetry or quarantine state.
Production FL debugging is POSTMORTEM debugging (stragglers, poisoned
silos, divergence onset — the failure modes FedBuff-style async schedules
care about, arXiv:2106.06639), so the :class:`FlightRecorder` keeps a ring
of the last ``window`` rounds' full-fidelity host-side round records and
``observability.bundle.dump_bundle`` publishes them on any abnormal end.

Cost contract (the reason this can default on):

- fed from the existing ``RoundConsumer`` epilogue / chunked epilogue with
  data the fused device->host transfer ALREADY pulled — recording adds
  zero device syncs and zero compiled-program changes on either execution
  mode (recorder-on is pinned bit-identical to recorder-off by tests);
- memory is O(window x cohort slots), never O(rounds) or O(registry): each
  entry holds [K]-shaped host arrays (telemetry vectors, masks, the
  round's REGISTRY ids under cohort-slot execution) plus a scalar summary
  dict, and the deque evicts beyond ``window`` (asserted by a
  registry-size-invariance test at fixed K).

The SIGTERM half lives here too: :func:`trap_sigterm` converts a SIGTERM
delivered during ``fit()`` into a :class:`SigtermShutdown` raised in the
main thread, which the simulation's abnormal-end hook turns into a
postmortem bundle before the process exits 143.
"""

from __future__ import annotations

import collections
import contextlib
import signal
import threading
from typing import Any, Iterator, Mapping

import numpy as np

DEFAULT_WINDOW = 16

# conventional "terminated by SIGTERM" exit status (128 + 15)
SIGTERM_EXIT_CODE = 143


class SigtermShutdown(SystemExit):
    """SIGTERM arrived mid-``fit()``. A ``SystemExit`` subclass so an
    unhandled propagation exits with the conventional 143 status; the
    simulation's abnormal-end hook dumps a postmortem bundle first."""

    def __init__(self) -> None:
        super().__init__(SIGTERM_EXIT_CODE)


@contextlib.contextmanager
def trap_sigterm(on_signal: Any = None) -> Iterator[bool]:
    """Install a SIGTERM -> :class:`SigtermShutdown` handler for the scope.

    Installed only when running on the main thread (CPython delivers
    signals there) AND the process still has the default disposition — a
    caller-installed SIGTERM handler is never displaced. Yields whether the
    trap is armed; the previous disposition is restored on exit.

    ``on_signal`` (optional, exception-proof) runs inside the handler
    BEFORE the raise — the simulation snapshots "which round was the run
    at when the signal arrived" here, because by the time the exception
    finishes unwinding, the pipeline's teardown drains will have recorded
    later rounds into the black box."""
    if threading.current_thread() is not threading.main_thread():
        yield False
        return
    try:
        prev = signal.getsignal(signal.SIGTERM)
    except (ValueError, OSError):  # exotic embedding without signal support
        yield False
        return
    if prev not in (signal.SIG_DFL, None):
        yield False
        return

    def _handler(signum, frame):  # noqa: ARG001 (signal API)
        if on_signal is not None:
            try:
                on_signal()
            except Exception:
                pass
        raise SigtermShutdown()

    signal.signal(signal.SIGTERM, _handler)
    try:
        yield True
    finally:
        signal.signal(signal.SIGTERM, prev)


def _host_arrays(tree: Mapping[str, Any] | None) -> dict[str, np.ndarray] | None:
    if tree is None:
        return None
    return {k: np.asarray(v) for k, v in tree.items()}


class FlightRecorder:
    """Ring buffer of the last ``window`` rounds' host-side round records.

    One entry per completed round: the round's scalar metrics summary (the
    same dict the ``round`` JSONL event carries — execution mode,
    compile/device/host walls, wire bytes, async buffer/staleness, cohort
    staging facts), aggregate fit/eval losses, the participation mask, the
    per-client ``RoundTelemetry`` vectors, the in-graph quarantine mask,
    the round's injected-fault summary and — under cohort-slot execution —
    the [K] REGISTRY ids the slots mapped to, so postmortem attribution
    names real clients, not slot positions.

    Thread-safe: the pipelined path records from the ``RoundConsumer``
    thread while ``dump_bundle`` may run on the main thread.
    """

    def __init__(self, window: int = DEFAULT_WINDOW):
        if window < 1:
            raise ValueError(f"window must be >= 1; got {window}")
        self.window = int(window)
        self._ring: collections.deque[dict] = collections.deque(
            maxlen=self.window
        )
        self._lock = threading.Lock()
        self._checkpoint: dict[str, Any] = {}
        self._run_facts: dict[str, Any] = {}
        # lock-FREE mirror of last_round() for signal handlers: a SIGTERM
        # can land while THIS thread holds self._lock (chunked-mode
        # record_round runs on the main thread) — the handler must never
        # acquire the lock or the process deadlocks instead of exiting 143
        self._last_round_hint: int | None = None

    # -- feeding (consumer thread / chunked epilogue) --------------------
    def record_round(
        self,
        round_idx: int,
        summary: Mapping[str, Any],
        *,
        fit_loss: float | None = None,
        eval_loss: float | None = None,
        mask: Any = None,
        telemetry: Mapping[str, Any] | None = None,
        registry_ids: Any = None,
        fault: Mapping[str, Any] | None = None,
    ) -> None:
        """Append one round's record (evicting past ``window``). Every
        array argument is host data the round's fused transfer already
        materialized — never pass device buffers that still back live
        state."""
        entry: dict[str, Any] = {
            "round": int(round_idx),
            "summary": dict(summary),
        }
        if fit_loss is not None:
            entry["fit_loss"] = float(fit_loss)
        if eval_loss is not None:
            entry["eval_loss"] = float(eval_loss)
        if mask is not None:
            entry["mask"] = np.asarray(mask)
        if telemetry is not None:
            entry["telemetry"] = _host_arrays(telemetry)
        if registry_ids is not None:
            entry["registry_ids"] = np.asarray(registry_ids)
        if fault is not None:
            entry["fault"] = dict(fault)
        with self._lock:
            self._ring.append(entry)
            self._bump_hint(int(round_idx))

    def attach(self, round_idx: int, **fields: Any) -> None:
        """Merge late-arriving facts (e.g. the quarantine mask, emitted
        after the round's metrics) into that round's entry; silently a
        no-op when the round already left the ring."""
        with self._lock:
            for entry in reversed(self._ring):
                if entry["round"] == int(round_idx):
                    for k, v in fields.items():
                        entry[k] = (np.asarray(v)
                                    if isinstance(v, np.ndarray) or hasattr(v, "shape")
                                    else v)
                    return

    def _bump_hint(self, round_idx: int) -> None:
        # caller holds self._lock; plain int assignment is atomic to read
        if self._last_round_hint is None or round_idx > self._last_round_hint:
            self._last_round_hint = round_idx

    def note_checkpoint(self, stats: Mapping[str, Any]) -> None:
        """Remember the newest durable checkpoint's facts (path,
        generation, round, bytes) — the bundle's "what to resume from"."""
        with self._lock:
            self._checkpoint = dict(stats)
            if stats.get("round") is not None:
                self._bump_hint(int(stats["round"]))

    def set_run_facts(self, **facts: Any) -> None:
        """Run-level provenance (execution mode, config hash, cohort
        shape) merged into the bundle header."""
        with self._lock:
            self._run_facts.update(facts)

    # -- reading ---------------------------------------------------------
    @property
    def entries(self) -> list[dict]:
        with self._lock:
            return [dict(e) for e in self._ring]

    @property
    def rounds(self) -> list[int]:
        with self._lock:
            return [int(e["round"]) for e in self._ring]

    @property
    def checkpoint(self) -> dict:
        with self._lock:
            return dict(self._checkpoint)

    @property
    def run_facts(self) -> dict:
        with self._lock:
            return dict(self._run_facts)

    def last_round(self) -> int | None:
        """Newest round the recorder knows about — the ring's newest entry
        or the newest checkpoint note, whichever is later (a SIGTERM
        landing inside round r's checkpoint save may beat the epilogue's
        record of round r into the recorder)."""
        with self._lock:
            return self._last_round_hint

    @property
    def last_round_hint(self) -> int | None:
        """LOCK-FREE read of :meth:`last_round` for signal handlers — a
        handler runs on whatever thread currently holds (or is about to
        take) the recorder lock, so it must never acquire it."""
        return self._last_round_hint

    def nbytes(self) -> int:
        """Host bytes of the ring's array payload — the O(window x slots)
        quantity the bounded-memory contract is asserted on (scalar
        summaries are negligible and excluded so the figure is
        registry-size-invariant by construction)."""
        total = 0
        with self._lock:
            for entry in self._ring:
                for v in entry.values():
                    if isinstance(v, np.ndarray):
                        total += v.nbytes
                    elif isinstance(v, dict):
                        total += sum(
                            a.nbytes for a in v.values()
                            if isinstance(a, np.ndarray)
                        )
        return total

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._checkpoint = {}
            self._run_facts = {}
            self._last_round_hint = None

"""The single analytic-FLOP numerator rule.

`bench.py`'s analytic MFU arms, `tools/flash_crossover.py`'s crossover
model, and `observability/hloscan.py`'s shape-based dot counter must never
disagree about the same matmul. This module is the one place the counting
convention lives:

- a dot/matmul of result shape ``M x N`` contracting over ``K`` costs
  ``2*M*N*K`` flops (multiply + add, the ``FL4HEALTH_BENCH_ANALYTIC_FLOPS``
  convention and XLA ``HloCostAnalysis``'s rule);
- a training step costs 3x the forward pass (forward + ~2x backward).

No jax import — bench and the CLI tools import this before (or without)
a backend.
"""

from __future__ import annotations

from math import prod
from typing import Sequence

# Backward pass ~= 2x forward for dense nets (dL/dx and dL/dW each cost a
# forward-sized matmul), so train = 3x forward. Shared by bench.py and
# tools/flash_crossover.py.
TRAIN_STEP_FLOP_MULTIPLIER = 3.0


def dot_flops(result_shape: Sequence[int], contracted: Sequence[int]) -> float:
    """Flops of one dot: 2 * prod(result dims) * prod(contracted dims)."""
    return 2.0 * prod(result_shape) * prod(contracted)


def matmul_flops(m: int, k: int, n: int) -> float:
    """Flops of one ``[m,k] @ [k,n]`` matmul: ``2*m*k*n``."""
    return dot_flops((m, n), (k,))


def transformer_fwd_flops_per_token(
    d_model: int, d_ff: int, n_layers: int, seq: int
) -> float:
    """Forward flops per token of a standard pre-LN transformer block stack.

    Per layer: QKV+out projections ``8*d^2``, attention scores+values
    ``4*seq*d`` (two ``[seq,d]x[d,seq]``-shaped contractions per token),
    and the two MLP matmuls ``4*d*d_ff``.
    """
    return (8.0 * d_model * d_model + 4.0 * seq * d_model + 4.0 * d_model * d_ff) * n_layers


def transformer_round_flops(
    d_model: int,
    d_ff: int,
    n_layers: int,
    seq: int,
    n_clients: int,
    batch: int,
    local_steps: int,
) -> float:
    """Analytic flops of one federated round of transformer local training:
    train-step multiplier x per-token forward x tokens per step x steps x
    clients."""
    per_tok_fwd = transformer_fwd_flops_per_token(d_model, d_ff, n_layers, seq)
    return TRAIN_STEP_FLOP_MULTIPLIER * per_tok_fwd * seq * batch * local_steps * n_clients

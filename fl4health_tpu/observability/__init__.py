"""Observability — round-level tracing, metrics, and XLA profiling hooks.

The reference's visibility story is wall-clock deltas in reporter dicts
(base_server.py fit/eval timing). Because the TPU build compiles a whole FL
round into two XLA programs, "where did the time go" needs three different
instruments, bundled here:

- :mod:`~fl4health_tpu.observability.spans` — nested context-manager spans
  on monotonic clocks, exported as Chrome trace-event JSON (open in
  Perfetto: one smoke run yields a visual per-round timeline of
  configure_fit -> fit_round -> aggregate -> eval_round -> checkpoint);
- :mod:`~fl4health_tpu.observability.registry` — process-wide
  counters/gauges/histograms with Prometheus text exposition and a JSONL
  event log (``tools/perf_report.py`` renders it);
- :mod:`~fl4health_tpu.observability.jaxmon` — JAX hooks: compile/cache
  event counting via ``jax.monitoring``, honest device-time fencing
  (``block_until_ready`` only when enabled), opt-in per-round
  ``jax.profiler.trace`` capture;
- :mod:`~fl4health_tpu.observability.telemetry` — IN-GRAPH round
  telemetry: a ``RoundTelemetry`` pytree of per-client training-health
  statistics compiled into the round programs themselves, so observability
  rides the chunked-scan fast path instead of forcing per-round dispatch;
- :mod:`~fl4health_tpu.observability.health` — the ``HealthWatchdog``
  consuming that telemetry against a declarative ``HealthPolicy``
  (NaN/Inf, loss divergence, dead clients, contribution skew), able to
  halt ``fit()`` with a structured ``TrainingHealthError``;
- :mod:`~fl4health_tpu.observability.introspect` — COMPILED-program
  introspection: per-program XLA cost/memory analysis (FLOPs, bytes
  accessed, HBM footprint), compile time and persistent-cache
  attribution, feeding measured MFU and the HBM-headroom gauge;
- :mod:`~fl4health_tpu.observability.exposition` /
  :mod:`~fl4health_tpu.observability.manifest` — a stdlib-only HTTP pull
  endpoint (``/metrics`` Prometheus text, ``/manifest`` run-provenance
  JSON) so a live ``fit()`` can be scraped mid-run;
- :mod:`~fl4health_tpu.observability.device_specs` — published per-chip
  peaks (bf16 FLOP/s, HBM capacity/bandwidth), the denominators for MFU
  and roofline positions.

:class:`Observability` is the facade ``FederatedSimulation`` accepts: it
wires all three to the process-wide defaults (so transport byte counters
land in the same snapshot) and owns export. Disabled, every hook is a
shared no-op — zero device syncs, zero allocations on the round hot path.
"""

from __future__ import annotations

import os
from typing import Any

from fl4health_tpu.observability.adminplane import AdminPlane, AdminRejection
from fl4health_tpu.observability.exposition import ScrapeServer
from fl4health_tpu.observability.fleet import FleetLedger
from fl4health_tpu.observability.slo import SLOEngine, SLOPolicy
from fl4health_tpu.observability.timeseries import RoundTimeSeries
from fl4health_tpu.observability.flightrec import (
    DEFAULT_WINDOW,
    FlightRecorder,
    SigtermShutdown,
    trap_sigterm,
)
from fl4health_tpu.observability.health import (
    HealthPolicy,
    HealthWatchdog,
    TrainingHealthError,
)
from fl4health_tpu.observability.introspect import (
    ProgramIntrospector,
    ProgramReport,
)
from fl4health_tpu.observability.manifest import config_hash, run_manifest
from fl4health_tpu.observability.jaxmon import (
    CompileMonitor,
    profile_round,
    synced,
)
from fl4health_tpu.observability.registry import (
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    get_registry,
    set_registry,
)
from fl4health_tpu.observability.spans import (
    _NULL_SPAN,
    Span,
    Tracer,
    get_tracer,
    set_tracer,
)
from fl4health_tpu.observability.tracectx import (
    TraceContext,
    flow_id,
    traced_handler,
)

__all__ = [
    "Observability",
    "AdminPlane",
    "AdminRejection",
    "SLOPolicy",
    "SLOEngine",
    "RoundTimeSeries",
    "FleetLedger",
    "TraceContext",
    "flow_id",
    "traced_handler",
    "FlightRecorder",
    "SigtermShutdown",
    "trap_sigterm",
    "Tracer",
    "Span",
    "MetricsRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "CompileMonitor",
    "HealthPolicy",
    "HealthWatchdog",
    "TrainingHealthError",
    "ProgramIntrospector",
    "ProgramReport",
    "ScrapeServer",
    "run_manifest",
    "config_hash",
    "get_tracer",
    "set_tracer",
    "get_registry",
    "set_registry",
    "profile_round",
    "synced",
]


class Observability:
    """One handle bundling tracer + registry + JAX hooks for a run.

    Defaults bind to the process-wide tracer/registry so free-function call
    sites (transport codec, coordinator) and the simulation share one
    snapshot; pass private instances for isolation (tests do).

    ``profile_round_idx`` selects ONE round for a ``jax.profiler.trace``
    capture under ``output_dir/xprof`` — device-level detail without paying
    profiler overhead on every round.

    ``telemetry`` (default on) compiles the in-graph
    :class:`~fl4health_tpu.observability.telemetry.RoundTelemetry` outputs
    into the round programs — per-client loss/grad-norm/update-norm
    statistics, non-finite counts, DP clip fraction and weight divergence —
    so a telemetry-on run keeps the chunked-scan fast path (the telemetry
    rides the existing fused transfers; loss trajectories stay
    bit-identical). ``watchdog`` attaches a
    :class:`~fl4health_tpu.observability.health.HealthWatchdog` that
    screens the telemetry each round and can halt ``fit()`` with a
    structured :class:`TrainingHealthError`.

    ``per_round_spans`` (opt-in) forces ``fit()`` onto the pipelined
    per-round path so the span timeline / device-time fences retain
    per-round granularity — with it off, enabling observability no longer
    demotes the chunked-scan execution mode (only ``profile_round_idx``
    still does).

    ``introspection`` (default on) captures each compiled round program's
    XLA cost/memory analysis at build time (``ProgramIntrospector``),
    which powers measured per-round MFU and the HBM-headroom gauge — all
    at program-build time, zero per-round cost. ``http_port`` (opt-in)
    starts the :class:`ScrapeServer` pull endpoint (``/metrics`` +
    ``/manifest``) for the handle's armed lifetime; ``http_port=0`` binds
    an OS-assigned port, readable from ``scrape_url``. The endpoint binds
    loopback by default — set ``http_host="0.0.0.0"`` for a remote
    Prometheus to reach it.

    The operations plane (both OFF by default): ``slo`` takes an
    :class:`~fl4health_tpu.observability.slo.SLOPolicy` and evaluates it
    each round in the epilogue (``fl_slo_*`` gauges, ``slo`` JSONL events,
    the ``degraded`` healthz state); ``admin_token`` arms the
    :class:`~fl4health_tpu.observability.adminplane.AdminPlane` behind
    ``POST /admin/scalars`` (shared-secret header auth) for live,
    journaled retunes of the hoisted scalars. Either one also arms the
    bounded :class:`~fl4health_tpu.observability.timeseries.RoundTimeSeries`
    (``ops_window`` rounds) that computes the serving KPIs.
    """

    def __init__(
        self,
        enabled: bool = True,
        output_dir: str | None = None,
        tracer: Tracer | None = None,
        registry: MetricsRegistry | None = None,
        profile_round_idx: int | None = None,
        sync_device: bool = True,
        telemetry: bool = True,
        per_round_spans: bool = False,
        watchdog: "HealthWatchdog | None" = None,
        introspection: bool = True,
        http_port: int | None = None,
        http_host: str = "127.0.0.1",
        flight_recorder: "bool | FlightRecorder" = True,
        flightrec_window: int | None = None,
        fleet_ledger: "bool | FleetLedger" = True,
        slo: "SLOPolicy | None" = None,
        admin_token: str | None = None,
        ops_window: int = 256,
    ):
        self.enabled = enabled
        self.output_dir = output_dir
        self.tracer = tracer if tracer is not None else get_tracer()
        self.registry = registry if registry is not None else get_registry()
        self.profile_round_idx = profile_round_idx
        self.sync_device = sync_device
        self.telemetry = telemetry
        self.per_round_spans = per_round_spans
        self.watchdog = watchdog
        self.introspection = introspection
        self.http_port = http_port
        self.http_host = http_host
        # Flight recorder (observability/flightrec.py): ALWAYS-ON by
        # default — a bounded ring of the last rounds' host-side records,
        # fed by data the round epilogues already pulled (zero device
        # syncs, recorder-on pinned bit-identical to recorder-off).
        # Bundles publish under output_dir on abnormal ends; without an
        # output_dir the ring stays queryable in memory.
        if isinstance(flight_recorder, FlightRecorder):
            self.flight_recorder: FlightRecorder | None = flight_recorder
        elif flight_recorder:
            self.flight_recorder = FlightRecorder(
                window=flightrec_window or DEFAULT_WINDOW
            )
        else:
            self.flight_recorder = None
        # Fleet ledger (observability/fleet.py): per-client LIFETIME
        # records at O(participated) host memory, same always-on/zero-sync
        # contract as the flight recorder. Rides the checkpoint frames via
        # the simulation (not here), and backs /fleet + /clients/<id>.
        if isinstance(fleet_ledger, FleetLedger):
            self.fleet_ledger: FleetLedger | None = fleet_ledger
        elif fleet_ledger:
            self.fleet_ledger = FleetLedger()
        else:
            self.fleet_ledger = None
        # Operations plane (PR 19): OFF unless an SLOPolicy or admin token
        # arms it. Host-side only — fed from epilogue summaries the run
        # already pulled, so arming it cannot add a device sync, and the
        # off path is bit-identical by construction.
        self.slo: "SLOEngine | None" = (
            SLOEngine(slo, self.registry) if slo is not None else None
        )
        self.admin: "AdminPlane | None" = (
            AdminPlane(admin_token, self.registry)
            if admin_token is not None else None
        )
        self.timeseries: "RoundTimeSeries | None" = (
            RoundTimeSeries(window=ops_window)
            if (self.slo is not None or self.admin is not None) else None
        )
        self._unhealthy: str | None = None
        self._degraded: str | None = None
        self.introspector = ProgramIntrospector(self.registry)
        self._manifest: dict[str, Any] = {}
        self._scrape_server: ScrapeServer | None = None
        self.compile_monitor = CompileMonitor(self.registry)
        # Ownership of the tracer's enabled flag: only the handle that
        # actually flipped it on may flip it off (and clear its events) at
        # shutdown — a disabled Observability, or one handed an
        # already-enabled tracer, must not reset state it doesn't own.
        self._owns_tracer_enable = False
        if enabled:
            self.start()

    @property
    def telemetry_enabled(self) -> bool:
        """True when the round programs should compile in-graph
        RoundTelemetry outputs."""
        return self.enabled and self.telemetry

    @property
    def introspection_enabled(self) -> bool:
        """True when compiled-program introspection should run at program
        build time."""
        return self.enabled and self.introspection

    @property
    def scrape_url(self) -> str | None:
        """Base URL of the live scrape endpoint, or None when not serving."""
        return self._scrape_server.url if self._scrape_server else None

    # -- run manifest ----------------------------------------------------
    def update_manifest(self, fields: "dict[str, Any]") -> dict:
        """Merge ``fields`` into the run manifest served at ``/manifest``
        (and exported as manifest.json). Returns the current manifest."""
        self._manifest.update(fields)
        return dict(self._manifest)

    @property
    def manifest(self) -> dict:
        return dict(self._manifest)

    def start(self) -> "Observability":
        """(Re-)arm the hooks: enable the tracer, install the compile
        monitor, reset the watchdog's per-run state. Called by ``__init__``
        and again by ``FederatedSimulation`` at each ``fit()`` so a handle
        survives multiple runs (``shutdown`` disarms it between them).
        Idempotent; no-op when disabled."""
        if self.enabled:
            self._unhealthy = None  # per-run: a fresh fit() is healthy
            self._degraded = None
            if self.watchdog is not None:
                self.watchdog.reset()
            if not self.tracer.enabled:
                # flipping the (possibly process-global) tracer on is what
                # makes transport/engine spans visible
                self.tracer.enabled = True
                self._owns_tracer_enable = True
            if self.output_dir is not None:
                # crash-safe black box: mirror spans to trace.json AS THEY
                # HAPPEN (Chrome JSON Array Format stays loadable even if
                # the process dies mid-run; export() finalizes the
                # complete envelope over it at shutdown)
                os.makedirs(self.output_dir, exist_ok=True)
                self.tracer.stream_to(
                    os.path.join(self.output_dir, "trace.json")
                )
            self.compile_monitor.install()
            if self.http_port is not None and self._scrape_server is None:
                # live pull endpoint for the armed lifetime of the handle —
                # a scrape reads host-side floats only (no device work)
                ledger = self.fleet_ledger
                self._scrape_server = ScrapeServer(
                    self.registry,
                    manifest_provider=lambda: dict(self._manifest),
                    host=self.http_host,
                    port=self.http_port,
                    health_provider=lambda: self._unhealthy,
                    fleet_provider=(
                        (lambda: ledger.summary()) if ledger is not None
                        else None
                    ),
                    client_provider=(
                        (lambda cid: ledger.get(cid)) if ledger is not None
                        else None
                    ),
                    degraded_provider=lambda: self._degraded,
                    slo_provider=(
                        (lambda: self.slo.standing())
                        if self.slo is not None else None
                    ),
                    admin_plane=self.admin,
                )
        return self

    # -- abnormal-end surface -------------------------------------------
    @property
    def unhealthy_reason(self) -> str | None:
        """The verdict summary once the run halted, else None (healthy)."""
        return self._unhealthy

    def mark_unhealthy(self, reason: str) -> None:
        """Flip ``/healthz`` to 503 with ``reason`` as the body — called on
        a watchdog halt and on every postmortem bundle dump, so the armed
        scrape endpoint stops reporting a dying run healthy."""
        self._unhealthy = str(reason)

    def mark_healthy(self) -> None:
        """Reset the ``/healthz`` verdict back to 200 ("ok") — the inverse
        of :meth:`mark_unhealthy`. The recovery supervisor calls this once
        a self-healed run's probation window passes, so an orchestrator
        polling the armed scrape endpoint sees the recovery instead of a
        503 that stays sticky until the next ``start()``."""
        self._unhealthy = None

    @property
    def degraded_slo(self) -> str | None:
        """Name of the SLO objective standing in breach, else None."""
        return self._degraded

    def mark_degraded(self, slo_name: str) -> None:
        """Flip ``/healthz`` to 200 ``degraded: <slo>`` — the limping state
        between healthy and the 503 a halt raises. Dead beats limping:
        a 503 verdict always wins over this channel."""
        self._degraded = str(slo_name)

    def clear_degraded(self) -> None:
        self._degraded = None

    def dump_bundle(self, verdict: "dict[str, Any]") -> str | None:
        """Publish a postmortem bundle (``observability/bundle.py``) under
        ``output_dir`` from the flight recorder's ring + the live trace/
        registry/manifest. Returns the bundle path, or None when disabled
        or there is nowhere to publish. Marks the run unhealthy."""
        if not self.enabled or self.output_dir is None:
            return None
        from fl4health_tpu.observability.bundle import dump_bundle

        path = dump_bundle(
            self.output_dir, verdict,
            recorder=self.flight_recorder,
            tracer=self.tracer if self.tracer.enabled else None,
            registry=self.registry,
            manifest=self._manifest or None,
            fleet=(self.fleet_ledger.snapshot()
                   if self.fleet_ledger is not None else None),
        )
        self.mark_unhealthy(
            f"{verdict.get('kind', 'exception')}: "
            f"{verdict.get('message', '')} (bundle: {path})"
        )
        self.registry.counter(
            "fl_flightrec_bundles_total",
            help="postmortem bundles published on abnormal ends",
        ).inc()
        return path

    # -- tracing ---------------------------------------------------------
    def span(self, name: str, cat: str = "round", **args: Any):
        if not self.enabled:
            return _NULL_SPAN
        return self.tracer.span(name, cat=cat, **args)

    def instant(self, name: str, **args: Any) -> None:
        if self.enabled:
            self.tracer.instant(name, **args)

    # -- metrics ---------------------------------------------------------
    def counter(self, name: str, help: str = "", labels=None) -> Counter:
        return self.registry.counter(name, help=help, labels=labels)

    def gauge(self, name: str, help: str = "", labels=None) -> Gauge:
        return self.registry.gauge(name, help=help, labels=labels)

    def histogram(self, name: str, help: str = "", labels=None, **kw) -> Histogram:
        return self.registry.histogram(name, help=help, labels=labels, **kw)

    def log_event(self, event: str, **fields: Any) -> dict | None:
        if not self.enabled:
            return None
        rec = self.registry.log_event(event, **fields)
        if event == "recovery" and self.timeseries is not None:
            # the supervisor's self-heal ladder routes through here — fold
            # engage/probation_passed/halt into the MTTR KPI
            self.timeseries.note_recovery(fields.get("phase"),
                                          ts=rec.get("ts"))
        return rec

    def snapshot(self) -> dict:
        return self.registry.snapshot()

    # -- operations plane ------------------------------------------------
    def observe_round_kpis(self, rnd: int, summary: "dict[str, Any]", *,
                           fit_loss: float | None = None,
                           eval_loss: float | None = None):
        """Feed one epilogue round summary to the ops plane: refresh the
        KPI time-series, evaluate the SLO policy, and drive the degraded
        healthz channel. No-op (returns None) when the plane is unarmed —
        the default path stays byte-for-byte untouched."""
        ts = self.timeseries
        if not self.enabled or ts is None:
            return None
        kpis = ts.observe_round(summary, fit_loss=fit_loss,
                                eval_loss=eval_loss)
        if self.slo is None:
            return kpis
        verdict = self.slo.evaluate(rnd, kpis)
        if verdict["degraded_slo"] is not None:
            self.mark_degraded(verdict["degraded_slo"])
        else:
            self.clear_degraded()
        return verdict

    # -- JAX hooks -------------------------------------------------------
    def fence(self, tree: Any) -> tuple[Any, float]:
        """``block_until_ready`` fence returning (tree, wait_seconds); a pure
        pass-through when disabled — no new syncs on the disabled path."""
        return synced(tree, enabled=self.enabled and self.sync_device)

    def maybe_profile(self, round_idx: int):
        """``jax.profiler.trace`` context for the chosen round, else no-op."""
        if (
            self.enabled
            and self.profile_round_idx is not None
            and round_idx == self.profile_round_idx
            and self.output_dir is not None
        ):
            return profile_round(os.path.join(self.output_dir, "xprof"))
        return profile_round(None)

    # -- export ----------------------------------------------------------
    def export(self) -> dict[str, str]:
        """Write trace.json (Chrome trace events), metrics.prom (Prometheus
        text), metrics.jsonl (event log) under ``output_dir``. Returns
        {artifact: path}; empty when disabled or no output_dir."""
        if not self.enabled or self.output_dir is None:
            return {}
        os.makedirs(self.output_dir, exist_ok=True)
        paths = {
            "trace": self.tracer.export(os.path.join(self.output_dir, "trace.json")),
            "prometheus": self.registry.export_prometheus(
                os.path.join(self.output_dir, "metrics.prom")
            ),
            "events": self.registry.dump_jsonl(
                os.path.join(self.output_dir, "metrics.jsonl")
            ),
        }
        if self._manifest:
            import json

            from fl4health_tpu.core.io import atomic_write

            mpath = os.path.join(self.output_dir, "manifest.json")
            with atomic_write(mpath) as f:
                f.write(json.dumps(self._manifest, indent=2, default=str))
            paths["manifest"] = mpath
        return paths

    def shutdown(self) -> dict[str, str]:
        """Export artifacts and disarm every hook: detach the compile
        monitor (so a later run's monitor doesn't double-count compile
        events through the global fan-out), and — if this handle is the one
        that enabled the tracer — disable it and drop its exported events
        (a long-lived process must not accumulate spans forever, nor re-export
        run 1's events into run 2's trace). ``start()`` re-arms."""
        paths = self.export()
        self.compile_monitor.uninstall()
        if self._scrape_server is not None:
            self._scrape_server.close()
            self._scrape_server = None
        if self._owns_tracer_enable:
            self.tracer.enabled = False
            # a stream export() didn't finalize (no output_dir, or a
            # different path) still terminates cleanly here
            self.tracer.close_stream()
            self.tracer.clear()
            self._owns_tracer_enable = False
        if "events" in paths:
            # only after a successful JSONL dump — with no output_dir the
            # events stay readable programmatically (registry.events)
            self.registry.clear_events()
        return paths

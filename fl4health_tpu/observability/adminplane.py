"""Admin plane — live, journaled retunes of the hoisted scalar registry.

Role: ROADMAP item 3's "operators retune hoisted scalars … through an admin
endpoint next to /metrics//healthz". PR 11 hoisted the scalar
hyperparameters (``sweep/hoisting.SCALAR_BINDINGS``) out of compiled round
programs; this module lets a live run rebind them at the next round boundary
with **zero recompiles** — the same mechanism the sweep uses per cell, now
driven by an authenticated ``POST /admin/scalars``.

Honesty about what is live-rebindable (``hoisting.live_rebind_kind``):

- state-kind scalars (``server_lr``, ``proximal_weight``) are server-state
  leaves — always rebindable via ``apply_state_scalars``;
- ``staleness_exponent`` is a live dispatch input on async runs — a plain
  ``setattr`` lands at the next event dispatch;
- the remaining attr-kind scalars (trim fraction, top-k endpoints, …) are
  baked trace constants on standalone runs: a setattr would *appear* to
  work while the compiled program kept the old value. Those submits are
  rejected with a structured ``static_scalar`` error instead of lying.

Threading contract: the HTTP handler thread only validates and enqueues
(``submit``); the producer thread drains at each round/event boundary
(``drain``) and applies to the producer-owned server state. Applied retunes
are journaled three ways — an ``admin`` JSONL event, ``fl_admin_*``
instruments, and a manifest descriptor — and ``schedule()`` replays a
journal programmatically so a retuned run stays bit-reproducible from
scratch (the acceptance drill pins this).

No JAX at import time; ``sweep.hoisting`` loads lazily on first use.
"""

from __future__ import annotations

import hmac
import threading
import time
from typing import Any, Callable, Mapping

__all__ = ["AdminPlane", "AdminRejection"]


class AdminRejection(Exception):
    """A structured admin-plane refusal, rendered as JSON by the endpoint.

    ``status`` is the HTTP status the handler answers; ``error`` a stable
    machine-readable tag; ``detail`` the operator-facing explanation.
    """

    def __init__(self, status: int, error: str, detail: str):
        super().__init__(detail)
        self.status = int(status)
        self.error = error
        self.detail = detail

    def doc(self) -> dict[str, Any]:
        return {"error": self.error, "detail": self.detail}


def _hoisting():
    from fl4health_tpu.sweep import hoisting
    return hoisting


class AdminPlane:
    """Pending-retune queue between the admin endpoint and the round loop.

    Built only when ``Observability(admin_token=...)`` arms it (off by
    default). ``bind_run`` is called by ``fit()`` once the execution mode is
    chosen; until then every submit is refused with ``no_active_run``.
    """

    AUTH_HEADER = "X-Admin-Token"

    def __init__(self, token: str, registry=None,
                 clock: Callable[[], float] = time.time):
        if not token or not isinstance(token, str):
            raise ValueError(
                "admin_token must be a non-empty shared secret; the admin "
                "plane refuses to start unauthenticated")
        self._token = token
        self._registry = registry
        self._clock = clock
        self._lock = threading.Lock()
        self._pending: dict[str, float] = {}
        self._schedule: dict[int, dict[str, float]] = {}
        self._journal: list[dict[str, Any]] = []
        self._strategy: Any = None
        self._mode: str | None = None
        self._async_active = False

    # ------------------------------------------------------------- lifecycle
    def bind_run(self, strategy, execution_mode: str,
                 async_active: bool = False) -> None:
        """Arm validation against the live run. Clears pending submits from
        any earlier fit (a fresh run must not inherit stale retunes); the
        programmatic ``schedule()`` survives — it IS the replay input."""
        with self._lock:
            self._strategy = strategy
            self._mode = execution_mode
            self._async_active = bool(async_active)
            self._pending.clear()

    # ----------------------------------------------------------------- auth
    def authorize(self, provided: str | None) -> None:
        """Constant-time shared-secret check; raises 401 on mismatch."""
        if provided is None or not hmac.compare_digest(
                provided.encode(), self._token.encode()):
            raise AdminRejection(
                401, "unauthorized",
                f"missing or wrong {self.AUTH_HEADER} header")

    # --------------------------------------------------------------- submits
    def _validate(self, scalars: Mapping[str, Any]) -> dict[str, float]:
        """All-or-nothing validation against the bound run. Returns the
        coerced float dict; raises AdminRejection with a structured error."""
        if not isinstance(scalars, Mapping) or not scalars:
            raise AdminRejection(
                400, "bad_request",
                'body must be a non-empty JSON object of {"scalar": value}')
        if self._strategy is None or self._mode is None:
            raise AdminRejection(
                409, "no_active_run",
                "no fit() is bound to the admin plane yet; retunes apply "
                "only to a live run")
        h = _hoisting()
        from fl4health_tpu.server.simulation import EXEC_CHUNKED
        if self._mode == EXEC_CHUNKED:
            # chunked_scan dispatches many rounds per call; there is no
            # per-round boundary on the host to apply at.
            raise AdminRejection(
                409, "mid_chunk",
                "this run executes chunked_scan — rounds inside a chunk "
                "have no host-side boundary to retune at; run with "
                "execution_mode='pipelined' for live retunes")
        out: dict[str, float] = {}
        for name, raw in scalars.items():
            try:
                value = float(raw)
            except (TypeError, ValueError):
                raise AdminRejection(
                    400, "bad_request",
                    f"scalar {name!r} value {raw!r} is not a number") from None
            try:
                kind = h.live_rebind_kind(self._strategy, name,
                                          async_active=self._async_active)
            except KeyError:
                raise AdminRejection(
                    400, "unknown_scalar",
                    f"{name!r} is not a registered hoisted scalar; "
                    f"registered: {sorted(h.SCALAR_BINDINGS)}") from None
            if kind == "inapplicable":
                raise AdminRejection(
                    409, "inapplicable_scalar",
                    f"{name!r} has no owner in this run's strategy chain")
            if kind == "static":
                raise AdminRejection(
                    409, "static_scalar",
                    f"{name!r} is an attr-kind scalar baked into the "
                    "compiled round program as a constant on this run; a "
                    "live rebind would silently not take effect — restart "
                    "the run, or explore it through sweep/ (which hoists "
                    "it as a program input)")
            try:
                h.binding(name).check(self._strategy, value)
            except ValueError as e:
                raise AdminRejection(400, "invalid_value", str(e)) from None
            out[name] = value
        return out

    def submit(self, scalars: Mapping[str, Any]) -> dict[str, Any]:
        """Validate and enqueue a retune (HTTP handler thread). Applies at
        the next round boundary the producer reaches."""
        with self._lock:
            values = self._validate(scalars)
            self._pending.update(values)
            accepted = dict(self._pending)
        if self._registry is not None:
            self._registry.counter(
                "fl_admin_requests",
                help="accepted POST /admin/scalars submissions").inc()
        return {"accepted": values, "pending": accepted,
                "applies": "next_round_boundary"}

    def schedule(self, round_idx: int, scalars: Mapping[str, float]) -> None:
        """Programmatic retune at a specific round — the replay mechanism.
        A from-scratch run fed an applied journal via ``schedule()``
        reproduces a live-retuned run bit-exactly."""
        with self._lock:
            slot = self._schedule.setdefault(int(round_idx), {})
            slot.update({str(k): float(v) for k, v in scalars.items()})

    # ------------------------------------------------------------- round loop
    def drain(self, round_idx: int) -> dict[str, float]:
        """Take everything due at this round boundary (producer thread):
        scheduled retunes for this round, overridden by live submits."""
        with self._lock:
            due = dict(self._schedule.pop(int(round_idx), {}))
            due.update(self._pending)
            self._pending.clear()
            return due

    def note_applied(self, round_idx: int, values: Mapping[str, float],
                     source: str = "live") -> dict[str, Any]:
        """Journal an applied retune; returns the journal entry."""
        entry = {"round": int(round_idx),
                 "scalars": {k: float(v) for k, v in values.items()},
                 "source": source, "ts": self._clock()}
        with self._lock:
            self._journal.append(entry)
        reg = self._registry
        if reg is not None:
            reg.log_event("admin", round=entry["round"],
                          scalars=entry["scalars"], source=source)
            reg.counter("fl_admin_retunes",
                        help="scalar retunes applied at round boundaries"
                        ).inc()
            for name, value in entry["scalars"].items():
                reg.gauge("fl_admin_scalar",
                          help="last admin-applied value per hoisted scalar",
                          labels={"scalar": name}).set(value)
        return entry

    # ----------------------------------------------------------------- reads
    def journal(self) -> list[dict[str, Any]]:
        with self._lock:
            return [dict(e) for e in self._journal]

    def descriptor(self) -> dict[str, Any]:
        """The manifest block disclosing the plane + every applied retune —
        what makes a retuned run replayable from its artifacts."""
        with self._lock:
            return {
                "enabled": True,
                "retunes": [
                    {"round": e["round"], "scalars": dict(e["scalars"]),
                     "source": e["source"]}
                    for e in self._journal
                ],
            }

"""State checkpointing — preemption-resilient resume for a federated run.

Parity: /root/reference/fl4health/checkpointing/state_checkpointer.py:41
(`StateCheckpointer` saving a dict of attributes via typed snapshotters,
utils/snapshotter.py:46-259) and the per-round resume loops
(servers/base_server.py:143 `fit_with_per_round_checkpointing`,
clients/basic_client.py:319-327).

TPU-native: all training state — the stacked client TrainState, the strategy's
server state, PRNG key, history — is already pytrees, so one msgpack blob plus
a small typed header replaces the reference's per-type snapshotter zoo. The
typed layer that remains is ``Snapshotter``s for host-side python values
(ints, floats, strings, dataclass records) which ride alongside the array
payload.

Crash consistency (the preemption-survivable contract):

- **Versioned, CRC-footed frames.** One checkpoint is ONE file —
  ``[magic][version][header-length][header JSON][msgpack blob][CRC32]`` —
  written to a temp sibling and published with a single ``os.replace``
  (``core.io.atomic_write``), so a SIGKILL mid-write can never tear the
  published path. The CRC32 footer covers every preceding byte, so a file
  torn by a non-atomic filesystem (or corrupted at rest) is DETECTED at
  restore instead of deserializing garbage into live training state.
- **Retention ring.** The last ``keep`` generations are retained as
  ``<name>.g<NNNNNNNN>.ckpt`` (monotonic generation numbers, oldest pruned
  after each atomic publish). Restore walks newest→oldest: a corrupt newest
  generation logs a warning, counts as a fallback, and the previous good
  generation restores instead — a preemption mid-rotation costs one
  checkpoint interval, never the run.
- **Config binding.** The header carries the run-manifest ``config_hash``
  (observability/manifest.py) of the run that wrote it; restoring into a
  simulation whose resume-relevant config hashes differently raises
  :class:`CheckpointConfigMismatchError` — a checkpoint can't silently
  resume a *different* experiment.
- **Typed corruption errors.** Torn/truncated/CRC-mismatched files raise
  :class:`CheckpointCorruptError` naming the file, so an operator (or the
  ring fallback) knows exactly which artifact died.

Legacy (pre-ring) ``<name>.ckpt`` files — no magic, no CRC — still load
(format version 0), so checkpoints written before this format survive the
upgrade.
"""

from __future__ import annotations

import dataclasses
import importlib
import json
import logging
import os
import re
import time
import zlib
from abc import ABC, abstractmethod
from typing import Any, Callable, Mapping

from flax import serialization

from fl4health_tpu.core.io import atomic_write

logger = logging.getLogger(__name__)

# Frame layout v1: MAGIC (8B) | version u32 BE | header length u64 BE |
# header JSON (utf-8) | msgpack blob | CRC32 u32 BE over all prior bytes.
_MAGIC = b"FL4HCKPT"
FORMAT_VERSION = 1
# magic + version + header length + (empty header) + (empty blob) + crc
_MIN_FRAME = len(_MAGIC) + 4 + 8 + 4


class CheckpointCorruptError(RuntimeError):
    """A checkpoint file failed structural validation (truncated frame,
    CRC mismatch, unparseable header, unknown format version). The message
    names the file so the ring fallback / operator knows which generation
    died."""

    def __init__(self, path: str, reason: str):
        super().__init__(f"corrupt checkpoint {path}: {reason}")
        self.path = path
        self.reason = reason


class CheckpointConfigMismatchError(ValueError):
    """The checkpoint was written by a run whose resume-relevant config
    hashes differently — restoring it would silently continue a different
    experiment."""

    def __init__(self, path: str, stored: str, current: str):
        super().__init__(
            f"checkpoint {path} was written under config_hash {stored} but "
            f"this run's resume-relevant config hashes to {current}; a "
            "checkpoint resumes only the experiment that wrote it (rebuild "
            "the simulation with the original configuration, or clear() the "
            "checkpoint directory to start fresh)"
        )
        self.path = path
        self.stored = stored
        self.current = current


# -- frame primitives --------------------------------------------------------
# The versioned CRC-footed frame is reusable beyond checkpoints: the flight
# recorder's postmortem ring (observability/bundle.py) publishes its host
# records through the SAME writer, so every durable artifact in the repo
# shares one corruption-detection story.

def write_frame(path: str, trees: Mapping[str, Any],
                host_header: Mapping[str, Any] | None = None,
                meta: Mapping[str, Any] | None = None) -> dict:
    """Serialize + atomically publish ONE versioned frame at ``path``:
    ``[magic][version][header-length][header JSON][msgpack blob][CRC32]``.
    ``trees`` is any flax-serializable pytree bag (the msgpack blob);
    ``host_header``/``meta`` land in the JSON header. Returns
    ``{path, bytes, write_s}``."""
    t0 = time.perf_counter()
    header_bytes = json.dumps(
        {"host": dict(host_header or {}), "meta": {
            "format_version": FORMAT_VERSION,
            "saved_unix": time.time(),
            **dict(meta or {}),
        }}
    ).encode("utf-8")
    blob = serialization.to_bytes(dict(trees))
    body = b"".join((
        _MAGIC,
        FORMAT_VERSION.to_bytes(4, "big"),
        len(header_bytes).to_bytes(8, "big"),
        header_bytes,
        blob,
    ))
    crc = zlib.crc32(body) & 0xFFFFFFFF
    with atomic_write(path, "wb") as f:  # single atomic publish
        f.write(body)
        f.write(crc.to_bytes(4, "big"))
    return {"path": path, "bytes": len(body) + 4,
            "write_s": time.perf_counter() - t0}


def read_frame(path: str) -> tuple[dict, dict, bytes]:
    """Parse + CRC-verify one frame -> (host_header, meta, msgpack blob).
    Raises :class:`CheckpointCorruptError` naming the file on any
    structural failure; legacy (pre-magic) v0 files still load."""
    with open(path, "rb") as f:
        data = f.read()
    if not data.startswith(_MAGIC):
        # legacy v0: [8B header length][header JSON][blob], no CRC
        if len(data) < 8:
            raise CheckpointCorruptError(path, "truncated legacy frame")
        n = int.from_bytes(data[:8], "big")
        if 8 + n > len(data):
            raise CheckpointCorruptError(
                path, "truncated legacy header (torn write?)"
            )
        try:
            header = json.loads(data[8:8 + n].decode("utf-8"))
        except (UnicodeDecodeError, json.JSONDecodeError) as e:
            raise CheckpointCorruptError(
                path, f"unparseable legacy header ({e})"
            ) from e
        return header, {"format_version": 0}, data[8 + n:]
    if len(data) < _MIN_FRAME:
        raise CheckpointCorruptError(
            path, f"truncated frame ({len(data)} bytes)"
        )
    body, crc_stored = data[:-4], int.from_bytes(data[-4:], "big")
    if (zlib.crc32(body) & 0xFFFFFFFF) != crc_stored:
        raise CheckpointCorruptError(
            path, "CRC32 mismatch (torn or corrupt write)"
        )
    version = int.from_bytes(data[8:12], "big")
    if version > FORMAT_VERSION:
        raise CheckpointCorruptError(
            path,
            f"format version {version} is newer than this build's "
            f"{FORMAT_VERSION}",
        )
    hlen = int.from_bytes(data[12:20], "big")
    if 20 + hlen > len(body):
        raise CheckpointCorruptError(path, "truncated header")
    try:
        header = json.loads(body[20:20 + hlen].decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise CheckpointCorruptError(
            path, f"unparseable header ({e})"
        ) from e
    return (header.get("host", {}), header.get("meta", {}),
            body[20 + hlen:])


@dataclasses.dataclass
class RestoreInfo:
    """Facts about one successful restore — which file/generation won, and
    which newer generations were skipped as corrupt (the ring fallback)."""

    path: str
    generation: int  # 0 for a legacy (pre-ring) file
    nbytes: int
    meta: dict
    fallback_skipped: list[str] = dataclasses.field(default_factory=list)


class Snapshotter(ABC):
    """Typed converter to/from a JSON-safe header value
    (utils/snapshotter.py:46 equivalent for host-side state)."""

    @abstractmethod
    def save(self, value: Any) -> Any:
        ...

    @abstractmethod
    def load(self, payload: Any, template: Any) -> Any:
        ...


class SerializableSnapshotter(Snapshotter):
    """ints / floats / strings / bools / lists / dicts — stored verbatim."""

    def save(self, value):
        return value

    def load(self, payload, template):
        return payload


def _resolve_dataclass(spec: str):
    """``module:QualName`` -> class, or None when unresolvable (the caller
    degrades to raw dicts rather than failing the whole restore)."""
    mod_name, _, qual = spec.partition(":")
    try:
        obj: Any = importlib.import_module(mod_name)
        for part in qual.split("."):
            obj = getattr(obj, part)
        return obj if dataclasses.is_dataclass(obj) else None
    except Exception:
        logger.warning("cannot resolve checkpoint record class %r", spec)
        return None


class DataclassListSnapshotter(Snapshotter):
    """A list of dataclass records (e.g. RoundRecord history).

    The header stores the record class name alongside the rows, so a
    NON-empty payload restores real dataclass instances even when the
    caller's template list is empty (the natural resume template — the
    fresh run has no history yet). Legacy headers (a bare row list, no
    class name) still load; without a template *or* a stored class name
    they degrade to raw dicts, the old behavior."""

    def save(self, value):
        payload: dict[str, Any] = {
            "rows": [dataclasses.asdict(v) for v in value]
        }
        if value:
            cls = type(value[0])
            payload["record_class"] = f"{cls.__module__}:{cls.__qualname__}"
        return payload

    def load(self, payload, template):
        if payload is None:
            return []
        if isinstance(payload, list):  # legacy header: bare row list
            rows, record_class = payload, None
        else:
            rows = payload.get("rows", [])
            record_class = payload.get("record_class")
        if not rows:
            return []
        cls = type(template[0]) if template else None
        if cls is None and record_class:
            cls = _resolve_dataclass(record_class)
        if cls is None:
            return rows
        return [cls(**row) for row in rows]


class StateCheckpointer:
    """Save/load a named bag of state: array pytrees go into one msgpack
    blob, host-side values into a JSON header. Loading requires templates
    with the same structure (the caller always has them — it constructs the
    run first, then restores into it).

    ``keep`` sizes the retention ring (≥1; 2 by default so a corrupt newest
    generation still has a good predecessor). ``checkpoint_every`` is the
    save cadence the simulation honors — on the chunked execution path it
    also sets ``rounds_per_dispatch``, so each snapshot rides the existing
    chunk-boundary host touch instead of forcing per-round dispatch.
    ``config_hash`` binds every frame to the writing run's resume-relevant
    config (``FederatedSimulation`` fills it in at ``fit()`` when left
    None). ``on_save`` is an optional callback receiving a stats dict
    ``{path, generation, bytes, write_s, ...extra_meta}`` after each
    publish — the simulation wires it to the ``fl_ckpt_*`` metrics; it may
    run on the async writer thread.
    """

    def __init__(self, directory: str, name: str = "state", *,
                 keep: int = 2, checkpoint_every: int = 1,
                 config_hash: str | None = None,
                 on_save: Callable[[dict], None] | None = None):
        if keep < 1:
            raise ValueError(f"keep must be >= 1; got {keep}")
        if checkpoint_every < 1:
            raise ValueError(
                f"checkpoint_every must be >= 1; got {checkpoint_every}"
            )
        self.directory = directory
        self.name = name
        self.keep = int(keep)
        self.checkpoint_every = int(checkpoint_every)
        self.config_hash = config_hash
        self.on_save = on_save
        self.last_save_stats: dict | None = None
        self.last_restore_info: RestoreInfo | None = None

    # -- paths -----------------------------------------------------------
    @property
    def _legacy_path(self) -> str:
        return os.path.join(self.directory, f"{self.name}.ckpt")

    # kept for callers/tests that reference the pre-ring single path
    _path = _legacy_path

    def _generation_path(self, gen: int) -> str:
        return os.path.join(self.directory, f"{self.name}.g{gen:08d}.ckpt")

    def generations(self) -> list[tuple[int, str]]:
        """(generation, path) pairs present on disk, oldest first."""
        pat = re.compile(re.escape(self.name) + r"\.g(\d{8})\.ckpt$")
        out = []
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        for fname in names:
            m = pat.fullmatch(fname)
            if m:
                out.append((int(m.group(1)),
                            os.path.join(self.directory, fname)))
        return sorted(out)

    def candidate_paths(self) -> list[tuple[int, str]]:
        """Restore candidates newest-first: ring generations, then the
        legacy single file (generation 0) if present."""
        cands = list(reversed(self.generations()))
        if os.path.exists(self._legacy_path):
            cands.append((0, self._legacy_path))
        return cands

    def exists(self) -> bool:
        return bool(self.candidate_paths())

    def _orphan_tmp_paths(self) -> list[str]:
        """Temp siblings (``<frame>.tmp.<pid>``) a SIGKILL mid-write left
        behind — ``atomic_write`` unlinks them on a Python exception, but
        a hard kill can't. A preemptible job would otherwise leak one
        full-frame file per eviction, forever."""
        pat = re.compile(
            re.escape(self.name) + r"\.(g\d{8}\.)?ckpt\.tmp\.\d+$"
        )
        try:
            names = os.listdir(self.directory)
        except OSError:
            return []
        return [os.path.join(self.directory, n) for n in names
                if pat.fullmatch(n)]

    def _prune_orphan_tmp(self) -> None:
        # called right after an atomic publish: our own temp file has been
        # renamed away by then, so everything still matching is litter
        # from a killed writer (single-writer-per-directory contract)
        for path in self._orphan_tmp_paths():
            try:
                os.remove(path)
            except OSError:
                pass

    def clear(self) -> None:
        for _gen, path in self.candidate_paths():
            try:
                os.remove(path)
            except OSError:
                pass
        self._prune_orphan_tmp()

    def prune_generations_from_round(self, round_idx: int) -> list[str]:
        """Rollback support (``resilience/supervisor.py``): delete ring
        generations whose frame ``meta["round"]`` is at or past
        ``round_idx`` — after an abnormal end at round *r* the newest
        durable generations may already hold the poisoned state, so a
        resume must restore a generation that PREDATES the failure.
        Corrupt frames are pruned too (they are rollback fodder either
        way); legacy frames with no recorded round are kept — deleting
        state of unknown vintage is an operator call, not a supervisor's.
        Returns the deleted paths."""
        removed: list[str] = []
        for _gen, path in self.candidate_paths():
            try:
                _host, meta, _blob = read_frame(path)
            except CheckpointCorruptError:
                meta = {"round": round_idx}  # corrupt: treat as at-fault
            r = meta.get("round")
            if r is None or int(r) < int(round_idx):
                continue
            try:
                os.remove(path)
                removed.append(path)
            except OSError:
                logger.warning("could not prune checkpoint generation at "
                               "%s during rollback", path)
        return removed

    # -- save ------------------------------------------------------------
    def save(self, trees: Mapping[str, Any], host: Mapping[str, Any] | None = None,
             snapshotters: Mapping[str, Snapshotter] | None = None,
             extra_meta: Mapping[str, Any] | None = None) -> dict:
        """Serialize + atomically publish one new generation, prune the
        ring to ``keep``, and return the save stats dict."""
        t0 = time.perf_counter()
        os.makedirs(self.directory, exist_ok=True)
        snapshotters = snapshotters or {}
        host_header: dict[str, Any] = {}
        for k, v in (host or {}).items():
            snap = snapshotters.get(k, SerializableSnapshotter())
            host_header[k] = snap.save(v)
        gens = self.generations()
        gen = (gens[-1][0] + 1) if gens else 1
        path = self._generation_path(gen)
        frame_stats = write_frame(
            path, trees, host_header=host_header,
            meta={"config_hash": self.config_hash, **dict(extra_meta or {})},
        )
        # rotation: prune only AFTER the new generation is durable, so a
        # kill anywhere in save() leaves at least the previous good ring
        for old_gen, old_path in gens[:max(len(gens) + 1 - self.keep, 0)]:
            try:
                os.remove(old_path)
            except OSError:
                logger.warning("could not prune checkpoint generation %d "
                               "(%s)", old_gen, old_path)
        # ...and sweep up temp litter a previous process's mid-write kill
        # left behind (our own temp was just renamed into place)
        self._prune_orphan_tmp()
        stats = {
            "path": path,
            "generation": gen,
            "bytes": frame_stats["bytes"],
            "write_s": time.perf_counter() - t0,
            **dict(extra_meta or {}),
        }
        self.last_save_stats = stats
        if self.on_save is not None:
            try:
                self.on_save(dict(stats))
            except Exception:
                # metrics/reporting hooks must never take down a save (it
                # may be the last durable state before a preemption)
                logger.warning("checkpoint on_save hook failed",
                               exc_info=True)
        return stats

    # -- read / verify ---------------------------------------------------
    def _read_file(self, path: str) -> tuple[dict, dict, bytes]:
        """Parse + verify ONE checkpoint file -> (host_header, meta, blob).
        Raises :class:`CheckpointCorruptError` naming the file on any
        structural failure. Thin wrapper over :func:`read_frame` (the
        shared frame primitive)."""
        return read_frame(path)

    def _read(self) -> tuple[dict, dict, bytes, RestoreInfo]:
        """Newest-good read with ring fallback: walk candidates newest to
        oldest, skipping (and logging) corrupt generations. Raises the
        newest file's :class:`CheckpointCorruptError` when every candidate
        is bad, and ``FileNotFoundError`` when none exists."""
        cands = self.candidate_paths()
        if not cands:
            raise FileNotFoundError(
                f"no checkpoint found under {self.directory!r} "
                f"(name={self.name!r})"
            )
        skipped: list[str] = []
        first_err: CheckpointCorruptError | None = None
        for gen, path in cands:
            try:
                host, meta, blob = self._read_file(path)
            except CheckpointCorruptError as e:
                logger.warning(
                    "checkpoint generation %d is corrupt (%s); falling "
                    "back to the previous generation", gen, e,
                )
                skipped.append(path)
                first_err = first_err or e
                continue
            info = RestoreInfo(
                path=path, generation=gen,
                nbytes=os.path.getsize(path), meta=meta,
                fallback_skipped=skipped,
            )
            return host, meta, blob, info
        assert first_err is not None
        raise first_err

    # -- load ------------------------------------------------------------
    def load_with_info(
        self, tree_templates: Mapping[str, Any],
        host_templates: Mapping[str, Any] | None = None,
        snapshotters: Mapping[str, Snapshotter] | None = None,
        expected_config_hash: str | None = None,
    ) -> tuple[dict, dict, RestoreInfo]:
        snapshotters = snapshotters or {}
        header, meta, blob, info = self._read()
        stored = meta.get("config_hash")
        if (expected_config_hash is not None and stored is not None
                and stored != expected_config_hash):
            raise CheckpointConfigMismatchError(
                info.path, stored, expected_config_hash
            )
        trees = serialization.from_bytes(dict(tree_templates), blob)
        host = {}
        for k, template in (host_templates or {}).items():
            snap = snapshotters.get(k, SerializableSnapshotter())
            host[k] = snap.load(header.get(k), template)
        self.last_restore_info = info
        return trees, host, info

    def load(self, tree_templates: Mapping[str, Any],
             host_templates: Mapping[str, Any] | None = None,
             snapshotters: Mapping[str, Snapshotter] | None = None,
             expected_config_hash: str | None = None,
             ) -> tuple[dict, dict]:
        trees, host, _info = self.load_with_info(
            tree_templates, host_templates, snapshotters,
            expected_config_hash=expected_config_hash,
        )
        return trees, host


class SimulationStateCheckpointer(StateCheckpointer):
    """Covers both reference roles at once: the server defaults (model,
    current_round, history, server_name — state_checkpointer.py:438-448) AND
    the client defaults (model, optimizers, schedulers, steps, meters
    :296-325), because the simulation's stacked client TrainState carries every
    client's model/optimizer/RNG in one pytree.

    Beyond the synchronous roles it also snapshots buffered-async runs
    (``save_async_snapshot``/``load_async_simulation``): the FedBuff
    ``pending`` update buffer, the event cursor, and the virtual clock —
    plus a fingerprint of the consumed prefix of the static event plan, so
    a resume can PROVE it is continuing the same arrival schedule before
    splicing restored state into it."""

    TREES = ("server_state", "client_states")

    def save_simulation(self, sim, current_round: int) -> None:
        self.save_simulation_snapshot(
            trees={
                "server_state": sim.server_state,
                "client_states": sim.client_states,
            },
            current_round=current_round,
            n_clients=sim.n_clients,
            history=list(sim.history),
        )

    def save_simulation_snapshot(
        self, trees, current_round: int, n_clients: int, history,
        writer=None, fleet=None,
    ) -> None:
        """Persist an explicit state snapshot — the pipelined round loop's
        entry point. ``trees`` must be caller-owned copies (host numpy under
        the async pipeline: the live device buffers may be donated into the
        next round before the write runs). With ``writer`` (an
        ``AsyncCheckpointWriter``) the serialize+write happens off-thread;
        saves stay ordered because the writer is single-worker.

        ``fleet`` (optional): the fleet ledger's JSON snapshot
        (``observability/fleet.py``), captured at call time so the async
        writer serializes a stable copy. Stored in the host header only
        when present — ledger-off frames are byte-identical to legacy."""
        host = {
            "kind": "sync",
            "current_round": current_round,
            "n_clients": n_clients,
            "history": list(history),
        }
        if fleet is not None:
            host["fleet"] = fleet
        kwargs = dict(
            trees=dict(trees),
            host=host,
            snapshotters={"history": DataclassListSnapshotter()},
            extra_meta={"round": current_round, "kind": "sync"},
        )
        if writer is not None:
            writer.submit(self.save, **kwargs)
        else:
            self.save(**kwargs)

    def save_async_snapshot(
        self, trees, event: int, n_clients: int, history,
        plan_fingerprint: str, virtual_time_s: float, writer=None,
        fleet=None,
    ) -> None:
        """Persist a buffered-async snapshot: server state, client stack
        AND the in-flight ``pending`` update buffer, with the event cursor,
        virtual clock, and the fingerprint of the event plan's consumed
        prefix (``server.async_schedule.plan_fingerprint``). ``fleet``:
        see :meth:`save_simulation_snapshot`."""
        host = {
            "kind": "async",
            "current_event": event,
            "n_clients": n_clients,
            "history": list(history),
            "plan_fingerprint": plan_fingerprint,
            "virtual_time_s": float(virtual_time_s),
        }
        if fleet is not None:
            host["fleet"] = fleet
        kwargs = dict(
            trees=dict(trees),
            host=host,
            snapshotters={"history": DataclassListSnapshotter()},
            extra_meta={"round": event, "kind": "async"},
        )
        if writer is not None:
            writer.submit(self.save, **kwargs)
        else:
            self.save(**kwargs)

    def save_cohort_snapshot(
        self, trees, current_round: int, slots: int, registry_size: int,
        registry_rows: dict, history, writer=None, fleet=None,
    ) -> None:
        """Persist a cohort-slot snapshot: the [slots]-shaped server/client
        state trees PLUS the registry's dirty rows (``ClientRegistry.
        export_rows``) — every participated client's persistent
        ``TrainState`` and strategy rows, keyed by the registry ids stored
        in the frame header. ``n_clients`` in the header is the SLOT count
        (the restore template's shape); ``registry_size`` binds the frame
        to its client population. ``fleet``: see
        :meth:`save_simulation_snapshot`.

        Both cohort dispatch routes write this same frame: the pipelined
        path at its per-round cadence, the chunked path at chunk
        boundaries (the chunk length IS ``checkpoint_every``, so every
        due round is a boundary and the window has already been scattered
        back into the registry when the snapshot is taken). A frame is
        therefore route-agnostic — a run saved pipelined may resume
        chunked and vice versa, and the resumed trajectory stays
        bit-identical because both routes draw round ``r``'s cohort from
        the same ``fold_in(seed, 2000+r)`` stream."""
        trees = dict(trees)
        c_ids = registry_rows.get("client_ids")
        s_ids = registry_rows.get("strategy_ids")
        if registry_rows.get("client_rows") is not None:
            trees["registry_client_rows"] = registry_rows["client_rows"]
        if registry_rows.get("strategy_rows") is not None:
            trees["registry_strategy_rows"] = registry_rows["strategy_rows"]
        host = {
            "kind": "cohort",
            "current_round": current_round,
            "n_clients": slots,
            "registry_size": registry_size,
            "registry_client_ids": [
                int(i) for i in (c_ids if c_ids is not None else ())
            ],
            "registry_strategy_ids": [
                int(i) for i in (s_ids if s_ids is not None else ())
            ],
            "history": list(history),
        }
        if fleet is not None:
            host["fleet"] = fleet
        kwargs = dict(
            trees=trees,
            host=host,
            snapshotters={"history": DataclassListSnapshotter()},
            extra_meta={"round": current_round, "kind": "cohort"},
        )
        if writer is not None:
            writer.submit(self.save, **kwargs)
        else:
            self.save(**kwargs)

    def load_cohort_simulation(self, sim) -> int:
        """Restore a cohort-slot run: slot states adopt onto the live
        simulation (mesh-aware, like the sync path) and the registry's
        dirty rows — sized from the header's id lists — reload into the
        sparse stores, so every participated client resumes from its last
        persisted row. Returns the next round to run (1-based)."""
        header, _meta, blob, info = self._read()
        kind = header.get("kind") or "sync"
        if kind != "cohort":
            raise ValueError(
                f"checkpoint {info.path} was written by a {kind} run; a "
                "cohort-slot simulation can only resume cohort frames "
                "(they carry the registry's dirty rows)"
            )
        if header["n_clients"] != sim.n_clients:
            raise ValueError(
                f"checkpoint has {header['n_clients']} cohort slots, run "
                f"has {sim.n_clients}"
            )
        if header.get("registry_size") != sim.registry_size:
            raise ValueError(
                f"checkpoint registry holds {header.get('registry_size')} "
                f"clients, run's registry holds {sim.registry_size}"
            )
        self._check_config(info, sim)
        c_ids = header.get("registry_client_ids") or []
        s_ids = header.get("registry_strategy_ids") or []
        templates = {
            "server_state": sim.server_state,
            "client_states": sim.client_states,
        }
        row_templates = sim.registry.row_templates(len(c_ids), len(s_ids))
        if "client_rows" in row_templates:
            templates["registry_client_rows"] = row_templates["client_rows"]
        if "strategy_rows" in row_templates:
            templates["registry_strategy_rows"] = (
                row_templates["strategy_rows"]
            )
        trees = serialization.from_bytes(templates, blob)
        sim.adopt_restored_state(trees["server_state"],
                                 trees["client_states"])
        sim.registry.load_rows(
            c_ids, trees.get("registry_client_rows"),
            s_ids, trees.get("registry_strategy_rows"),
        )
        sim.history = DataclassListSnapshotter().load(
            header.get("history"), self._history_template()
        )
        self._adopt_fleet(sim, header)
        self.last_restore_info = info
        return int(header["current_round"]) + 1

    @staticmethod
    def _adopt_fleet(sim, header: dict) -> None:
        """Hand the frame's fleet-ledger snapshot (or None for a legacy
        frame, which clears the ledger) to the simulation — resumed and
        rolled-back runs re-absorb replayed rounds exactly once."""
        if hasattr(sim, "adopt_fleet_snapshot"):
            sim.adopt_fleet_snapshot(header.get("fleet"))

    def _history_template(self):
        from fl4health_tpu.server.simulation import RoundRecord

        # one template record keeps LEGACY payloads (bare row lists with no
        # stored class name) restoring real RoundRecords
        return [RoundRecord(0, {}, {}, {}, {}, 0.0, 0.0)]

    def load_simulation(self, sim) -> int:
        """Restore in place; returns the next round to run (1-based).
        Header facts (kind/cohort/config binding) are validated BEFORE the
        array blob deserializes, so a wrong-experiment restore fails with
        its real reason, never a pytree-structure error. Mesh runs get the
        restored host arrays ``device_put`` back onto the round programs'
        shardings (``sim.adopt_restored_state``)."""
        header, _meta, blob, info = self._read()
        kind = header.get("kind") or "sync"
        if kind == "async":
            raise ValueError(
                f"checkpoint {info.path} was written by a buffered-async "
                "run (it carries a pending update buffer); resume it with "
                "the same async_config instead"
            )
        if kind != "sync":
            raise ValueError(
                f"checkpoint {info.path} was written by a {kind} run (its "
                "frame carries extra state — registry rows); resume it "
                "with the matching cohort configuration instead"
            )
        if header["n_clients"] != sim.n_clients:
            raise ValueError(
                f"checkpoint has {header['n_clients']} clients, run has "
                f"{sim.n_clients}"
            )
        self._check_config(info, sim)
        trees = serialization.from_bytes(
            {"server_state": sim.server_state,
             "client_states": sim.client_states},
            blob,
        )
        sim.adopt_restored_state(trees["server_state"],
                                 trees["client_states"])
        sim.history = DataclassListSnapshotter().load(
            header.get("history"), self._history_template()
        )
        self._adopt_fleet(sim, header)
        self.last_restore_info = info
        return int(header["current_round"]) + 1

    def load_async_simulation(self, sim, pending_template, plan) -> int:
        """Restore a buffered-async run mid-plan; returns the next EVENT to
        run (1-based). Verifies the stored plan-prefix fingerprint against
        the (re-derived) static event plan, so splicing restored state into
        a *different* arrival schedule fails loudly instead of silently
        de-synchronizing staleness accounting."""
        from fl4health_tpu.server.async_schedule import plan_fingerprint

        header, _meta, blob, info = self._read()
        if (header.get("kind") or "sync") != "async":
            raise ValueError(
                f"checkpoint {info.path} was written by a synchronous run "
                "(no pending update buffer); resume it without async_config"
            )
        if header["n_clients"] != sim.n_clients:
            raise ValueError(
                f"checkpoint has {header['n_clients']} clients, run has "
                f"{sim.n_clients}"
            )
        self._check_config(info, sim)
        event = int(header["current_event"])
        if event > plan.n_events:
            raise ValueError(
                f"checkpoint is at event {event} but the resumed plan has "
                f"only {plan.n_events} events; fit() at least {event} rounds"
            )
        expected_fp = plan_fingerprint(plan, event)
        if (header.get("plan_fingerprint")
                and header["plan_fingerprint"] != expected_fp):
            raise ValueError(
                f"checkpoint {info.path} was written under a different "
                "async event plan (fingerprint mismatch over the first "
                f"{event} events) — the AsyncConfig seed, FaultPlan, cohort "
                "and buffer_size must match the interrupted run for the "
                "buffered updates to resume bit-identically"
            )
        trees = serialization.from_bytes(
            {"server_state": sim.server_state,
             "client_states": sim.client_states,
             "pending": pending_template},
            blob,
        )
        sim.adopt_restored_state(
            trees["server_state"], trees["client_states"],
            pending=trees["pending"],
        )
        sim.history = DataclassListSnapshotter().load(
            header.get("history"), self._history_template()
        )
        self._adopt_fleet(sim, header)
        self.last_restore_info = info
        return event + 1

    def _check_config(self, info: RestoreInfo, sim) -> None:
        stored = info.meta.get("config_hash")
        current = self.config_hash
        if current is None:
            current = sim._resume_config_hash()
        if stored is not None and current is not None and stored != current:
            raise CheckpointConfigMismatchError(info.path, stored, current)

"""State checkpointing — preemption-resilient resume for a federated run.

Parity: /root/reference/fl4health/checkpointing/state_checkpointer.py:41
(`StateCheckpointer` saving a dict of attributes via typed snapshotters,
utils/snapshotter.py:46-259) and the per-round resume loops
(servers/base_server.py:143 `fit_with_per_round_checkpointing`,
clients/basic_client.py:319-327).

TPU-native: all training state — the stacked client TrainState, the strategy's
server state, PRNG key, history — is already pytrees, so one msgpack blob plus
a small typed header replaces the reference's per-type snapshotter zoo. The
typed layer that remains is ``Snapshotter``s for host-side python values
(ints, floats, strings, dataclass records) which ride alongside the array
payload.
"""

from __future__ import annotations

import dataclasses
import json
import os
from abc import ABC, abstractmethod
from typing import Any, Mapping

from flax import serialization

from fl4health_tpu.core.io import atomic_write


class Snapshotter(ABC):
    """Typed converter to/from a JSON-safe header value
    (utils/snapshotter.py:46 equivalent for host-side state)."""

    @abstractmethod
    def save(self, value: Any) -> Any:
        ...

    @abstractmethod
    def load(self, payload: Any, template: Any) -> Any:
        ...


class SerializableSnapshotter(Snapshotter):
    """ints / floats / strings / bools / lists / dicts — stored verbatim."""

    def save(self, value):
        return value

    def load(self, payload, template):
        return payload


class DataclassListSnapshotter(Snapshotter):
    """A list of dataclass records (e.g. RoundRecord history)."""

    def save(self, value):
        return [dataclasses.asdict(v) for v in value]

    def load(self, payload, template):
        if not payload:
            return []
        cls = type(template[0]) if template else None
        if cls is None:
            return payload
        return [cls(**row) for row in payload]


class StateCheckpointer:
    """Save/load a named bag of state: array pytrees go into one msgpack blob,
    host-side values into a JSON header. Loading requires templates with the
    same structure (the caller always has them — it constructs the run first,
    then restores into it).

    One checkpoint is ONE file — [8-byte header length][header JSON][msgpack
    blob] — written to a temp name and moved into place with a single
    ``os.replace``, so a preemption can never leave header and arrays from
    different rounds (the crash window the reference's per-attribute
    ``torch.save`` files have).
    """

    def __init__(self, directory: str, name: str = "state"):
        self.directory = directory
        self.name = name

    @property
    def _path(self) -> str:
        return os.path.join(self.directory, f"{self.name}.ckpt")

    def exists(self) -> bool:
        return os.path.exists(self._path)

    def save(self, trees: Mapping[str, Any], host: Mapping[str, Any] | None = None,
             snapshotters: Mapping[str, Snapshotter] | None = None) -> None:
        os.makedirs(self.directory, exist_ok=True)
        snapshotters = snapshotters or {}
        header = {}
        for k, v in (host or {}).items():
            snap = snapshotters.get(k, SerializableSnapshotter())
            header[k] = snap.save(v)
        header_bytes = json.dumps(header).encode("utf-8")
        blob = serialization.to_bytes(dict(trees))
        with atomic_write(self._path, "wb") as f:  # single atomic publish
            f.write(len(header_bytes).to_bytes(8, "big"))
            f.write(header_bytes)
            f.write(blob)

    def _read(self) -> tuple[dict, bytes]:
        with open(self._path, "rb") as f:
            n = int.from_bytes(f.read(8), "big")
            header = json.loads(f.read(n).decode("utf-8"))
            blob = f.read()
        return header, blob

    def load(self, tree_templates: Mapping[str, Any],
             host_templates: Mapping[str, Any] | None = None,
             snapshotters: Mapping[str, Snapshotter] | None = None,
             ) -> tuple[dict, dict]:
        snapshotters = snapshotters or {}
        header, blob = self._read()
        trees = serialization.from_bytes(dict(tree_templates), blob)
        host = {}
        for k, template in (host_templates or {}).items():
            snap = snapshotters.get(k, SerializableSnapshotter())
            host[k] = snap.load(header.get(k), template)
        return trees, host

    def clear(self) -> None:
        if os.path.exists(self._path):
            os.remove(self._path)


class SimulationStateCheckpointer(StateCheckpointer):
    """Covers both reference roles at once: the server defaults (model,
    current_round, history, server_name — state_checkpointer.py:438-448) AND
    the client defaults (model, optimizers, schedulers, steps, meters
    :296-325), because the simulation's stacked client TrainState carries every
    client's model/optimizer/RNG in one pytree."""

    TREES = ("server_state", "client_states")

    def save_simulation(self, sim, current_round: int) -> None:
        self.save_simulation_snapshot(
            trees={
                "server_state": sim.server_state,
                "client_states": sim.client_states,
            },
            current_round=current_round,
            n_clients=sim.n_clients,
            history=list(sim.history),
        )

    def save_simulation_snapshot(
        self, trees, current_round: int, n_clients: int, history,
        writer=None,
    ) -> None:
        """Persist an explicit state snapshot — the pipelined round loop's
        entry point. ``trees`` must be caller-owned copies (host numpy under
        the async pipeline: the live device buffers may be donated into the
        next round before the write runs). With ``writer`` (an
        ``AsyncCheckpointWriter``) the serialize+write happens off-thread;
        saves stay ordered because the writer is single-worker."""
        kwargs = dict(
            trees=dict(trees),
            host={
                "current_round": current_round,
                "n_clients": n_clients,
                "history": list(history),
            },
            snapshotters={"history": DataclassListSnapshotter()},
        )
        if writer is not None:
            writer.submit(self.save, **kwargs)
        else:
            self.save(**kwargs)

    def load_simulation(self, sim) -> int:
        """Restore in place; returns the next round to run (1-based)."""
        from fl4health_tpu.server.simulation import RoundRecord

        trees, host = self.load(
            tree_templates={
                "server_state": sim.server_state,
                "client_states": sim.client_states,
            },
            host_templates={
                "current_round": 0,
                "n_clients": sim.n_clients,
                "history": [RoundRecord(0, {}, {}, {}, {}, 0.0, 0.0)],
            },
            snapshotters={"history": DataclassListSnapshotter()},
        )
        if host["n_clients"] != sim.n_clients:
            raise ValueError(
                f"checkpoint has {host['n_clients']} clients, run has {sim.n_clients}"
            )
        sim.server_state = trees["server_state"]
        sim.client_states = trees["client_states"]
        sim.history = host["history"]
        return int(host["current_round"]) + 1

"""Checkpointing subsystem — model artifacts + resumable run state.

Two concerns, as in the reference (SURVEY.md §2.9): (1) model-artifact
checkpointing with latest/best policies and pre/post-aggregation modes
(checkpointing.checkpointer); (2) preemption-resilient state checkpointing
with typed snapshotters and per-round resume (checkpointing.state).
A third, TPU-native concern rides along: (3) the async writer
(checkpointing.async_writer) that the pipelined round loop uses to move
msgpack serialization and file I/O off the round-critical path.
"""

from fl4health_tpu.checkpointing.async_writer import AsyncCheckpointWriter
from fl4health_tpu.checkpointing.checkpointer import (
    BestLossCheckpointer,
    BestMetricCheckpointer,
    CheckpointMode,
    FunctionCheckpointer,
    LatestCheckpointer,
    ParamsCheckpointer,
    load_params,
    save_params,
)
from fl4health_tpu.checkpointing.state import (
    CheckpointConfigMismatchError,
    CheckpointCorruptError,
    RestoreInfo,
    SimulationStateCheckpointer,
    Snapshotter,
    StateCheckpointer,
)

__all__ = [
    "AsyncCheckpointWriter",
    "BestLossCheckpointer",
    "BestMetricCheckpointer",
    "CheckpointConfigMismatchError",
    "CheckpointCorruptError",
    "CheckpointMode",
    "FunctionCheckpointer",
    "LatestCheckpointer",
    "ParamsCheckpointer",
    "RestoreInfo",
    "SimulationStateCheckpointer",
    "Snapshotter",
    "StateCheckpointer",
    "load_params",
    "save_params",
]

"""Checkpointing subsystem — model artifacts + resumable run state.

Two concerns, as in the reference (SURVEY.md §2.9): (1) model-artifact
checkpointing with latest/best policies and pre/post-aggregation modes
(checkpointing.checkpointer); (2) preemption-resilient state checkpointing
with typed snapshotters and per-round resume (checkpointing.state).
"""

from fl4health_tpu.checkpointing.checkpointer import (
    BestLossCheckpointer,
    BestMetricCheckpointer,
    CheckpointMode,
    FunctionCheckpointer,
    LatestCheckpointer,
    ParamsCheckpointer,
    load_params,
    save_params,
)
from fl4health_tpu.checkpointing.state import (
    SimulationStateCheckpointer,
    Snapshotter,
    StateCheckpointer,
)

__all__ = [
    "BestLossCheckpointer",
    "BestMetricCheckpointer",
    "CheckpointMode",
    "FunctionCheckpointer",
    "LatestCheckpointer",
    "ParamsCheckpointer",
    "SimulationStateCheckpointer",
    "Snapshotter",
    "StateCheckpointer",
    "load_params",
    "save_params",
]

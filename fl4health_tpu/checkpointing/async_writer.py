"""Async checkpoint writer — serialize + write artifacts off the round loop.

The reference checkpoints synchronously inside the round loop
(``torch.save`` in ``TorchModuleCheckpointer.maybe_checkpoint``); on the TPU
build the msgpack serialization and file write are pure host work that the
async round pipeline (``server/pipeline.py``) moves off the critical path.
The checkpoint *decision* (best-loss/best-metric comparisons) stays ordered
in the round consumer; only the persist lands here.

Jobs receive HOST data (numpy pytrees snapshotted before the next round's
donation invalidates the device buffers) — a submitted job must never touch
live simulation state. The single worker keeps writes ordered, so "latest"
policies end with the last round's artifact on disk. Queue, flush-barrier
and first-exception propagation contracts come from
:class:`~fl4health_tpu.core.workqueue.SingleWorkerQueue`.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

from fl4health_tpu.checkpointing.checkpointer import save_params
from fl4health_tpu.core.workqueue import SingleWorkerQueue


class AsyncCheckpointWriter(SingleWorkerQueue):
    """Bounded single-worker queue for checkpoint persists."""

    def __init__(self, maxsize: int = 4, name: str = "fl-ckpt-writer"):
        super().__init__(maxsize=maxsize, name=name)

    def submit(self, fn: Callable, *args: Any, **kwargs: Any) -> None:
        """Enqueue a persist job; blocks when ``maxsize`` writes are pending
        (disk slower than rounds must throttle the pipeline, not accumulate
        unbounded host copies). Re-raises a stored failure first."""
        super().submit(functools.partial(fn, *args, **kwargs) if (args or kwargs)
                       else fn)

    def submit_save(self, path: str, params: Any) -> None:
        """Persist a params pytree (flax msgpack bytes) asynchronously.
        ``params`` must already be host data (numpy leaves)."""
        self.submit(save_params, path, params)

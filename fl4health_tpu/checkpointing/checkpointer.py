"""Model-artifact checkpointing — latest/best-loss/best-metric policies.

Parity: /root/reference/fl4health/checkpointing/checkpointer.py
(`TorchModuleCheckpointer` :15, `FunctionTorchModuleCheckpointer` :62,
`LatestTorchModuleCheckpointer` :162, `BestLossTorchModuleCheckpointer` :204,
`BestMetricTorchModuleCheckpointer` :267) and the PRE/POST-aggregation modes
of /root/reference/fl4health/checkpointing/client_module.py:23-28.

TPU-native: a "model" is a params pytree; artifacts are flax msgpack bytes
(`flax.serialization.to_bytes`). Loading requires a template pytree of the
same structure — the natural JAX idiom (orbax does the same via restore args).
"""

from __future__ import annotations

import enum
import os
from abc import ABC, abstractmethod
from typing import Any, Callable, Mapping

import numpy as np
from flax import serialization

from fl4health_tpu.core.types import Params


class CheckpointMode(enum.Enum):
    """When a client-side checkpointer fires (client_module.py:23-28)."""

    PRE_AGGREGATION = "pre_aggregation"
    POST_AGGREGATION = "post_aggregation"


def save_params(path: str, params: Params) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, "wb") as f:
        f.write(serialization.to_bytes(params))


def load_params(path: str, template: Params) -> Params:
    with open(path, "rb") as f:
        return serialization.from_bytes(template, f.read())


class ParamsCheckpointer(ABC):
    """Decides per call whether the given params are worth persisting.

    ``async_writer`` (an ``AsyncCheckpointWriter`` or None) routes the
    persist off-thread: the *decision* stays wherever ``maybe_checkpoint``
    runs (ordered, in the round consumer under the pipelined loop), only the
    serialize+write moves. The pipelined ``fit()`` attaches its writer for
    the duration of the run; standalone use stays synchronous.
    """

    def __init__(self, path: str):
        self.path = path
        self.async_writer = None

    @abstractmethod
    def maybe_checkpoint(
        self, params: Params, loss: float | None, metrics: Mapping[str, Any]
    ) -> bool:
        ...

    def _persist(self, params: Params) -> None:
        """Write now, or hand off to the attached async writer. ``params``
        handed to a writer must already be host data (the pipelined loop
        snapshots before submitting — device buffers may be donated away by
        the time the write runs)."""
        if self.async_writer is not None:
            self.async_writer.submit_save(self.path, params)
        else:
            save_params(self.path, params)

    def load(self, template: Params) -> Params:
        return load_params(self.path, template)


class FunctionCheckpointer(ParamsCheckpointer):
    """Score-function policy (FunctionTorchModuleCheckpointer :62): keep the
    checkpoint whenever score improves (maximize=True: larger is better)."""

    def __init__(
        self,
        path: str,
        score_fn: Callable[[float | None, Mapping[str, Any]], float],
        maximize: bool = False,
        name: str | None = None,
    ):
        super().__init__(path)
        self.score_fn = score_fn
        self.maximize = maximize
        self.name = name or score_fn.__name__
        self.best_score: float | None = None

    def maybe_checkpoint(self, params, loss, metrics) -> bool:
        score = float(self.score_fn(loss, metrics))
        if np.isnan(score):
            return False
        improved = (
            self.best_score is None
            or (score > self.best_score if self.maximize else score < self.best_score)
        )
        if improved:
            self.best_score = score
            self._persist(params)
        return improved


class LatestCheckpointer(ParamsCheckpointer):
    """Unconditional overwrite (LatestTorchModuleCheckpointer :162)."""

    def maybe_checkpoint(self, params, loss, metrics) -> bool:
        self._persist(params)
        return True


class BestLossCheckpointer(FunctionCheckpointer):
    """Keep the lowest loss seen (BestLossTorchModuleCheckpointer :204)."""

    def __init__(self, path: str):
        super().__init__(path, lambda loss, _m: float("inf") if loss is None else loss,
                         maximize=False, name="loss")


class BestMetricCheckpointer(FunctionCheckpointer):
    """Track one metric key (BestMetricTorchModuleCheckpointer :267)."""

    def __init__(self, path: str, metric_key: str, maximize: bool = True):
        def score(_loss, metrics):
            if metric_key not in metrics:
                raise KeyError(
                    f"metric '{metric_key}' not present in {sorted(metrics)}"
                )
            return float(metrics[metric_key])

        super().__init__(path, score, maximize=maximize, name=metric_key)

"""Differential-privacy subsystem: native RDP accounting + DP-SGD primitives.

Replaces the reference's dp-accounting/Opacus dependencies (SURVEY.md §2.8)
with pure-math RDP accounting (privacy.rdp, privacy.accountants) and
vmap-based per-example gradient clipping/noising (privacy.dpsgd).
"""

from fl4health_tpu.privacy.accountants import (
    FixedSamplingWithoutReplacement,
    FlClientLevelAccountantFixedSamplingNoReplacement,
    FlClientLevelAccountantPoissonSampling,
    FlInstanceLevelAccountant,
    MomentsAccountant,
    PoissonSampling,
)
from fl4health_tpu.privacy.dpsgd import (
    clip_per_example,
    gaussian_noise_like,
    noisy_clipped_mean_grads,
    validate_dp_safe_model_state,
)

__all__ = [
    "FixedSamplingWithoutReplacement",
    "FlClientLevelAccountantFixedSamplingNoReplacement",
    "FlClientLevelAccountantPoissonSampling",
    "FlInstanceLevelAccountant",
    "MomentsAccountant",
    "PoissonSampling",
    "clip_per_example",
    "gaussian_noise_like",
    "noisy_clipped_mean_grads",
    "validate_dp_safe_model_state",
]

"""FL privacy accountants — parity with the reference's accountant stack.

Reference: /root/reference/fl4health/privacy/moments_accountant.py:64
(`MomentsAccountant` wrapping dp-accounting's RDP accountant, with
`PoissonSampling` :30 / `FixedSamplingWithoutReplacement` :46 sampling
strategies) and /root/reference/fl4health/privacy/fl_accountants.py:12
(`FlInstanceLevelAccountant`, `FlClientLevelAccountantPoissonSampling` :127,
`FlClientLevelAccountantFixedSamplingNoReplacement` :184).

Here the RDP math is native (fl4health_tpu.privacy.rdp); the accountant layer
keeps the reference's API shapes: sampling-strategy objects, single-event or
trajectory composition, get_epsilon / get_delta.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from math import ceil
from typing import Sequence

import numpy as np

from fl4health_tpu.privacy import rdp as rdp_math


class SamplingStrategy(ABC):
    """How examples/clients enter a batch/round; selects the RDP formula."""

    @abstractmethod
    def step_rdp(self, noise_multiplier: float, orders: Sequence[float]) -> np.ndarray:
        ...


class PoissonSampling(SamplingStrategy):
    def __init__(self, sampling_ratio: float):
        if not 0.0 <= sampling_ratio <= 1.0:
            raise ValueError("sampling_ratio must be in [0, 1]")
        self.sampling_ratio = sampling_ratio

    def step_rdp(self, noise_multiplier, orders):
        return rdp_math.rdp_poisson_subsampled_gaussian(
            self.sampling_ratio, noise_multiplier, orders
        )


class FixedSamplingWithoutReplacement(SamplingStrategy):
    def __init__(self, population_size: int, sample_size: int):
        self.population_size = population_size
        self.sample_size = sample_size

    def step_rdp(self, noise_multiplier, orders):
        return rdp_math.rdp_sampled_without_replacement_gaussian(
            self.population_size, self.sample_size, noise_multiplier, orders
        )


class MomentsAccountant:
    """Compose (sampling, sigma, steps) events; answer epsilon/delta queries.

    Mirrors the reference MomentsAccountant (moments_accountant.py:64-200):
    scalar args = one self-composed event; list args = a training trajectory
    composed in sequence.
    """

    def __init__(self, moment_orders: Sequence[float] | None = None):
        self.moment_orders = (
            list(moment_orders) if moment_orders is not None
            else rdp_math.default_orders()
        )

    def _total_rdp(
        self,
        sampling: SamplingStrategy | Sequence[SamplingStrategy],
        noise_multiplier: float | Sequence[float],
        updates: int | Sequence[int],
    ) -> np.ndarray:
        if isinstance(sampling, SamplingStrategy):
            sampling = [sampling]
        n = max(
            len(sampling),
            len(noise_multiplier) if not isinstance(noise_multiplier, (int, float)) else 1,
            len(updates) if not isinstance(updates, int) else 1,
        )
        # scalars broadcast to the trajectory length
        if isinstance(noise_multiplier, (int, float)):
            noise_multiplier = [float(noise_multiplier)] * n
        if isinstance(updates, int):
            updates = [updates] * n
        if len(sampling) == 1 and n > 1:
            sampling = list(sampling) * n
        if not (len(sampling) == len(noise_multiplier) == len(updates)):
            raise ValueError("trajectory lists must have equal length")
        total = np.zeros(len(self.moment_orders), dtype=np.float64)
        for strat, sigma, n in zip(sampling, noise_multiplier, updates):
            total = total + n * strat.step_rdp(sigma, self.moment_orders)
        return total

    def get_epsilon(self, sampling, noise_multiplier, updates, delta: float) -> float:
        rdp = self._total_rdp(sampling, noise_multiplier, updates)
        return rdp_math.epsilon_from_rdp(self.moment_orders, rdp, delta)

    def get_delta(self, sampling, noise_multiplier, updates, epsilon: float) -> float:
        rdp = self._total_rdp(sampling, noise_multiplier, updates)
        return rdp_math.delta_from_rdp(self.moment_orders, rdp, epsilon)


class FlInstanceLevelAccountant:
    """Instance-level DP across FL rounds (fl_accountants.py:12): Poisson
    sampling at BOTH levels — effective per-step inclusion probability for a
    data point on client c is client_sampling_rate * (batch_c / dataset_c);
    total steps = rounds * epochs_per_round * batches_per_epoch_c; epsilon is
    the max over clients."""

    def __init__(
        self,
        client_sampling_rate: float,
        noise_multiplier: float,
        epochs_per_round: int | None,
        client_batch_sizes: Sequence[int],
        client_dataset_sizes: Sequence[int],
        moment_orders: Sequence[float] | None = None,
        steps_per_round: int | None = None,
    ):
        """steps_per_round: alternative to epochs_per_round for step-driven
        local training (the reference's epochs-xor-steps config shape) —
        total compositions become rounds * steps_per_round per client."""
        if len(client_batch_sizes) != len(client_dataset_sizes):
            raise ValueError("batch/dataset size lists must align")
        if (epochs_per_round is None) == (steps_per_round is None):
            raise ValueError("specify exactly one of epochs_per_round / steps_per_round")
        self.noise_multiplier = noise_multiplier
        self.epochs_per_round = epochs_per_round
        self.steps_per_round = steps_per_round
        self.num_batches_per_client = [
            ceil(d / b) for b, d in zip(client_batch_sizes, client_dataset_sizes)
        ]
        self.sampling_per_client = [
            PoissonSampling(client_sampling_rate * b / d)
            for b, d in zip(client_batch_sizes, client_dataset_sizes)
        ]
        # Full-participation variant (no client-level subsampling): used for
        # rounds where EVERY client is known to touch data, e.g. the
        # DP-SCAFFOLD warm start — those rounds get no amplification from
        # client_sampling_rate and must be composed at rate b/d.
        self.full_sampling_per_client = [
            PoissonSampling(b / d)
            for b, d in zip(client_batch_sizes, client_dataset_sizes)
        ]
        self.accountant = MomentsAccountant(moment_orders)

    def _updates_for(self, rounds: int, n_batches: int) -> int:
        if self.steps_per_round is not None:
            return ceil(rounds * self.steps_per_round)
        return ceil(rounds * self.epochs_per_round * n_batches)

    def _per_client(self, fn, server_updates: int, value: float,
                    full_participation_rounds: int = 0) -> float:
        results = []
        for n_batches, sampling, full_sampling in zip(
            self.num_batches_per_client, self.sampling_per_client,
            self.full_sampling_per_client,
        ):
            total = self._updates_for(server_updates, n_batches)
            if full_participation_rounds:
                # heterogeneous trajectory: subsampled rounds + full rounds,
                # composed additively in RDP space (MomentsAccountant lists)
                extra = self._updates_for(full_participation_rounds, n_batches)
                results.append(fn(
                    [sampling, full_sampling], self.noise_multiplier,
                    [total, extra], value,
                ))
            else:
                results.append(fn(sampling, self.noise_multiplier, total, value))
        return max(results)

    def get_epsilon(self, server_updates: int, delta: float,
                    full_participation_rounds: int = 0) -> float:
        return self._per_client(self.accountant.get_epsilon, server_updates,
                                delta, full_participation_rounds)

    def get_delta(self, server_updates: int, epsilon: float,
                  full_participation_rounds: int = 0) -> float:
        return self._per_client(self.accountant.get_delta, server_updates,
                                epsilon, full_participation_rounds)


class ClientLevelAccountant(ABC):
    """Client-level DP: each round is one subsampled-Gaussian query over the
    client population (fl_accountants.py:98)."""

    def __init__(
        self,
        noise_multiplier: float | Sequence[float],
        moment_orders: Sequence[float] | None = None,
    ):
        self.noise_multiplier = noise_multiplier
        self.accountant = MomentsAccountant(moment_orders)

    @abstractmethod
    def _sampling(self) -> SamplingStrategy | Sequence[SamplingStrategy]:
        ...

    def get_epsilon(self, server_updates: int | Sequence[int], delta: float) -> float:
        return self.accountant.get_epsilon(
            self._sampling(), self.noise_multiplier, server_updates, delta
        )

    def get_delta(self, server_updates: int | Sequence[int], epsilon: float) -> float:
        return self.accountant.get_delta(
            self._sampling(), self.noise_multiplier, server_updates, epsilon
        )


class FlClientLevelAccountantPoissonSampling(ClientLevelAccountant):
    """fl_accountants.py:127 — clients join each round i.i.d. Bernoulli(q)."""

    def __init__(
        self,
        client_sampling_rate: float | Sequence[float],
        noise_multiplier: float | Sequence[float],
        moment_orders: Sequence[float] | None = None,
    ):
        super().__init__(noise_multiplier, moment_orders)
        self.client_sampling_rate = client_sampling_rate

    def _sampling(self):
        if isinstance(self.client_sampling_rate, (int, float)):
            return PoissonSampling(float(self.client_sampling_rate))
        return [PoissonSampling(float(q)) for q in self.client_sampling_rate]


class FlClientLevelAccountantFixedSamplingNoReplacement(ClientLevelAccountant):
    """fl_accountants.py:184 — exactly n of N clients sampled per round."""

    def __init__(
        self,
        n_total_clients: int,
        n_clients_sampled: int | Sequence[int],
        noise_multiplier: float | Sequence[float],
        moment_orders: Sequence[float] | None = None,
    ):
        super().__init__(noise_multiplier, moment_orders)
        self.n_total_clients = n_total_clients
        self.n_clients_sampled = n_clients_sampled

    def _sampling(self):
        if isinstance(self.n_clients_sampled, int):
            return FixedSamplingWithoutReplacement(
                self.n_total_clients, self.n_clients_sampled
            )
        return [
            FixedSamplingWithoutReplacement(self.n_total_clients, n)
            for n in self.n_clients_sampled
        ]

"""Per-example DP-SGD primitives — the Opacus replacement, TPU-native.

Reference path: Opacus ``PrivacyEngine.make_private`` installs per-sample
gradient hooks + flat clipping + Gaussian noise inside the optimizer step
(/root/reference/fl4health/clients/instance_level_dp_client.py:85-114). On TPU
the same computation is ``vmap(grad)`` over the batch, a per-example global-norm
clip, a masked sum, and one Gaussian draw per parameter leaf — all fused by XLA
into the training step (no hooks, no eager per-tensor work).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp

from fl4health_tpu.core.types import Params, PRNGKey


def clip_per_example(per_example_grads: Params, bound: float) -> tuple[Params, jax.Array]:
    """Flat-clip each example's gradient pytree to l2 norm <= bound.

    ``per_example_grads`` has a leading [B] axis on every leaf. Returns the
    clipped tree and the pre-clip per-example norms [B].
    """
    sq = sum(
        jnp.sum(jnp.square(g).reshape(g.shape[0], -1), axis=-1)
        for g in jax.tree_util.tree_leaves(per_example_grads)
    )
    norms = jnp.sqrt(jnp.maximum(sq, 0.0))
    factor = jnp.minimum(1.0, bound / jnp.maximum(norms, 1e-12))

    def scale(g):
        return g * factor.reshape((-1,) + (1,) * (g.ndim - 1))

    return jax.tree_util.tree_map(scale, per_example_grads), norms


def gaussian_noise_like(rng: PRNGKey, tree: Params, stddev) -> Params:
    """One independent Gaussian draw per leaf, std ``stddev``."""
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    keys = jax.random.split(rng, len(leaves))
    noised = [
        jax.random.normal(k, l.shape, jnp.float32).astype(l.dtype) * stddev
        for k, l in zip(keys, leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, noised)


def noisy_clipped_mean_grads(
    per_example_grads: Params,
    example_mask: jax.Array,
    rng: PRNGKey,
    clipping_bound: float,
    noise_multiplier: float,
    use_fused_kernel: bool = False,
    return_clip_fraction: bool = False,
) -> Params:
    """DP-SGD gradient: clip each example to C, masked-sum, add N(0, (sigma C)^2)
    per coordinate, divide by the number of real examples (Opacus' mean-loss
    semantics with the actual batch size).

    ``use_fused_kernel`` routes the clip+reduce through the Pallas kernels
    (kernels/dp_clip.py): two passes over the [B, D] per-example tensor
    instead of three, no materialized clipped intermediate. Opt-in because
    the engine vmaps client logic over the clients axis and pallas_call
    batching support depends on the backend; the XLA path is always safe.

    ``return_clip_fraction`` appends the fraction of real examples whose
    pre-clip norm exceeded C — the classic DP tuning diagnostic (a clip
    fraction pinned at 1.0 means the bound is strangling the signal; 0.0
    means it is pure noise headroom). Derived from norms both paths already
    compute, so it never adds a pass over the gradient tensor; the noised
    gradient itself is bit-identical either way.
    """
    m = example_mask.astype(jnp.float32)
    if use_fused_kernel:
        from fl4health_tpu.kernels.dp_clip import fused_clipped_masked_sum

        summed, norms = fused_clipped_masked_sum(
            per_example_grads, m, clipping_bound, return_norms=True
        )
    else:
        clipped, norms = clip_per_example(per_example_grads, clipping_bound)

        def masked_sum(g):
            return jnp.sum(g * m.reshape((-1,) + (1,) * (g.ndim - 1)), axis=0)

        summed = jax.tree_util.tree_map(masked_sum, clipped)
    noise = gaussian_noise_like(rng, summed, noise_multiplier * clipping_bound)
    denom = jnp.maximum(jnp.sum(m), 1.0)
    grads = jax.tree_util.tree_map(lambda s, n: (s + n) / denom, summed, noise)
    if return_clip_fraction:
        clip_fraction = jnp.sum((norms > clipping_bound) * m) / denom
        return grads, clip_fraction
    return grads


def make_per_example_grads(
    single_example_loss: Callable[[Params, Any], jax.Array],
):
    """vmap(grad) over a batch: single_example_loss(params, example) -> scalar."""
    g = jax.grad(single_example_loss)
    return jax.vmap(g, in_axes=(None, 0))


def validate_dp_safe_model_state(model_state: Any) -> None:
    """Per-example gradients require per-example independence: mutable batch
    statistics (BatchNorm) mix examples and are rejected, mirroring the
    reference's privacy_validate_and_fix_modules
    (/root/reference/fl4health/utils/privacy_utilities.py:11) which swaps
    BatchNorm for GroupNorm. In flax, build DP models with GroupNorm/LayerNorm.
    """
    if model_state:
        bad = [k for k in model_state.keys() if k == "batch_stats"]
        if bad:
            raise ValueError(
                "DP-SGD with per-example gradients is incompatible with "
                "BatchNorm (mutable 'batch_stats' collection present). Use "
                "GroupNorm/LayerNorm in DP models, as the reference's Opacus "
                "module validator enforces."
            )

"""Renyi-DP accounting for the (subsampled) Gaussian mechanism — pure math.

The reference delegates to Google's ``dp-accounting`` RDP accountant
(/root/reference/fl4health/privacy/moments_accountant.py:64); here the math is
implemented directly (no native dependency, off the hot path):

- RDP of the Poisson-subsampled Gaussian mechanism at integer and fractional
  orders alpha, per Mironov, Talwar & Zhang, "Renyi Differential Privacy of the
  Sampled Gaussian Mechanism" (2019), Sec. 3.3 (the stable log-space series).
- Linear composition over steps (RDP adds).
- Conversion RDP -> (epsilon, delta) with the improved bound of
  Canonne-Kairouz-Steinke / Balle et al. (the same conversion dp-accounting
  uses), and RDP -> delta at fixed epsilon.

Everything is float64 NumPy/SciPy on host: accounting runs once per round at
most and never enters a jit trace.
"""

from __future__ import annotations

import math
from typing import Iterable, Sequence

import numpy as np
from scipy import special


def default_orders() -> list[float]:
    """Reference default moment orders (moments_accountant.py:85-88)."""
    low = [1.25, 1.5, 1.75, 2.0, 2.25, 2.5, 3.0, 3.5, 4.0, 4.5]
    medium = [float(x) for x in range(5, 64)]
    high = [128.0, 256.0, 512.0]
    return low + medium + high


# ---------------------------------------------------------------------------
# log-space helpers
# ---------------------------------------------------------------------------

def _log_add(a: float, b: float) -> float:
    if a == -np.inf:
        return b
    if b == -np.inf:
        return a
    hi, lo = max(a, b), min(a, b)
    return hi + math.log1p(math.exp(lo - hi))


def _log_sub(a: float, b: float) -> float:
    """log(exp(a) - exp(b)); requires a >= b."""
    if b == -np.inf:
        return a
    if a == b:
        return -np.inf
    return a + math.log1p(-math.exp(b - a))


def _log_erfc(x: float) -> float:
    """log(erfc(x)), stable for large x: erfc(x) = 2 * Phi(-sqrt(2) x)."""
    return math.log(2.0) + special.log_ndtr(-x * math.sqrt(2.0))


def _log_comb(n: float, k: int) -> float:
    return (
        special.gammaln(n + 1) - special.gammaln(k + 1) - special.gammaln(n - k + 1)
    )


# ---------------------------------------------------------------------------
# RDP of the sampled Gaussian mechanism
# ---------------------------------------------------------------------------

def _log_a_int(q: float, sigma: float, alpha: int) -> float:
    """log E_{k~Bin(alpha,q)}[exp(k(k-1)/(2 sigma^2))] — exact for integer alpha."""
    log_a = -np.inf
    for i in range(alpha + 1):
        log_coef = (
            _log_comb(alpha, i)
            + i * math.log(q)
            + (alpha - i) * math.log1p(-q)
        )
        log_a = _log_add(log_a, log_coef + (i * i - i) / (2.0 * sigma**2))
    return log_a


def _log_a_frac(q: float, sigma: float, alpha: float) -> float:
    """Fractional-order series (Mironov et al. 2019, Sec 3.3), log-space."""
    log_a0, log_a1 = -np.inf, -np.inf
    z0 = sigma**2 * math.log(1.0 / q - 1.0) + 0.5
    i = 0
    while True:
        coef = special.binom(alpha, i)
        log_coef = math.log(abs(coef)) if coef != 0 else -np.inf
        j = alpha - i

        log_t0 = log_coef + i * math.log(q) + j * math.log1p(-q)
        log_t1 = log_coef + j * math.log(q) + i * math.log1p(-q)

        log_e0 = math.log(0.5) + _log_erfc((i - z0) / (math.sqrt(2.0) * sigma))
        log_e1 = math.log(0.5) + _log_erfc((z0 - j) / (math.sqrt(2.0) * sigma))

        log_s0 = log_t0 + (i * i - i) / (2.0 * sigma**2) + log_e0
        log_s1 = log_t1 + (j * j - j) / (2.0 * sigma**2) + log_e1

        if coef > 0:
            log_a0 = _log_add(log_a0, log_s0)
            log_a1 = _log_add(log_a1, log_s1)
        else:
            log_a0 = _log_sub(log_a0, log_s0)
            log_a1 = _log_sub(log_a1, log_s1)

        i += 1
        if max(log_s0, log_s1) < -30 and i > alpha:
            break
    return _log_add(log_a0, log_a1)


def rdp_poisson_subsampled_gaussian(
    q: float, noise_multiplier: float, orders: Sequence[float]
) -> np.ndarray:
    """RDP(alpha) of ONE step of the Poisson-subsampled Gaussian mechanism.

    add-or-remove-one adjacency; ``q`` is the Poisson inclusion probability,
    ``noise_multiplier`` the sigma on a sensitivity-1 sum.
    """
    sigma = float(noise_multiplier)
    out = np.zeros(len(orders), dtype=np.float64)
    for idx, alpha in enumerate(orders):
        if q == 0.0:
            out[idx] = 0.0
        elif sigma == 0.0 or alpha <= 1.0:
            out[idx] = np.inf
        elif q == 1.0:
            out[idx] = alpha / (2.0 * sigma**2)
        else:
            if float(alpha).is_integer():
                log_a = _log_a_int(q, sigma, int(alpha))
            else:
                log_a = _log_a_frac(q, sigma, float(alpha))
            out[idx] = log_a / (alpha - 1.0)
    return out


def rdp_gaussian(noise_multiplier: float, orders: Sequence[float]) -> np.ndarray:
    """RDP(alpha) of the plain Gaussian mechanism: alpha / (2 sigma^2)."""
    sigma = float(noise_multiplier)
    orders_arr = np.asarray(orders, dtype=np.float64)
    if sigma == 0.0:
        return np.full_like(orders_arr, np.inf)
    return orders_arr / (2.0 * sigma**2)


def rdp_sampled_without_replacement_gaussian(
    population: int, sample: int, noise_multiplier: float, orders: Sequence[float]
) -> np.ndarray:
    """Conservative RDP bound for fixed-size sampling WITHOUT replacement under
    the replace-one adjacency. dp-accounting implements the
    Wang-Balle-Kasiviswanathan amplification bound here; we instead use the
    sound amplification-FREE bound: condition on the worst case that the
    replaced element is in the sample, where the Gaussian query's sensitivity
    is 2 (one contribution removed, one added), giving
    RDP(alpha) = alpha * 2^2 / (2 sigma^2) = 2 alpha / sigma^2.
    Ignoring amplification only over-estimates epsilon — never a privacy
    soundness risk. (WBK amplification is a tightening left for later.)
    """
    del population, sample  # amplification-free bound doesn't use them
    return 4.0 * rdp_gaussian(noise_multiplier, orders)


# ---------------------------------------------------------------------------
# RDP -> (epsilon, delta)
# ---------------------------------------------------------------------------

def epsilon_from_rdp(
    orders: Sequence[float], rdp: Iterable[float], delta: float
) -> float:
    """min over alpha of the CKS/Balle conversion:
    eps = rdp + log((alpha-1)/alpha) - (log(delta) + log(alpha)) / (alpha - 1).
    """
    if delta <= 0 or delta >= 1:
        raise ValueError("delta must be in (0, 1)")
    best = np.inf
    for alpha, r in zip(orders, rdp):
        if alpha <= 1 or not np.isfinite(r):
            continue
        eps = (
            r
            + math.log1p(-1.0 / alpha)
            - (math.log(delta) + math.log(alpha)) / (alpha - 1.0)
        )
        best = min(best, max(eps, 0.0))
    return float(best)


def delta_from_rdp(
    orders: Sequence[float], rdp: Iterable[float], epsilon: float
) -> float:
    """min over alpha of delta = exp((alpha-1)(rdp - eps)) (Mironov Prop. 3),
    with the sharper log(alpha)/(alpha-1) refinement applied when favorable."""
    if epsilon < 0:
        raise ValueError("epsilon must be >= 0")
    best_log = 0.0  # delta <= 1
    for alpha, r in zip(orders, rdp):
        if alpha <= 1 or not np.isfinite(r):
            continue
        log_delta = (alpha - 1.0) * (r - epsilon)
        # refinement from the CKS conversion, valid for the same mechanism:
        refined = (alpha - 1.0) * (
            r - epsilon + math.log1p(-1.0 / alpha)
        ) - math.log(alpha)
        log_delta = min(log_delta, refined)
        best_log = min(best_log, log_delta)
    return float(min(1.0, math.exp(best_log)))

"""Reporters — metric/event sinks.

Parity: /root/reference/fl4health/reporting/ — BaseReporter
(base_reporter.py:10) with initialize/report(data, round, epoch, step)/
shutdown; ReportsManager fan-out (reports_manager.py:7); JsonReporter /
FileReporter (json_reporter.py:12,89) dumping a nested rounds dict (smoke
tests assert against it); WandBReporter (wandb_reporter.py:21).
"""

from __future__ import annotations

import datetime
import json
import os
import uuid
from typing import Any, Mapping, Sequence


class BaseReporter:
    def initialize(self, **kwargs: Any) -> None:
        pass

    def report(
        self,
        data: Mapping[str, Any],
        round: int | None = None,
        epoch: int | None = None,
        step: int | None = None,
    ) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class ReportsManager:
    """Fan-out to a set of reporters (reports_manager.py:7)."""

    def __init__(self, reporters: Sequence[BaseReporter] = ()):  # noqa: D401
        self.reporters = list(reporters)

    def initialize(self, **kwargs):
        for r in self.reporters:
            r.initialize(**kwargs)

    def report(self, data, round=None, epoch=None, step=None):
        for r in self.reporters:
            r.report(data, round=round, epoch=epoch, step=step)

    def shutdown(self):
        for r in self.reporters:
            r.shutdown()


class JsonReporter(BaseReporter):
    """Accumulate a nested dict {metadata..., rounds: {r: {...}}} and dump to
    JSON on shutdown (json_reporter.py:12). Smoke tests read this output."""

    def __init__(self, output_folder: str = ".", run_id: str | None = None):
        self.run_id = run_id or str(uuid.uuid4())
        self.output_folder = output_folder
        self.data: dict = {"rounds": {}}

    def report(self, data, round=None, epoch=None, step=None):
        if round is None:
            self.data.update(_jsonify(data))
        else:
            rd = self.data["rounds"].setdefault(str(round), {})
            if epoch is not None:
                rd = rd.setdefault("epochs", {}).setdefault(str(epoch), {})
            if step is not None:
                rd = rd.setdefault("steps", {}).setdefault(str(step), {})
            rd.update(_jsonify(data))

    def dump(self) -> str:
        os.makedirs(self.output_folder, exist_ok=True)
        path = os.path.join(self.output_folder, f"{self.run_id}.json")
        with open(path, "w") as f:
            json.dump(self.data, f, indent=2)
        return path

    def shutdown(self):
        self.dump()


def _jsonify(data: Mapping[str, Any]) -> dict:
    out = {}
    for k, v in data.items():
        if isinstance(v, Mapping):
            out[k] = _jsonify(v)
        elif isinstance(v, (int, float, str, bool, type(None))):
            out[k] = v
        elif isinstance(v, datetime.datetime):
            out[k] = v.isoformat()
        else:
            try:
                out[k] = float(v)
            except (TypeError, ValueError):
                out[k] = str(v)
    return out


class WandBReporter(BaseReporter):
    """wandb sink (wandb_reporter.py:21). Lazily imports wandb; degrades to a
    no-op with a warning when wandb is unavailable/offline."""

    def __init__(self, project: str = "fl4health_tpu", **init_kwargs):
        self.project = project
        self.init_kwargs = init_kwargs
        self._run = None

    def initialize(self, **kwargs):
        try:
            import wandb  # type: ignore

            self._run = wandb.init(project=self.project, **self.init_kwargs)
        except Exception:
            self._run = None

    def report(self, data, round=None, epoch=None, step=None):
        if self._run is None:
            return
        payload = dict(_jsonify(data))
        if round is not None:
            payload["round"] = round
        self._run.log(payload)

    def shutdown(self):
        if self._run is not None:
            self._run.finish()

"""Reporters — metric/event sinks.

Parity: /root/reference/fl4health/reporting/ — BaseReporter
(base_reporter.py:10) with initialize/report(data, round, epoch, step)/
shutdown; ReportsManager fan-out (reports_manager.py:7); JsonReporter /
FileReporter (json_reporter.py:12,89) dumping a nested rounds dict (smoke
tests assert against it); WandBReporter (wandb_reporter.py:21).
"""

from __future__ import annotations

import datetime
import json
import logging
import os
import uuid
from typing import Any, Mapping, Sequence

import numpy as np

from fl4health_tpu.core.io import atomic_write

logger = logging.getLogger(__name__)

# Arrays up to this many elements serialize as JSON lists; larger ones are
# summarized (a reporter dict is a log line, not a checkpoint format).
_MAX_ARRAY_ELEMENTS = 64


class BaseReporter:
    def initialize(self, **kwargs: Any) -> None:
        pass

    def report(
        self,
        data: Mapping[str, Any],
        round: int | None = None,
        epoch: int | None = None,
        step: int | None = None,
    ) -> None:
        raise NotImplementedError

    def shutdown(self) -> None:
        pass


class ReportsManager:
    """Fan-out to a set of reporters (reports_manager.py:7)."""

    def __init__(self, reporters: Sequence[BaseReporter] = ()):  # noqa: D401
        self.reporters = list(reporters)

    def initialize(self, **kwargs):
        for r in self.reporters:
            r.initialize(**kwargs)

    def report(self, data, round=None, epoch=None, step=None):
        for r in self.reporters:
            r.report(data, round=round, epoch=epoch, step=step)

    def shutdown(self):
        for r in self.reporters:
            r.shutdown()


class JsonReporter(BaseReporter):
    """Accumulate a nested dict {metadata..., rounds: {r: {...}}} and dump to
    JSON on shutdown (json_reporter.py:12). Smoke tests read this output."""

    def __init__(self, output_folder: str = ".", run_id: str | None = None):
        self.run_id = run_id or str(uuid.uuid4())
        self.output_folder = output_folder
        self.data: dict = {"rounds": {}}

    def report(self, data, round=None, epoch=None, step=None):
        if round is None:
            self.data.update(_jsonify(data))
        else:
            rd = self.data["rounds"].setdefault(str(round), {})
            if epoch is not None:
                rd = rd.setdefault("epochs", {}).setdefault(str(epoch), {})
            if step is not None:
                rd = rd.setdefault("steps", {}).setdefault(str(step), {})
            rd.update(_jsonify(data))

    def dump(self) -> str:
        # Atomic publish: dump() runs per shutdown and on per-round state
        # checkpoints; a crash mid-write must never leave a truncated JSON
        # that poisons the smoke-test reader.
        path = os.path.join(self.output_folder, f"{self.run_id}.json")
        with atomic_write(path) as f:
            json.dump(self.data, f, indent=2)
        return path

    def shutdown(self):
        self.dump()


def _jsonify(data: Mapping[str, Any]) -> dict:
    out = {}
    for k, v in data.items():
        if isinstance(v, Mapping):
            out[k] = _jsonify(v)
        elif isinstance(v, (int, float, str, bool, type(None))):
            out[k] = v
        elif isinstance(v, datetime.datetime):
            out[k] = v.isoformat()
        elif hasattr(v, "shape") and hasattr(v, "dtype"):
            # numpy / JAX arrays: 0-d -> Python scalar, small -> nested
            # lists, big -> a shape/dtype summary string (previously
            # non-scalar arrays fell through to str(v), mangling them into
            # unparseable reprs). Size-gate on shape METADATA before
            # np.asarray: a big on-device array must not pay a blocking
            # device->host transfer just to be summarized away.
            shape = tuple(v.shape)
            size = int(np.prod(shape, dtype=np.int64)) if shape else 1
            if shape and size > _MAX_ARRAY_ELEMENTS:
                out[k] = f"array(shape={shape}, dtype={v.dtype})"
            else:
                arr = np.asarray(v)
                out[k] = arr.item() if arr.ndim == 0 else arr.tolist()
        elif isinstance(v, (list, tuple)):
            out[k] = [
                _jsonify({"_": item})["_"]
                for item in v
            ]
        else:
            try:
                out[k] = float(v)
            except (TypeError, ValueError):
                out[k] = str(v)
    return out


class WandBReporter(BaseReporter):
    """wandb sink (wandb_reporter.py:21). Lazily imports wandb; degrades to a
    no-op with a warning when wandb is unavailable/offline."""

    def __init__(self, project: str = "fl4health_tpu", **init_kwargs):
        self.project = project
        self.init_kwargs = init_kwargs
        self._run = None

    def initialize(self, **kwargs):
        try:
            import wandb  # type: ignore

            self._run = wandb.init(project=self.project, **self.init_kwargs)
        except Exception as e:
            # the docstring's promised degradation is "no-op WITH a warning";
            # swallowing silently hid misconfigured runs for entire jobs
            logger.warning(
                "WandBReporter disabled (wandb init failed: %s: %s); "
                "reports will be dropped.", type(e).__name__, e,
            )
            self._run = None

    def report(self, data, round=None, epoch=None, step=None):
        if self._run is None:
            return
        payload = dict(_jsonify(data))
        if round is not None:
            payload["round"] = round
        self._run.log(payload)

    def shutdown(self):
        if self._run is not None:
            self._run.finish()

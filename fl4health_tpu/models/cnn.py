"""Reference example models, flax-native.

Parity targets: /root/reference/examples/models/cnn_model.py (the ``Net``
CIFAR CNN and MNIST variants used throughout the smoke tests). These are
capability equivalents — conv stacks sized for the MXU (channel counts padded
to friendly multiples where it costs nothing).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

from fl4health_tpu.precision.policy import conv_compute_dtype


class MnistNet(nn.Module):
    """Small MNIST CNN (examples/models/cnn_model.py MnistNet equivalent):
    two conv+pool blocks then two dense layers."""

    n_classes: int = 10
    hidden: int = 120

    @nn.compact
    def __call__(self, x, train: bool = True):
        # x: [B, 28, 28, 1] (NHWC — TPU-native layout)
        x = nn.Conv(16, (5, 5))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(32, (5, 5))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        features = nn.relu(nn.Dense(self.hidden)(x))
        logits = nn.Dense(self.n_classes)(features)
        return {"prediction": logits}, {"features": features}


_CONV_SPATIAL_CHARS = "DHW"  # trailing chars; rank picks the suffix


def _conv_dimension_numbers(rank: int) -> tuple[str, str, str]:
    """Channels-last dimension-number strings for any spatial rank
    (1D "NWC", 2D "NHWC", 3D "NDHWC")."""
    spatial = _CONV_SPATIAL_CHARS[-rank:]
    return (f"N{spatial}C", f"{spatial}IO", f"N{spatial}C")


class MxuConv(nn.Module):
    """N-D convolution lowered as im2col + matmul, parameter-compatible with
    ``nn.Conv`` (same spatial+IO kernel + bias shapes, same output up to
    float association). The spatial rank comes from ``len(kernel_size)``
    (2-D and 3-D are the exercised cases).

    Why it exists: the cohort engine vmaps local training over a leading
    [clients] axis of per-client WEIGHTS, which turns every ``nn.Conv`` into
    a batched-kernel (grouped) convolution. That lowering is the suspected
    TPU MFU limiter for the cohort CNN (BENCH_r03 note) — and worse: when
    the clients axis is SHARDED over a mesh, XLA's grouped-conv partitioner
    can reject the op outright (feature_group_count divisibility,
    tests/parallel/test_sharded_mesh.py's segmentation round). Patch
    extraction (``conv_general_dilated_patches``) is weight-independent, so
    under the clients-vmap it stays an unbatched op, and the only batched op
    left is a plain ``dot_general`` with a leading batch dim — the shape the
    MXU is built for, and one that shards over the clients axis without
    constraint.

    Measured (2026-07): on XLA:CPU ~3.4x slower than grouped conv (the
    patches backward lowers to a col2im scatter-add). The TPU A/B answered
    the open BENCH_r03 question: on a real v5e
    (`BENCH_tpu_20260731_034629.json` ``conv_mxu_alt``) im2col reaches only
    606 steps/s vs grouped conv's 3186 — XLA:TPU lowers the vmapped grouped
    conv onto the MXU just fine, so ``lax`` stays the default everywhere the
    partitioner accepts it. MxuConv's role is therefore NOT speed: for
    sharded-clients meshes it is the path that compiles at all (the
    partitioner rejection above), and it is what makes segmentation rounds
    shardable over the clients axis.
    """

    features: int
    kernel_size: tuple[int, ...] = (3, 3)
    padding: str = "SAME"
    # None = nn.Conv's dtype=None semantics: ONE promotion rule —
    # precision.policy.conv_compute_dtype, result_type over input + kernel
    # + bias (flax's promote_dtype includes the bias; an earlier version
    # here omitted it, which could diverge from nn.Conv under mixed-dtype
    # params). Under the engine-level precision cast every operand is
    # already the policy dtype, so the rule degenerates to it — keeping the
    # lax/mxu impls numerically interchangeable at bf16 (parity pinned by
    # tests/models/test_mxu_conv.py).
    dtype: jnp.dtype | None = None
    strides: tuple[int, ...] | None = None

    @nn.compact
    def __call__(self, x):
        ks = tuple(self.kernel_size)
        rank = len(ks)
        cin = x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (*ks, cin, self.features),
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        dtype = (self.dtype if self.dtype is not None
                 else conv_compute_dtype(x.dtype, kernel.dtype, bias.dtype))
        patches = jax.lax.conv_general_dilated_patches(
            x.astype(dtype), ks,
            tuple(self.strides) if self.strides else (1,) * rank,
            self.padding,
            dimension_numbers=_conv_dimension_numbers(rank),
        )
        # patches feature dim is ordered (cin, *kernel); fold the kernel the
        # same way so parameters stay interchangeable with nn.Conv.
        w = jnp.transpose(kernel, (rank, *range(rank), rank + 1)).reshape(
            cin * int(np.prod(ks)), self.features
        )
        y = patches @ w.astype(dtype)
        return y + bias.astype(dtype)


def resolve_conv_impl(impl: str, *, sharded_clients: bool = False) -> str:
    """Resolve ``"auto"`` to a concrete conv impl per the measured policy:

    ``"lax"`` (grouped-conv ``nn.Conv``) everywhere XLA accepts it — the
    real-TPU A/B in the :class:`MxuConv` docstring measured grouped conv
    3186 vs im2col's 606 steps/s on a v5e, so im2col is never a speed play;
    ``"mxu"`` only where the grouped-conv partitioner REJECTS the vmapped
    ``nn.Conv``: clients-axis-sharded meshes (``sharded_clients=True`` —
    the ``tests/parallel/test_sharded_mesh.py`` segmentation case), where
    the weight-independent patch extraction is the lowering that compiles
    at all. Concrete impls pass through unchanged."""
    if impl == "auto":
        return "mxu" if sharded_clients else "lax"
    if impl not in ("lax", "mxu"):
        raise ValueError(
            f"conv impl must be 'lax', 'mxu' or 'auto', got {impl!r}"
        )
    return impl


def make_conv(
    impl: str,
    features: int,
    kernel_size: tuple[int, ...],
    *,
    strides: tuple[int, ...] | None = None,
    padding: str = "SAME",
    dtype: jnp.dtype | None = None,
    name: str | None = None,
) -> nn.Module:
    """The ONE conv-impl switch ("lax" = nn.Conv, "mxu" = MxuConv) shared by
    every model that offers the knob (CifarNet, the U-Net blocks/heads).
    ``"auto"`` resolves via :func:`resolve_conv_impl`; a module cannot know
    at trace time whether its clients axis is mesh-sharded, so ``"auto"``
    here assumes unsharded ("lax") — callers building for a
    clients-sharded mesh resolve with ``sharded_clients=True`` first (the
    bench's ``make_sim`` does).

    Callers must pass ``name`` matching nn.Conv's auto-name for that call
    site ("Conv_0", "Conv_1", ...): both impls then produce identical param
    paths, hence identical RNG-keyed initial values, so checkpoints and
    exchanger path filters are impl-agnostic.
    """
    impl = resolve_conv_impl(impl)
    if impl == "mxu":
        return MxuConv(features, tuple(kernel_size), strides=strides,
                       padding=padding, dtype=dtype, name=name)
    return nn.Conv(features, tuple(kernel_size), strides=strides,
                   padding=padding, dtype=dtype, use_bias=True, name=name)


class CifarNet(nn.Module):
    """CIFAR-10 CNN (examples/models/cnn_model.py Net equivalent).

    ``dtype`` sets the compute dtype (params stay fp32): bf16 here is the
    TPU mixed-precision path — MXU-native matmuls/convs, fp32 logits out.
    ``conv_impl``: "lax" uses ``nn.Conv``; "mxu" uses the im2col ``MxuConv``
    (identical params/outputs, radically better lowering under the
    per-client-weights vmap — see MxuConv).
    """

    n_classes: int = 10
    dtype: jnp.dtype = jnp.float32
    conv_impl: str = "lax"

    @nn.compact
    def __call__(self, x, train: bool = True):
        # x: [B, 32, 32, 3]
        x = x.astype(self.dtype)
        x = make_conv(self.conv_impl, 32, (5, 5), dtype=self.dtype,
                      name="Conv_0")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = make_conv(self.conv_impl, 64, (5, 5), dtype=self.dtype,
                      name="Conv_1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        features = nn.relu(nn.Dense(128, dtype=self.dtype)(x))
        logits = nn.Dense(self.n_classes, dtype=self.dtype)(features)
        return {"prediction": logits.astype(jnp.float32)}, {"features": features}


class Mlp(nn.Module):
    """Generic MLP used by tabular / synthetic examples."""

    features: Sequence[int] = (64, 32)
    n_outputs: int = 2

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        for f in self.features:
            x = nn.relu(nn.Dense(f)(x))
        logits = nn.Dense(self.n_outputs)(x)
        return {"prediction": logits}, {"features": x}


class LogisticRegression(nn.Module):
    n_outputs: int = 2

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        return {"prediction": nn.Dense(self.n_outputs)(x)}, {}

"""Reference example models, flax-native.

Parity targets: /root/reference/examples/models/cnn_model.py (the ``Net``
CIFAR CNN and MNIST variants used throughout the smoke tests). These are
capability equivalents — conv stacks sized for the MXU (channel counts padded
to friendly multiples where it costs nothing).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn


class MnistNet(nn.Module):
    """Small MNIST CNN (examples/models/cnn_model.py MnistNet equivalent):
    two conv+pool blocks then two dense layers."""

    n_classes: int = 10
    hidden: int = 120

    @nn.compact
    def __call__(self, x, train: bool = True):
        # x: [B, 28, 28, 1] (NHWC — TPU-native layout)
        x = nn.Conv(16, (5, 5))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(32, (5, 5))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        features = nn.relu(nn.Dense(self.hidden)(x))
        logits = nn.Dense(self.n_classes)(features)
        return {"prediction": logits}, {"features": features}


class CifarNet(nn.Module):
    """CIFAR-10 CNN (examples/models/cnn_model.py Net equivalent).

    ``dtype`` sets the compute dtype (params stay fp32): bf16 here is the
    TPU mixed-precision path — MXU-native matmuls/convs, fp32 logits out.
    """

    n_classes: int = 10
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = True):
        # x: [B, 32, 32, 3]
        x = x.astype(self.dtype)
        x = nn.Conv(32, (5, 5), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(64, (5, 5), dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        features = nn.relu(nn.Dense(128, dtype=self.dtype)(x))
        logits = nn.Dense(self.n_classes, dtype=self.dtype)(features)
        return {"prediction": logits.astype(jnp.float32)}, {"features": features}


class Mlp(nn.Module):
    """Generic MLP used by tabular / synthetic examples."""

    features: Sequence[int] = (64, 32)
    n_outputs: int = 2

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        for f in self.features:
            x = nn.relu(nn.Dense(f)(x))
        logits = nn.Dense(self.n_outputs)(x)
        return {"prediction": logits}, {"features": x}


class LogisticRegression(nn.Module):
    n_outputs: int = 2

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        return {"prediction": nn.Dense(self.n_outputs)(x)}, {}

"""Reference example models, flax-native.

Parity targets: /root/reference/examples/models/cnn_model.py (the ``Net``
CIFAR CNN and MNIST variants used throughout the smoke tests). These are
capability equivalents — conv stacks sized for the MXU (channel counts padded
to friendly multiples where it costs nothing).
"""

from __future__ import annotations

from typing import Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn


class MnistNet(nn.Module):
    """Small MNIST CNN (examples/models/cnn_model.py MnistNet equivalent):
    two conv+pool blocks then two dense layers."""

    n_classes: int = 10
    hidden: int = 120

    @nn.compact
    def __call__(self, x, train: bool = True):
        # x: [B, 28, 28, 1] (NHWC — TPU-native layout)
        x = nn.Conv(16, (5, 5))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = nn.Conv(32, (5, 5))(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        features = nn.relu(nn.Dense(self.hidden)(x))
        logits = nn.Dense(self.n_classes)(features)
        return {"prediction": logits}, {"features": features}


class MxuConv(nn.Module):
    """2-D convolution lowered as im2col + matmul, parameter-compatible with
    ``nn.Conv`` (same HWIO kernel + bias shapes, same output up to float
    association).

    Why it exists: the cohort engine vmaps local training over a leading
    [clients] axis of per-client WEIGHTS, which turns every ``nn.Conv`` into
    a batched-kernel (grouped) convolution — the suspected TPU MFU limiter
    for the cohort CNN (BENCH_r03 note). Patch extraction
    (``conv_general_dilated_patches``) is weight-independent, so under the
    clients-vmap it stays a single unbatched op, and the only batched op
    left is a plain ``dot_general`` with a leading batch dim — the shape the
    MXU is built for.

    Measured caveat (2026-07, 8-client vmapped CifarNet train step): on
    XLA:CPU this path is ~3.4x SLOWER than the grouped-conv lowering — the
    patches BACKWARD is a col2im scatter-add, which XLA:CPU runs poorly.
    The TPU comparison is the one that matters and must be measured there
    (``FL4HEALTH_BENCH_CONV=mxu``); this module is the experiment vehicle,
    not a universally-better default.
    """

    features: int
    kernel_size: tuple[int, int] = (3, 3)
    padding: str = "SAME"
    dtype: jnp.dtype = jnp.float32

    @nn.compact
    def __call__(self, x):
        kh, kw = self.kernel_size
        cin = x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (kh, kw, cin, self.features),
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        patches = jax.lax.conv_general_dilated_patches(
            x.astype(self.dtype), (kh, kw), (1, 1), self.padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        # patches feature dim is ordered (cin, kh, kw); fold the kernel the
        # same way so parameters stay interchangeable with nn.Conv.
        w = jnp.transpose(kernel, (2, 0, 1, 3)).reshape(
            cin * kh * kw, self.features
        )
        y = patches @ w.astype(self.dtype)
        return y + bias.astype(self.dtype)


class CifarNet(nn.Module):
    """CIFAR-10 CNN (examples/models/cnn_model.py Net equivalent).

    ``dtype`` sets the compute dtype (params stay fp32): bf16 here is the
    TPU mixed-precision path — MXU-native matmuls/convs, fp32 logits out.
    ``conv_impl``: "lax" uses ``nn.Conv``; "mxu" uses the im2col ``MxuConv``
    (identical params/outputs, radically better lowering under the
    per-client-weights vmap — see MxuConv).
    """

    n_classes: int = 10
    dtype: jnp.dtype = jnp.float32
    conv_impl: str = "lax"

    def _conv(self, features, kernel_size, name):
        # Explicit names pin BOTH impls to the same param paths ("Conv_0",
        # "Conv_1" — nn.Conv's auto-names), so the tree structure, the
        # RNG-keyed initial values, and any checkpoint/exchange path filters
        # are identical regardless of conv_impl.
        if self.conv_impl == "mxu":
            return MxuConv(features, kernel_size, dtype=self.dtype, name=name)
        return nn.Conv(features, kernel_size, dtype=self.dtype, name=name)

    @nn.compact
    def __call__(self, x, train: bool = True):
        # x: [B, 32, 32, 3]
        x = x.astype(self.dtype)
        x = self._conv(32, (5, 5), "Conv_0")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = self._conv(64, (5, 5), "Conv_1")(x)
        x = nn.relu(x)
        x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        features = nn.relu(nn.Dense(128, dtype=self.dtype)(x))
        logits = nn.Dense(self.n_classes, dtype=self.dtype)(features)
        return {"prediction": logits.astype(jnp.float32)}, {"features": features}


class Mlp(nn.Module):
    """Generic MLP used by tabular / synthetic examples."""

    features: Sequence[int] = (64, 32)
    n_outputs: int = 2

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        for f in self.features:
            x = nn.relu(nn.Dense(f)(x))
        logits = nn.Dense(self.n_outputs)(x)
        return {"prediction": logits}, {"features": x}


class LogisticRegression(nn.Module):
    n_outputs: int = 2

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        return {"prediction": nn.Dense(self.n_outputs)(x)}, {}

"""Masked layers for FedPM — Bernoulli-score parameter masking.

Parity targets (/root/reference/fl4health/model_bases/masked_layers/):
- masked_linear.py:11 MaskedLinear, masked_conv.py:15-720 MaskedConv1d/2d/3d +
  transposed variants, masked_normalization_layers.py:19-313 MaskedLayerNorm /
  MaskedBatchNorm*: the underlying weight/bias are FROZEN; learnable "score"
  tensors are passed through a sigmoid to Bernoulli probabilities, a binary
  mask is sampled each forward, and ``mask * weight`` is applied. Gradients
  reach the scores through the straight-through estimator
  (utils/functions.py:10 BernoulliSample: backward = probs * grad).
- masked_layers_utils.py:23 convert_to_masked_model (module swap in place).

TPU-native design: frozen weights live in a ``frozen`` variable collection
(part of the engine's model_state, never touched by the optimizer); scores
are ordinary flax ``params`` so every optimizer/exchanger works unchanged.
Mask sampling uses the ``mask`` PRNG stream when provided; without it (e.g.
deterministic evaluation) the expected mask ``probs`` is used instead of a
sample — torch's global-RNG sampling during eval has no jit-safe equivalent,
and the expectation is the variance-free estimator of the same forward.
"""

from __future__ import annotations

from collections.abc import Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn


@jax.custom_vjp
def bernoulli_ste(probs: jax.Array, rng: jax.Array) -> jax.Array:
    """Bernoulli sample with the straight-through 'gradient' = probs * g
    (utils/functions.py:10, per Bengio et al. 1308.3432 §4)."""
    return jax.random.bernoulli(rng, probs).astype(probs.dtype)


def _bernoulli_fwd(probs, rng):
    return bernoulli_ste(probs, rng), probs


def _bernoulli_bwd(probs, g):
    return probs * g, None


bernoulli_ste.defvjp(_bernoulli_fwd, _bernoulli_bwd)


class _MaskedMixin:
    """Shared score-init + mask-sampling for all masked layers."""

    def _masked(self, name: str, value: jax.Array) -> jax.Array:
        """Sample (or take the expectation of) the binary mask for a frozen
        tensor and apply it."""
        scores = self.param(f"{name}_scores", nn.initializers.normal(1.0), value.shape)
        probs = jax.nn.sigmoid(scores)
        if self.has_rng("mask"):
            mask = bernoulli_ste(probs, self.make_rng("mask"))
        else:
            mask = probs  # deterministic expectation (eval without an rng)
        return mask * value

    def _frozen(self, name: str, init, shape) -> jax.Array:
        var = self.variable("frozen", name, init, shape)
        return var.value


def _dim_numbers(n_spatial: int):
    """Channel-last conv dimension numbers for 1/2/3 spatial dims."""
    spatial = ("W", "HW", "DHW")[n_spatial - 1]
    return (f"N{spatial}C", f"{spatial}IO", f"N{spatial}C")


class MaskedDense(_MaskedMixin, nn.Module):
    """Masked linear layer (masked_linear.py:11)."""

    features: int
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        kernel = self._frozen(
            "kernel",
            lambda shape: nn.initializers.lecun_normal()(self.make_rng("params"), shape),
            (x.shape[-1], self.features),
        )
        y = x @ self._masked("kernel", kernel)
        if self.use_bias:
            bias = self._frozen("bias", lambda s: jnp.zeros(s), (self.features,))
            y = y + self._masked("bias", bias)
        return y


class MaskedConv(_MaskedMixin, nn.Module):
    """Masked N-D convolution (masked_conv.py:15,144,270 for 1d/2d/3d —
    dimensionality follows len(kernel_size))."""

    features: int
    kernel_size: Sequence[int]
    strides: Sequence[int] | None = None
    padding: str = "SAME"
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        ksize = tuple(self.kernel_size)
        in_features = x.shape[-1]
        kernel = self._frozen(
            "kernel",
            lambda shape: nn.initializers.lecun_normal()(self.make_rng("params"), shape),
            (*ksize, in_features, self.features),
        )
        masked_kernel = self._masked("kernel", kernel)
        y = jax.lax.conv_general_dilated(
            x, masked_kernel,
            window_strides=tuple(self.strides) if self.strides else (1,) * len(ksize),
            padding=self.padding, dimension_numbers=_dim_numbers(len(ksize)),
        )
        if self.use_bias:
            bias = self._frozen("bias", lambda s: jnp.zeros(s), (self.features,))
            y = y + self._masked("bias", bias)
        return y


class MaskedConvTranspose(_MaskedMixin, nn.Module):
    """Masked N-D transposed convolution (masked_conv.py:396-720)."""

    features: int
    kernel_size: Sequence[int]
    strides: Sequence[int] | None = None
    padding: str = "SAME"
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        ksize = tuple(self.kernel_size)
        in_features = x.shape[-1]
        kernel = self._frozen(
            "kernel",
            lambda shape: nn.initializers.lecun_normal()(self.make_rng("params"), shape),
            (*ksize, in_features, self.features),
        )
        masked_kernel = self._masked("kernel", kernel)
        y = jax.lax.conv_transpose(
            x, masked_kernel,
            strides=tuple(self.strides) if self.strides else (1,) * len(ksize),
            padding=self.padding,
        )
        if self.use_bias:
            bias = self._frozen("bias", lambda s: jnp.zeros(s), (self.features,))
            y = y + self._masked("bias", bias)
        return y


class MaskedLayerNorm(_MaskedMixin, nn.Module):
    """Masked LayerNorm (masked_normalization_layers.py:19): normalization is
    standard; the frozen affine scale/bias are masked."""

    epsilon: float = 1e-6

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        mean = jnp.mean(x, axis=-1, keepdims=True)
        var = jnp.var(x, axis=-1, keepdims=True)
        y = (x - mean) / jnp.sqrt(var + self.epsilon)
        scale = self._frozen("scale", lambda s: jnp.ones(s), (x.shape[-1],))
        bias = self._frozen("bias", lambda s: jnp.zeros(s), (x.shape[-1],))
        return y * self._masked("scale", scale) + self._masked("bias", bias)


class MaskedBatchNorm(_MaskedMixin, nn.Module):
    """Masked BatchNorm (masked_normalization_layers.py:147): running stats
    behave as in nn.BatchNorm (batch_stats collection); the frozen affine
    parameters are masked."""

    # torch momentum=0.1 (reference masked batch norm default) == flax-style
    # decay 0.9: running stats adapt at the reference's rate.
    momentum: float = 0.9
    epsilon: float = 1e-5

    @nn.compact
    def __call__(self, x: jax.Array, use_running_average: bool = False) -> jax.Array:
        features = x.shape[-1]
        ra_mean = self.variable("batch_stats", "mean", lambda s: jnp.zeros(s), (features,))
        ra_var = self.variable("batch_stats", "var", lambda s: jnp.ones(s), (features,))
        if use_running_average:
            mean, var = ra_mean.value, ra_var.value
        else:
            axes = tuple(range(x.ndim - 1))
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            if not self.is_initializing():
                ra_mean.value = self.momentum * ra_mean.value + (1 - self.momentum) * mean
                ra_var.value = self.momentum * ra_var.value + (1 - self.momentum) * var
        y = (x - mean) / jnp.sqrt(var + self.epsilon)
        scale = self._frozen("scale", lambda s: jnp.ones(s), (features,))
        bias = self._frozen("bias", lambda s: jnp.zeros(s), (features,))
        return y * self._masked("scale", scale) + self._masked("bias", bias)


# ---------------------------------------------------------------------------
# Ready-made masked architectures + dense-weight transplant
# ---------------------------------------------------------------------------

class MaskedMlp(nn.Module):
    """Masked counterpart of models.cnn.Mlp — the convert_to_masked_model
    analog for the standard test/bench MLP (flax module trees are static, so
    conversion is 'build the masked twin + transplant weights' rather than an
    in-place module swap)."""

    features: Sequence[int] = (64, 32)
    n_outputs: int = 2

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        for f in self.features:
            x = nn.relu(MaskedDense(f)(x))
        logits = MaskedDense(self.n_outputs)(x)
        return {"prediction": logits}, {"features": x}


class MaskedCnn(nn.Module):
    """Masked counterpart of a small conv net (masked_conv.py parity)."""

    channels: Sequence[int] = (8, 16)
    n_outputs: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        for c in self.channels:
            x = nn.relu(MaskedConv(c, (3, 3))(x))
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        x = x.reshape((x.shape[0], -1))
        logits = MaskedDense(self.n_outputs)(x)
        return {"prediction": logits}, {"features": x}


def _normalized_path(path) -> tuple:
    """Strip flax module-class prefixes so Dense_0.kernel and
    MaskedDense_0.kernel coincide: 'Name_3' segments normalize to '3'."""
    out = []
    for p in path:
        seg = str(getattr(p, "key", getattr(p, "idx", p)))
        head, _, tail = seg.rpartition("_")
        out.append(tail if head and tail.isdigit() else seg)
    return tuple(out)


def transplant_dense_weights(dense_params, frozen: dict) -> dict:
    """Copy a trained dense model's parameters into a masked model's frozen
    collection (MaskedLinear.from_pretrained parity, masked_linear.py:83).

    Matching is by module-index + parameter name with the flax class-name
    prefix stripped (Dense_0.kernel -> MaskedDense_0.kernel), since the
    masked twin's auto-generated module names differ from the dense ones.
    Shapes must agree for a leaf to be copied.
    """
    flat_dense = {
        _normalized_path(path): leaf
        for path, leaf in jax.tree_util.tree_flatten_with_path(dense_params)[0]
    }

    def replace(path, leaf):
        candidate = flat_dense.get(_normalized_path(path))
        if candidate is not None and candidate.shape == leaf.shape:
            return candidate
        return leaf

    return jax.tree_util.tree_map_with_path(replace, frozen)

"""Transformer encoder for federated sequence classification (BERT-class).

Parity surface: the reference's BERT fine-tuning capability
(/root/reference/examples/bert_finetuning_example — HF
``BertForSequenceClassification`` trained under BasicClient;
/root/reference/research/ag_news — dynamic-layer/sparse exchange on BERT;
/root/reference/examples/fedllm_example — LoRA fine-tuning via peft).

TPU-native design: a from-scratch flax encoder whose matmuls are shaped for
the MXU (d_model/d_ff multiples of 128 by default) with a ``dtype`` knob for
bf16 compute at fp32 params (the TPU mixed-precision recipe — no GradScaler
needed). Projection modules carry stable names (q_proj/k_proj/v_proj/o_proj,
ff_in/ff_out) so tensor-parallel sharding rules (parallel/tp.py) and
LoRA/PEFT path filters (utils/peft.py) can key on paths instead of module
classes. LoRA lives in ``LoraDense``: frozen-by-mask base kernel + low-rank
``lora_a @ lora_b`` delta, the pytree equivalent of peft's adapter injection
(/root/reference/fl4health/utils/peft_parameter_extraction.py:7).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import linen as nn


class LoraDense(nn.Module):
    """Dense with an additive low-rank adapter: y = xW + s * (x A) B.

    ``lora_b`` initializes to zero so the adapted model starts exactly at the
    base model (the published LoRA recipe). The base kernel/bias stay in the
    params tree (frozen via the optimizer mask, utils/peft.py) so the SAME
    pytree serves full fine-tuning and PEFT — only the mask and the
    exchanger's path filter change.
    """

    features: int
    rank: int = 0
    alpha: float = 16.0
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x):
        in_features = x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (in_features, self.features)
        )
        bias = self.param("bias", nn.initializers.zeros, (self.features,))
        y = x.astype(self.dtype) @ kernel.astype(self.dtype) + bias.astype(self.dtype)
        if self.rank > 0:
            lora_a = self.param(
                "lora_a",
                nn.initializers.normal(stddev=1.0 / self.rank),
                (in_features, self.rank),
            )
            lora_b = self.param(
                "lora_b", nn.initializers.zeros, (self.rank, self.features)
            )
            scale = self.alpha / self.rank
            y = y + scale * (
                (x.astype(self.dtype) @ lora_a.astype(self.dtype))
                @ lora_b.astype(self.dtype)
            )
        return y


class MultiHeadSelfAttention(nn.Module):
    """``attention_fn`` swaps the score/softmax/value core for an alternative
    implementation called as ``attention_fn(q, k, v, pad_mask=mask) -> out``
    (q/k/v/out all [B, T, H, D]) — e.g.
    ``functools.partial(parallel.ring_attention.ring_self_attention, mesh=m)``
    for long-context sequence parallelism over a (seq,) mesh. Attention
    dropout only applies to the default dense core (ring attention streams
    blocks and never materializes the score matrix).
    """

    d_model: int
    n_heads: int
    lora_rank: int = 0
    dtype: Any = jnp.float32
    dropout_rate: float = 0.0
    attention_fn: Any = None

    @nn.compact
    def __call__(self, x, pad_mask, train: bool):
        # x: [B, T, D]; pad_mask: [B, T] 1=token, 0=pad
        assert self.d_model % self.n_heads == 0, (
            f"d_model={self.d_model} must divide by n_heads={self.n_heads}"
        )
        head_dim = self.d_model // self.n_heads
        dense = lambda name: LoraDense(  # noqa: E731
            self.d_model, rank=self.lora_rank, dtype=self.dtype, name=name
        )
        q = dense("q_proj")(x)
        k = dense("k_proj")(x)
        v = dense("v_proj")(x)

        def split(t):
            return t.reshape(*t.shape[:-1], self.n_heads, head_dim)

        q, k, v = split(q), split(k), split(v)
        if self.attention_fn is not None:
            out = self.attention_fn(q, k, v, pad_mask=pad_mask)
        else:
            scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / jnp.sqrt(
                jnp.asarray(head_dim, self.dtype)
            )
            neg = jnp.asarray(jnp.finfo(jnp.float32).min, scores.dtype)
            scores = jnp.where(pad_mask[:, None, None, :] > 0, scores, neg)
            attn = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(
                self.dtype
            )
            if train and self.dropout_rate > 0:
                attn = nn.Dropout(self.dropout_rate, deterministic=False)(attn)
            out = jnp.einsum("bhqk,bkhd->bqhd", attn, v)
        out = out.reshape(*out.shape[:-2], self.d_model)
        return dense("o_proj")(out)


class EncoderBlock(nn.Module):
    d_model: int
    n_heads: int
    d_ff: int
    lora_rank: int = 0
    dtype: Any = jnp.float32
    dropout_rate: float = 0.0
    attention_fn: Any = None

    @nn.compact
    def __call__(self, x, pad_mask, train: bool):
        # Pre-LN (stable at small scale, standard for from-scratch training).
        h = nn.LayerNorm(name="ln_attn")(x)
        h = MultiHeadSelfAttention(
            self.d_model, self.n_heads, self.lora_rank, self.dtype,
            self.dropout_rate, self.attention_fn, name="attn",
        )(h, pad_mask, train)
        if train and self.dropout_rate > 0:
            h = nn.Dropout(self.dropout_rate, deterministic=False)(h)
        x = x + h
        h = nn.LayerNorm(name="ln_mlp")(x)
        h = LoraDense(self.d_ff, rank=self.lora_rank, dtype=self.dtype, name="ff_in")(h)
        h = nn.gelu(h)
        h = LoraDense(
            self.d_model, rank=self.lora_rank, dtype=self.dtype, name="ff_out"
        )(h)
        if train and self.dropout_rate > 0:
            h = nn.Dropout(self.dropout_rate, deterministic=False)(h)
        return x + h


class TransformerClassifier(nn.Module):
    """Encoder + mean-pool + classifier head, the AG-News/BERT-shaped model.

    Input: integer token ids [B, T]; id 0 is the pad token (mask derived
    in-model, so the engine's (x, y) batch contract holds unchanged).
    """

    vocab_size: int
    n_classes: int
    d_model: int = 128
    n_heads: int = 4
    n_layers: int = 2
    d_ff: int = 256
    max_len: int = 128
    lora_rank: int = 0
    dtype: Any = jnp.float32
    dropout_rate: float = 0.0
    attention_fn: Any = None  # e.g. ring attention for long contexts
    remat: bool = False  # rematerialize each encoder block on the backward
    # pass: activation memory drops from O(n_layers * T * d_model) to one
    # layer's worth at the cost of a second forward — the standard TPU
    # HBM-for-FLOPs trade for big-model configs (jax.checkpoint).

    @nn.compact
    def __call__(self, x, train: bool = True):
        pad_mask = (x > 0).astype(jnp.float32)
        tok = nn.Embed(self.vocab_size, self.d_model, name="tok_embed")(x)
        pos = self.param(
            "pos_embed",
            nn.initializers.normal(stddev=0.02),
            (self.max_len, self.d_model),
        )
        h = (tok + pos[None, : x.shape[1]]).astype(self.dtype)
        # static_argnums counts the module itself: (self, h, pad_mask, train)
        block_cls = nn.remat(EncoderBlock, static_argnums=(3,)) if self.remat else EncoderBlock
        for i in range(self.n_layers):
            h = block_cls(
                self.d_model, self.n_heads, self.d_ff, self.lora_rank,
                self.dtype, self.dropout_rate, self.attention_fn,
                name=f"layer_{i}",
            )(h, pad_mask, train)
        h = nn.LayerNorm(name="ln_final")(h.astype(jnp.float32))
        denom = jnp.maximum(pad_mask.sum(axis=1, keepdims=True), 1.0)
        pooled = (h * pad_mask[..., None]).sum(axis=1) / denom
        logits = nn.Dense(self.n_classes, name="classifier")(pooled)
        return {"prediction": logits.astype(jnp.float32)}, {"features": pooled}

"""Dynamic plain-conv U-Net with deep supervision — flax, channels-last.

Parity surface: the nnU-Net network the reference builds from plans
(/root/reference/fl4health/servers/nnunet_server.py:133
``initialize_server_model`` -> nnunetv2 ``build_network_architecture``;
client forward with deep-supervision list outputs,
/root/reference/fl4health/clients/nnunet_client.py:624 ``predict``).

TPU-native design: one nn.Module parameterized entirely by static plan
numbers (stages, features, strides, kernels) so a plans dict compiles to a
fixed XLA program. Layout is channels-last ([B, *spatial, C]) so convs lower
straight onto the MXU; InstanceNorm + LeakyReLU follow the nnU-Net recipe.
Deep supervision heads emit logits at every decoder scale as a dict
({"prediction", "ds_1", ...}) — the reference's list<->dict converters
(utils/nnunet_utils.py:167,195) collapse into this one contract.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn


class ConvBlock(nn.Module):
    """Conv -> InstanceNorm -> LeakyReLU (the nnU-Net basic block).

    ``conv_impl``: "lax" = nn.Conv; "mxu" = the im2col batched-matmul conv
    (models/cnn.py MxuConv — required when the clients axis is SHARDED:
    the grouped-conv lowering of per-client-weights vmapped nn.Conv is
    rejected by XLA's partitioner; pinned in
    tests/parallel/test_sharded_mesh.py). Param paths are identical either
    way ("Conv_0"), so checkpoints and exchangers are impl-agnostic."""

    features: int
    kernel_size: Sequence[int]
    strides: Sequence[int] | None = None
    conv_impl: str = "lax"

    @nn.compact
    def __call__(self, x):
        from fl4health_tpu.models.cnn import make_conv

        x = make_conv(
            self.conv_impl,
            self.features,
            tuple(self.kernel_size),
            strides=tuple(self.strides) if self.strides else None,
            name="Conv_0",
        )(x)
        x = nn.InstanceNorm(epsilon=1e-5)(x)
        return nn.leaky_relu(x, negative_slope=0.01)


class StackedConvs(nn.Module):
    features: int
    kernel_size: Sequence[int]
    n_convs: int
    first_stride: Sequence[int] | None = None
    conv_impl: str = "lax"

    @nn.compact
    def __call__(self, x):
        for i in range(self.n_convs):
            x = ConvBlock(
                self.features,
                self.kernel_size,
                strides=self.first_stride if i == 0 else None,
                conv_impl=self.conv_impl,
            )(x)
        return x


class PlainConvUNet(nn.Module):
    """N-dimensional U-Net assembled from plan numbers.

    features_per_stage / strides / kernel_sizes all have length ``n_stages``;
    ``strides[0]`` must be all-ones (stage 0 keeps full resolution). Spatial
    rank is inferred from the kernel-size rank, so the same class serves the
    2d and 3d_fullres configurations.
    """

    features_per_stage: tuple[int, ...]
    strides: tuple[tuple[int, ...], ...]
    kernel_sizes: tuple[tuple[int, ...], ...]
    n_classes: int
    n_conv_per_stage: int = 2
    deep_supervision: bool = True
    conv_impl: str = "lax"

    @nn.compact
    def __call__(self, x, train: bool = True):
        n_stages = len(self.features_per_stage)
        ndim = len(self.kernel_sizes[0])
        assert x.ndim == ndim + 2, (
            f"expected [B, *spatial({ndim}), C] input, got shape {x.shape}"
        )

        # Encoder: keep every stage's output for skips.
        skips = []
        for s in range(n_stages):
            x = StackedConvs(
                self.features_per_stage[s],
                self.kernel_sizes[s],
                self.n_conv_per_stage,
                first_stride=self.strides[s] if s > 0 else None,
                conv_impl=self.conv_impl,
            )(x)
            skips.append(x)

        # Decoder: transpose-conv upsample, concat skip, conv stack, seg head.
        ds_logits = []  # highest resolution LAST while building
        x = skips[-1]
        for s in range(n_stages - 2, -1, -1):
            up_stride = tuple(self.strides[s + 1])
            x = nn.ConvTranspose(
                self.features_per_stage[s],
                kernel_size=up_stride,
                strides=up_stride,
                padding="VALID",
            )(x)
            x = jnp.concatenate([x, skips[s]], axis=-1)
            x = StackedConvs(
                self.features_per_stage[s],
                self.kernel_sizes[s],
                self.n_conv_per_stage,
                conv_impl=self.conv_impl,
            )(x)
            if self.deep_supervision or s == 0:
                from fl4health_tpu.models.cnn import make_conv

                # explicit name matches nn.Conv's auto-name for the i-th
                # head so the param tree is impl-agnostic
                head = make_conv(
                    self.conv_impl, self.n_classes, (1,) * ndim,
                    name=f"Conv_{len(ds_logits)}",
                )(x)
                ds_logits.append(head)

        # Highest resolution is the final decoder stage's head.
        preds = {"prediction": ds_logits[-1]}
        if self.deep_supervision:
            for i, logits in enumerate(reversed(ds_logits[:-1]), start=1):
                preds[f"ds_{i}"] = logits
        return preds, {}


def unet_from_plans(
    plans: dict[str, Any],
    num_input_channels: int,
    num_classes: int,
    configuration: str | None = None,
    deep_supervision: bool = True,
    conv_impl: str = "lax",
) -> PlainConvUNet:
    """Instantiate the network a plans dict describes (the
    ``build_network_architecture`` equivalent, nnunet_server.py:145-152).
    ``num_input_channels`` is accepted for interface parity (the handshake
    ships it, nnunet_server.py:228) though flax infers input channels lazily.
    """
    del num_input_channels  # flax modules are input-shape polymorphic at init
    if configuration is None:
        from fl4health_tpu.nnunet.plans import default_configuration

        configuration = default_configuration(plans)
    cfg = plans["configurations"][configuration]
    return PlainConvUNet(
        features_per_stage=tuple(cfg["features_per_stage"]),
        strides=tuple(tuple(s) for s in cfg["strides"]),
        kernel_sizes=tuple(tuple(k) for k in cfg["kernel_sizes"]),
        n_classes=num_classes,
        n_conv_per_stage=int(cfg.get("n_conv_per_stage", 2)),
        deep_supervision=deep_supervision,
        conv_impl=conv_impl,
    )


def deep_supervision_strides(plans: dict[str, Any], configuration: str | None = None):
    """Cumulative per-axis downsampling factor for each deep-supervision
    output, ordered to match the prediction dict: index 0 is "ds_1" (half the
    scale of "prediction"), etc. Used to pool targets for the DS loss."""
    if configuration is None:
        from fl4health_tpu.nnunet.plans import default_configuration

        configuration = default_configuration(plans)
    strides = plans["configurations"][configuration]["strides"]
    cumulative = []
    running = [1] * len(strides[0])
    for s in strides[1:]:
        running = [r * si for r, si in zip(running, s)]
        cumulative.append(tuple(running))
    # Decoder emits heads at stages n-2 .. 0; "prediction" is stage 0 (full
    # res), ds_i is stage i for i = 1..n-2. The bottleneck (stage n-1) has no
    # head, so its cumulative factor is dropped; a 2-stage net has no DS
    # outputs at all.
    return cumulative[:-1]

"""Split-architecture model bases, flax-native.

Parity targets (/root/reference/fl4health/model_bases/):
- ``SequentiallySplitModel`` / ``SequentiallySplitExchangeBaseModel``
  (sequential_split_models.py:7,92) — features -> head, with the feature
  extractor as the exchange base.
- ``ParallelSplitModel`` + ``ParallelSplitHeadModule`` join modes CONCAT/SUM
  (parallel_split_models.py:13,83).
- ``FendaModel`` (fenda_base.py:8) — local ‖ global extractors, only the
  global ("second") extractor crosses the wire.
- ``ApflModule`` (apfl_base.py:9) — twin local/global models with adaptive
  alpha-mixed logits.
- ``MoonModel`` (moon_base.py:7) — sequential split + optional projection
  head, exposing contrastive features.
- ``FedRepModel`` (fedrep_base.py:4) — sequential split with head/rep
  training phases (freezing realized as gradient masks in the client logic).
- ``PerFclModel`` (perfcl_base.py:8) — parallel split exposing both feature
  streams for the dual contrastive losses.
- ``GpflModel`` + ``Gce``/``CoV`` (gpfl_base.py:12,90,171).
- ``EnsembleModel`` (ensemble_base.py:15).
- ``FedSimClrModel`` (fedsimclr_base.py:12).

TPU-native stance: "which subtree crosses the wire" is not a model-base
concern here — it is a path predicate handed to a
``fl4health_tpu.exchange.FixedLayerExchanger``. Each base documents its
exchange predicate as a staticmethod so client code stays declarative.
"""

from __future__ import annotations

import enum
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn


class JoinMode(enum.Enum):
    """ParallelSplitHeadModule join modes (parallel_split_models.py:13)."""

    CONCATENATE = "concatenate"
    SUM = "sum"


# ---------------------------------------------------------------------------
# Sequential split
# ---------------------------------------------------------------------------

class SequentiallySplitModel(nn.Module):
    """features -> head; returns prediction plus the feature stream
    (sequential_split_models.py:7 ``sequential_forward``)."""

    features_module: nn.Module
    head_module: nn.Module

    @nn.compact
    def __call__(self, x, train: bool = True):
        features = self.features_module(x, train=train)
        preds = self.head_module(features, train=train)
        return {"prediction": preds}, {"features": features}

    @staticmethod
    def exchange_features_only(path: str) -> bool:
        """Exchange predicate for SequentiallySplitExchangeBaseModel
        (sequential_split_models.py:92): share the feature extractor, keep
        the head private (FedPer/FedRep semantics)."""
        return path.startswith("features_module")


class HeadModule(nn.Module):
    """Parallel-split head joining two feature streams
    (parallel_split_models.py:13)."""

    head: nn.Module
    join_mode: JoinMode = JoinMode.CONCATENATE

    @nn.compact
    def __call__(self, local_features, global_features, train: bool = True):
        if self.join_mode is JoinMode.CONCATENATE:
            joined = jnp.concatenate([local_features, global_features], axis=-1)
        else:
            joined = local_features + global_features
        return self.head(joined, train=train)


class ParallelSplitModel(nn.Module):
    """Two parallel feature extractors joined by a head
    (parallel_split_models.py:83). Naming convention fixes the exchange
    boundary: ``second_feature_extractor`` is the globally-shared one
    (fenda_base.py:8 exchanges only ``second_feature_extractor.*``)."""

    first_feature_extractor: nn.Module  # local / personal
    second_feature_extractor: nn.Module  # global / aggregated
    head_module: HeadModule

    @nn.compact
    def __call__(self, x, train: bool = True):
        local_f = self.first_feature_extractor(x, train=train)
        global_f = self.second_feature_extractor(x, train=train)
        preds = self.head_module(local_f, global_f, train=train)
        return (
            {"prediction": preds},
            {"local_features": local_f, "global_features": global_f},
        )

    @staticmethod
    def exchange_global_extractor(path: str) -> bool:
        """FENDA exchange predicate (fenda_base.py:20 layers_to_exchange)."""
        return path.startswith("second_feature_extractor")


# FENDA is exactly a ParallelSplitModel with the global-extractor exchange
# predicate; PerFCL additionally consumes both feature streams in its loss.
FendaModel = ParallelSplitModel
PerFclModel = ParallelSplitModel


# ---------------------------------------------------------------------------
# APFL
# ---------------------------------------------------------------------------

class ApflModule(nn.Module):
    """APFL twin models with alpha-mixed personal logits (apfl_base.py:9).

    ``alpha`` lives in ``extra`` state on the client (it must never cross the
    wire and is updated with its own learning rate, apfl_base.py:86
    ``update_alpha``); the forward takes it as an argument so the mixing is
    differentiable and the client logic can take d(personal_loss)/d(alpha)
    directly — the exact gradient the reference's manual formula computes.
    """

    local_model: nn.Module
    global_model: nn.Module

    @nn.compact
    def __call__(self, x, alpha=None, train: bool = True):
        if alpha is None:
            alpha = 0.5
        local_out = self.local_model(x, train=train)
        global_out = self.global_model(x, train=train)
        local_logits = _prediction_of(local_out)
        global_logits = _prediction_of(global_out)
        personal = alpha * local_logits + (1.0 - alpha) * global_logits
        return (
            {
                "personal": personal,
                "global": global_logits,
                "local": local_logits,
                "prediction": personal,
            },
            {},
        )

    @staticmethod
    def exchange_global_model(path: str) -> bool:
        return path.startswith("global_model")


def _prediction_of(out):
    if isinstance(out, tuple):
        out = out[0]
    if isinstance(out, dict):
        return out["prediction"]
    return out


# ---------------------------------------------------------------------------
# MOON
# ---------------------------------------------------------------------------

class MoonModel(nn.Module):
    """Sequential split exposing (optionally projected) contrastive features
    (moon_base.py:7)."""

    base_module: nn.Module
    head_module: nn.Module
    projection_module: nn.Module | None = None

    @nn.compact
    def __call__(self, x, train: bool = True):
        features = self.base_module(x, train=train)
        if self.projection_module is not None:
            features = self.projection_module(features, train=train)
        preds = self.head_module(features, train=train)
        return {"prediction": preds}, {"features": features}


# FedRep shares MOON's topology; phase freezing is a gradient mask in
# FedRepClientLogic (fedrep_base.py:4 freeze/unfreeze become masks).
FedRepModel = SequentiallySplitModel


# ---------------------------------------------------------------------------
# GPFL
# ---------------------------------------------------------------------------

class Gce(nn.Module):
    """Global Conditional Embedding table (gpfl_base.py:12): a learnable
    class-embedding matrix. ``__call__`` returns cosine-similarity logits of
    features against the (L2-normalized) class embeddings — the GCE softmax
    loss is cross-entropy over these logits (gpfl_base.py:29-58) — plus the
    raw embedding table for conditional-input computation and the
    magnitude-level loss (frozen lookup, gpfl_client.py:311-330)."""

    n_classes: int
    feature_dim: int

    @nn.compact
    def __call__(self, features):
        embeddings = self.param(
            "embedding",
            nn.initializers.normal(stddev=1.0),
            (self.n_classes, self.feature_dim),
        )
        f = features / jnp.maximum(
            jnp.linalg.norm(features, axis=-1, keepdims=True), 1e-8
        )
        e = embeddings / jnp.maximum(
            jnp.linalg.norm(embeddings, axis=-1, keepdims=True), 1e-8
        )
        return f @ e.T, embeddings  # [B, C] cosine logits, raw table


class CoV(nn.Module):
    """Conditional-Value mapping (gpfl_base.py:90): computes gamma/beta from
    the conditional input and modulates the base features with a residual
    affine transform."""

    feature_dim: int

    @nn.compact
    def __call__(self, features, conditional):
        h = nn.relu(nn.Dense(self.feature_dim)(conditional))
        gamma = nn.Dense(self.feature_dim)(h)
        beta = nn.Dense(self.feature_dim)(h)
        return nn.relu(features * (1.0 + gamma) + beta)


class GpflModel(nn.Module):
    """GPFL (gpfl_base.py:12): base extractor -> CoV-modulated personalized
    feature (classified by the head) and generalized feature (aligned to the
    GCE class embeddings). The conditional inputs are NOT learned here — the
    client computes them each round from the frozen received GCE embeddings
    and the client's class-sample proportions
    (gpfl_client.py:213-233 ``compute_conditional_inputs``) and passes them in.
    """

    base_module: nn.Module
    n_classes: int
    feature_dim: int

    @nn.compact
    def __call__(self, x, p_cond=None, g_cond=None, train: bool = True):
        base = self.base_module(x, train=train)
        base = nn.Dense(self.feature_dim, name="feature_mapper")(base)
        if p_cond is None:
            p_cond = jnp.zeros((self.feature_dim,), base.dtype)
        if g_cond is None:
            g_cond = jnp.zeros((self.feature_dim,), base.dtype)
        cov = CoV(self.feature_dim, name="cov")
        b = base.shape[0]
        personal_f = cov(base, jnp.tile(p_cond[None], (b, 1)))
        general_f = cov(base, jnp.tile(g_cond[None], (b, 1)))
        gce_logits, embeddings = Gce(self.n_classes, self.feature_dim, name="gce")(
            general_f
        )
        preds = nn.Dense(self.n_classes, name="head")(personal_f)
        return (
            {"prediction": preds, "gce_logits": gce_logits},
            {
                "personal_features": personal_f,
                "general_features": general_f,
                "gce_embeddings": embeddings,
            },
        )

    @staticmethod
    def exchange_shared(path: str) -> bool:
        """GPFL aggregates the base extractor, feature mapper, CoV, and GCE;
        only the personalized head stays local (gpfl_client.py:155)."""
        return not path.startswith("head")


# ---------------------------------------------------------------------------
# Twin models (Ditto and friends)
# ---------------------------------------------------------------------------

class TwinModel(nn.Module):
    """Two full copies of an architecture: an exchanged ``global_model`` and a
    private ``personal_model`` (Ditto's twin-model layout, clients/
    ditto_client.py:20 keeps ``self.global_model`` + ``self.model``)."""

    global_model: nn.Module
    personal_model: nn.Module

    @nn.compact
    def __call__(self, x, train: bool = True):
        g_out = self.global_model(x, train=train)
        p_out = self.personal_model(x, train=train)
        features = {}
        for prefix, out in (("global", g_out), ("personal", p_out)):
            if isinstance(out, tuple) and len(out) == 2 and isinstance(out[1], dict):
                for k, v in out[1].items():
                    features[f"{prefix}_{k}"] = v
        g, p = _prediction_of(g_out), _prediction_of(p_out)
        return {"global": g, "personal": p, "prediction": p}, features

    @staticmethod
    def exchange_global_model(path: str) -> bool:
        return path.startswith("global_model")


# ---------------------------------------------------------------------------
# Ensemble
# ---------------------------------------------------------------------------

class EnsembleModel(nn.Module):
    """Train an ensemble simultaneously (ensemble_base.py:15). Predictions are
    keyed ``ensemble-pred-i`` plus the uniform-average ``prediction``."""

    members: Sequence[nn.Module]

    @nn.compact
    def __call__(self, x, train: bool = True):
        preds = {}
        logits = []
        for i, member in enumerate(self.members):
            out = _prediction_of(member(x, train=train))
            preds[f"ensemble-pred-{i}"] = out
            logits.append(out)
        preds["prediction"] = sum(logits) / float(len(logits))
        return preds, {}


# ---------------------------------------------------------------------------
# FedSimCLR
# ---------------------------------------------------------------------------

class FedSimClrModel(nn.Module):
    """SimCLR encoder + projection head, with an optional prediction head for
    the fine-tuning stage (fedsimclr_base.py:12 ``pretrain`` flag)."""

    encoder: nn.Module
    projection_head: nn.Module
    prediction_head: nn.Module | None = None
    pretrain: bool = True

    @nn.compact
    def __call__(self, x, train: bool = True):
        features = self.encoder(x, train=train)
        if self.pretrain:
            proj = self.projection_head(features, train=train)
            return {"prediction": proj}, {"features": features}
        assert self.prediction_head is not None
        preds = self.prediction_head(features, train=train)
        return {"prediction": preds}, {"features": features}


# ---------------------------------------------------------------------------
# Simple building-block extractors/heads for tests and examples
# ---------------------------------------------------------------------------

class DenseFeatures(nn.Module):
    """Small MLP feature extractor block."""

    features: Sequence[int] = (64,)

    @nn.compact
    def __call__(self, x, train: bool = True):
        x = x.reshape((x.shape[0], -1))
        for f in self.features:
            x = nn.relu(nn.Dense(f)(x))
        return x


class DenseHead(nn.Module):
    n_outputs: int = 10

    @nn.compact
    def __call__(self, x, train: bool = True):
        return nn.Dense(self.n_outputs)(x)


class ConvFeatures(nn.Module):
    """Conv feature extractor block (NHWC)."""

    channels: Sequence[int] = (16, 32)

    @nn.compact
    def __call__(self, x, train: bool = True):
        for c in self.channels:
            x = nn.Conv(c, (3, 3))(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), strides=(2, 2))
        return x.reshape((x.shape[0], -1))

"""Autoencoder model bases + VAE loss + PCA module.

Parity targets:
- BasicAe / VariationalAe / ConditionalVae
  (/root/reference/fl4health/model_bases/autoencoders_base.py:45,99,185):
  encoder/decoder composition; the VAE forward returns
  ``concat([logvar, mu, flattened_reconstruction])`` so the packed output can
  ride the standard prediction pipe and be unpacked by the loss
  (autoencoders_base.py:165-183).
- VaeLoss (/root/reference/fl4health/preprocessing/autoencoders/loss.py:8):
  base reconstruction loss + analytic KL to the standard normal.
- PcaModule (/root/reference/fl4health/model_bases/pca.py:12): SVD of
  (centered) data, projection/reconstruction, explained-variance APIs.

TPU-native design: reparameterization noise comes from the ``sampling`` PRNG
stream (deterministic under jit given the stream key); PCA is a pure
function returning an immutable ``PcaState`` instead of registered buffers.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from flax import linen as nn
from flax import struct


def reparameterize(mu: jax.Array, logvar: jax.Array, rng: jax.Array) -> jax.Array:
    """Reparameterization trick: mu + eps * exp(0.5*logvar), eps ~ N(0, I)
    (autoencoders_base.py:148-163). Shared by the VAEs and the latent-space
    processors (preprocessing/autoencoders.py)."""
    std = jnp.exp(0.5 * logvar)
    eps = jax.random.normal(rng, std.shape, std.dtype)
    return mu + eps * std


def _sampling_rng(module: nn.Module) -> jax.Array:
    """The 'sampling' stream when provided; a fixed key otherwise so
    evaluation without an rng stays deterministic."""
    return (
        module.make_rng("sampling")
        if module.has_rng("sampling")
        else jax.random.PRNGKey(0)
    )


class BasicAe(nn.Module):
    """Standard autoencoder (autoencoders_base.py:45)."""

    encoder: nn.Module
    decoder: nn.Module

    def encode(self, x: jax.Array, train: bool = True) -> jax.Array:
        return self.encoder(x, train=train)

    def decode(self, z: jax.Array, train: bool = True) -> jax.Array:
        return self.decoder(z, train=train)

    @nn.compact
    def __call__(self, x, train: bool = True):
        z = self.encode(x, train=train)
        recon = self.decode(z, train=train)
        return {"prediction": recon}, {"latent": z}


class VariationalAe(nn.Module):
    """VAE (autoencoders_base.py:99). The encoder must return (mu, logvar);
    the forward packs ``[logvar | mu | flat reconstruction]`` along the last
    axis exactly as the reference does (autoencoders_base.py:165-183) so
    ``vae_loss`` can unpack it."""

    encoder: nn.Module
    decoder: nn.Module

    def sampling(self, mu: jax.Array, logvar: jax.Array, rng: jax.Array) -> jax.Array:
        return reparameterize(mu, logvar, rng)

    @nn.compact
    def __call__(self, x, train: bool = True):
        mu, logvar = self.encoder(x, train=train)
        z = reparameterize(mu, logvar, _sampling_rng(self))
        recon = self.decoder(z, train=train)
        flat = recon.reshape(recon.shape[0], -1)
        packed = jnp.concatenate([logvar, mu, flat], axis=1)
        return {"prediction": packed}, {"latent": z, "mu": mu, "logvar": logvar}


class ConditionalVae(nn.Module):
    """CVAE (autoencoders_base.py:185). ``unpack_input_condition`` splits the
    packed model input into (input, condition); encoder/decoder receive the
    condition as their second argument."""

    encoder: nn.Module
    decoder: nn.Module
    unpack_input_condition: Callable[[jax.Array], tuple[jax.Array, jax.Array]] | None = None

    def sampling(self, mu: jax.Array, logvar: jax.Array, rng: jax.Array) -> jax.Array:
        return reparameterize(mu, logvar, rng)

    @nn.compact
    def __call__(self, x, train: bool = True):
        if self.unpack_input_condition is not None:
            inputs, condition = self.unpack_input_condition(x)
        else:
            inputs, condition = x, None
        mu, logvar = self.encoder(inputs, condition, train=train)
        z = reparameterize(mu, logvar, _sampling_rng(self))
        recon = self.decoder(z, condition, train=train)
        flat = recon.reshape(recon.shape[0], -1)
        packed = jnp.concatenate([logvar, mu, flat], axis=1)
        return {"prediction": packed}, {"latent": z, "mu": mu, "logvar": logvar}


def unpack_vae_output(packed: jax.Array, latent_dim: int):
    """[logvar | mu | flat recon] -> (recon, mu, logvar) (loss.py:44-65)."""
    logvar = packed[:, :latent_dim]
    mu = packed[:, latent_dim : 2 * latent_dim]
    recon = packed[:, 2 * latent_dim :]
    return recon, mu, logvar


def kl_to_standard_normal(mu: jax.Array, logvar: jax.Array,
                          mask: jax.Array | None = None) -> jax.Array:
    """-0.5 * sum(1 + logvar - mu^2 - e^logvar) (loss.py:31-42)."""
    per_example = -0.5 * jnp.sum(1 + logvar - mu**2 - jnp.exp(logvar), axis=-1)
    if mask is not None:
        per_example = per_example * mask
    return jnp.sum(per_example)


def make_vae_loss(latent_dim: int, base_loss: Callable) -> Callable:
    """VaeLoss equivalent (loss.py:8): criterion(packed_preds, targets, mask)
    = base_loss(recon, target, mask) + KL. ``base_loss`` follows the engine's
    (preds, targets, mask) criterion contract."""

    def criterion(packed: jax.Array, targets: jax.Array, mask: jax.Array) -> jax.Array:
        recon, mu, logvar = unpack_vae_output(packed, latent_dim)
        recon = recon.reshape(targets.shape)
        return base_loss(recon, targets, mask) + kl_to_standard_normal(mu, logvar, mask)

    return criterion


# ---------------------------------------------------------------------------
# PCA
# ---------------------------------------------------------------------------

@struct.dataclass
class PcaState:
    """Principal components as an immutable pytree (pca.py:12 holds these as
    module buffers)."""

    components: jax.Array  # [d, k] columns = principal directions
    singular_values: jax.Array  # [k]
    data_mean: jax.Array  # [d]


class PcaModule:
    """SVD-based PCA (pca.py:12). ``low_rank`` truncates to
    ``rank_estimation`` components after the (full) SVD — jnp has no partial
    SVD, and these matrices are off the hot path."""

    def __init__(self, low_rank: bool = False, full_svd: bool = False,
                 rank_estimation: int = 6):
        self.low_rank = low_rank
        self.full_svd = full_svd
        self.rank_estimation = rank_estimation

    @staticmethod
    def maybe_reshape(x: jax.Array) -> jax.Array:
        """Flatten trailing dims to 2-D [N, d] (pca.py:96)."""
        return x.reshape(x.shape[0], -1)

    def fit(self, x: jax.Array, center_data: bool = True) -> PcaState:
        """SVD of the (optionally centered) data matrix (pca.py:61-94)."""
        x = self.maybe_reshape(x)
        mean = jnp.mean(x, axis=0)
        if center_data:
            x = x - mean
        _, s, vt = jnp.linalg.svd(x, full_matrices=self.full_svd)
        components = vt.T
        if self.low_rank:
            k = min(self.rank_estimation, components.shape[1])
            components = components[:, :k]
            s = s[:k]
        return PcaState(components=components, singular_values=s, data_mean=mean)

    def project_lower_dim(self, state: PcaState, x: jax.Array,
                          k: int | None = None, center_data: bool = False) -> jax.Array:
        """x @ U_k (pca.py:149)."""
        x = self.maybe_reshape(x)
        if center_data:
            x = x - state.data_mean
        u = state.components if k is None else state.components[:, :k]
        return x @ u

    def project_back(self, state: PcaState, x_low: jax.Array,
                     add_mean: bool = False) -> jax.Array:
        """x_low @ U_k^T (+ mean) (pca.py:174)."""
        u = state.components[:, : x_low.shape[1]]
        out = x_low @ u.T
        if add_mean:
            out = out + state.data_mean
        return out

    def reconstruction_error(self, state: PcaState, x: jax.Array,
                             k: int | None = None, center_data: bool = False) -> jax.Array:
        """Mean squared reconstruction error (pca.py:195)."""
        x2d = self.maybe_reshape(x)
        low = self.project_lower_dim(state, x, k, center_data)
        back = self.project_back(state, low, add_mean=center_data)
        return jnp.sum((x2d - back) ** 2) / x2d.shape[0]

    def projection_variance(self, state: PcaState, x: jax.Array,
                            k: int | None = None, center_data: bool = False) -> jax.Array:
        """||X U_k||_F^2 / N (pca.py:220)."""
        low = self.project_lower_dim(state, x, k, center_data)
        return jnp.sum(low**2) / low.shape[0]

    @staticmethod
    def explained_variance_ratios(state: PcaState) -> jax.Array:
        """(pca.py:240)"""
        s2 = state.singular_values**2
        return s2 / jnp.sum(s2)

    @staticmethod
    def cumulative_explained_variance(state: PcaState) -> jax.Array:
        """(pca.py:237)"""
        return jnp.sum(state.singular_values**2)

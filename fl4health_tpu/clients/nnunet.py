"""nnU-Net federated segmentation client logic.

Parity surface (/root/reference/fl4health/clients/nnunet_client.py:71
``NnunetClient``, /root/reference/fl4health/clients/flexible/nnunet.py:85):
deep-supervision forward (:624 predict), per-scale weighted Dice+CE with
ignore-label masking (:659,:703), grad-norm clip 12 + polyLR SGD recipe
(:214,:334,:338 — provided here by ``nnunet.plans.nnunet_optimizer``), and
the ``get_properties`` plans handshake (:826: fingerprint extraction + plans
creation on request).

TPU-native design: the training loop is the shared compiled engine; this
logic only contributes the multi-scale loss (pure mask arithmetic) and the
host-side properties provider. AMP/GradScaler has no equivalent — bf16 on
TPU needs no loss scaling.
"""

from __future__ import annotations

from typing import Any, Callable, Mapping, Sequence

import numpy as np

from fl4health_tpu.clients.engine import Batch, ClientLogic, ModelDef
from fl4health_tpu.losses.segmentation import (
    deep_supervision_loss,
    masked_dice_ce_loss,
)
from fl4health_tpu.nnunet.plans import (
    extract_fingerprint,
    generate_plans,
    plans_to_bytes,
)


class NnunetClientLogic(ClientLogic):
    """Deep-supervision segmentation on the shared engine."""

    extra_loss_keys = ("dice", "ce")
    eval_loss_keys = ("dice", "ce")

    def __init__(
        self,
        model: ModelDef,
        ds_strides: Sequence[Sequence[int]],
        ignore_label: int | None = None,
        augment: bool = True,
    ):
        super().__init__(model, criterion=None)
        self.ds_strides = tuple(tuple(int(f) for f in s) for s in ds_strides)
        self.ignore_label = ignore_label
        self.augment_enabled = augment

    def augment(self, batch: Batch, rng, ctx):
        """On-device nnU-Net augmentation inside the scan (the reference's
        dataloader augmenter pipeline, nnunet_utils.py:307; see
        nnunet/augment.py). ``augment=False`` restores the raw-patch path."""
        if not self.augment_enabled:
            return batch
        from fl4health_tpu.nnunet.augment import augment_patch_batch

        x, y = augment_patch_batch(batch.x, batch.y, rng)
        return batch.replace(x=x, y=y)

    def training_loss(self, preds, features, batch: Batch, params, state, ctx):
        total, dice, ce = deep_supervision_loss(
            preds, batch.y, batch.example_mask, self.ds_strides, self.ignore_label
        )
        return total, {"dice": dice, "ce": ce}

    def eval_loss(self, preds, features, batch: Batch, params, state, ctx):
        total, dice, ce = masked_dice_ce_loss(
            preds["prediction"], batch.y, batch.example_mask, self.ignore_label
        )
        return total, {"dice": dice, "ce": ce}


def make_nnunet_properties_provider(
    volumes: Sequence[np.ndarray],
    spacings: Sequence[Sequence[float]],
    segmentations: Sequence[np.ndarray],
    num_classes: int | None = None,
    dataset_name: str = "client_dataset",
    configuration: str | None = None,
    max_patch_voxels: int | None = None,
    ignore_label: int | None = None,
) -> Callable[[Mapping[str, Any]], dict[str, Any]]:
    """The client half of the plans-negotiation handshake
    (nnunet_client.py:826 ``get_properties``): on request, extract the local
    fingerprint, build plans from it, and return
    {nnunet_plans, num_input_channels, num_segmentation_heads}.

    The fingerprint is computed lazily (only when the server actually asks)
    and cached, mirroring ``maybe_extract_fingerprint`` (:521).
    """
    cache: dict[str, Any] = {}

    def provider(request: Mapping[str, Any]) -> dict[str, Any]:
        if "plans" not in cache:
            fingerprint = extract_fingerprint(volumes, spacings, segmentations)
            cache["fingerprint"] = fingerprint
            cache["plans"] = generate_plans(
                fingerprint,
                dataset_name=dataset_name,
                configuration=configuration,
                max_patch_voxels=max_patch_voxels,
            )
        n_classes = num_classes
        if n_classes is None:
            # Highest real label + 1; the ignore label is a masking device,
            # not a class, and must not grow the segmentation head.
            labels = np.unique(np.concatenate([np.unique(s) for s in segmentations]))
            if ignore_label is not None:
                labels = labels[labels != ignore_label]
            n_classes = int(labels.max()) + 1
        return {
            "nnunet_plans": plans_to_bytes(cache["plans"]),
            "num_input_channels": int(cache["fingerprint"]["num_channels"]),
            "num_segmentation_heads": n_classes,
        }

    return provider

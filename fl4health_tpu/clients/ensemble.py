"""Ensemble client logic.

Parity: /root/reference/fl4health/clients/ensemble_client.py:17 +
model_bases/ensemble_base.py:15 — all ensemble members train simultaneously
on each batch (one optimizer each in the reference; a single combined
gradient pass here touches the same disjoint subtrees), and metrics are
computed on both the per-member and uniformly-averaged predictions.
"""

from __future__ import annotations

import jax.numpy as jnp

from fl4health_tpu.clients.engine import Batch, ClientLogic


class EnsembleClientLogic(ClientLogic):
    """Pair with ``models.bases.EnsembleModel`` and a FullExchanger."""

    def __init__(self, model, criterion, n_members: int):
        super().__init__(model, criterion)
        self.n_members = n_members
        self.extra_loss_keys = tuple(
            f"member_{i}" for i in range(n_members)
        )

    def training_loss(self, preds, features, batch: Batch, params, state, ctx):
        member_losses = {
            f"member_{i}": self.criterion(
                preds[f"ensemble-pred-{i}"], batch.y, batch.example_mask
            )
            for i in range(self.n_members)
        }
        total = sum(member_losses.values())
        return total, member_losses

"""FedPer / FedRep / FedBN client logics — exchange-boundary personalization.

Parity targets:
- FedPer (/root/reference/fl4health/clients/fedper_client.py:9): shared
  feature extractor + private head — pure exchanger configuration
  (SequentiallySplitExchangeBaseModel.exchange_features_only).
- FedBN (fedbn_client.py:7): exchange everything except normalization layers
  — ``exchange.norm_exclusion_exchanger()``.
- FedRep (fedrep_client.py:33): the same split as FedPer, but each round
  first trains the HEAD with the representation frozen for ``head_steps``
  local steps, then trains the REPRESENTATION with the head frozen
  (FedRepTrainMode, fedrep_client.py:28). Freezing is realized as gradient
  masks keyed on the step-within-round — one compiled program, no
  re-jitting per phase.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.clients.engine import Batch, ClientLogic, TrainState
from fl4health_tpu.core import pytree as ptu
from fl4health_tpu.core.types import Params

# FedPer and FedBN need no logic subclass — only an exchanger:
#   FedPer: FixedLayerExchanger(SequentiallySplitModel.exchange_features_only)
#   FedBN:  exchange.norm_exclusion_exchanger()
FedPerClientLogic = ClientLogic
FedBnClientLogic = ClientLogic


@struct.dataclass
class FedRepContext:
    round_start_step: jax.Array  # state.step when the round began


class FedRepClientLogic(ClientLogic):
    """Pair with ``models.bases.FedRepModel`` (= SequentiallySplitModel) and
    FixedLayerExchanger(SequentiallySplitModel.exchange_features_only).

    ``head_steps``: local steps of head-only training at the start of every
    round; all remaining steps train the representation only
    (fedrep_client.py:33 alternation).
    """

    def __init__(self, model, criterion, head_steps: int,
                 head_predicate=None):
        super().__init__(model, criterion)
        self.head_steps = head_steps
        self.head_predicate = head_predicate or (
            lambda path: path.startswith("head_module")
        )

    def init_round_context(self, state: TrainState, payload) -> FedRepContext:
        return FedRepContext(round_start_step=state.step)

    def transform_gradients(self, grads: Params, state: TrainState,
                            ctx: FedRepContext) -> Params:
        step_in_round = state.step - ctx.round_start_step
        head_phase = (step_in_round < self.head_steps).astype(jnp.float32)
        is_head = ptu.select_by_path(grads, self.head_predicate)
        return jax.tree_util.tree_map(
            lambda g, h: g * (head_phase if h else (1.0 - head_phase)),
            grads,
            is_head,
        )

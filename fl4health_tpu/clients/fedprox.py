"""Adaptive drift-constraint client logic (FedProx / MR-MTL base behavior).

Parity: /root/reference/fl4health/clients/adaptive_drift_constraint_client.py:21
(+ FedProxClient, fed_prox_client.py:4): training loss = criterion +
drift_penalty_weight/2 * ||w - w_received||^2; the received penalty weight
arrives in the payload; the vanilla (un-penalized) train loss is packed for
server-side mu adaptation (:82-106).
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import struct

from fl4health_tpu.clients.engine import Batch, ClientLogic, TrainState
from fl4health_tpu.core.types import Params
from fl4health_tpu.exchange.packer import AdaptiveConstraintPacket
from fl4health_tpu.losses.drift import weight_drift_loss


@struct.dataclass
class ProxContext:
    initial_params: Params
    drift_penalty_weight: Any


class FedProxClientLogic(ClientLogic):
    extra_loss_keys = ("vanilla", "penalty")

    def init_round_context(self, state: TrainState, payload) -> ProxContext:
        mu = getattr(payload, "drift_penalty_weight", jnp.asarray(0.1, jnp.float32))
        return ProxContext(initial_params=state.params, drift_penalty_weight=mu)

    def training_loss(self, preds, features, batch: Batch, params, state, ctx: ProxContext):
        vanilla = self.criterion(preds["prediction"], batch.y, batch.example_mask)
        penalty = 0.5 * weight_drift_loss(
            params, ctx.initial_params, ctx.drift_penalty_weight
        )
        return vanilla + penalty, {"vanilla": vanilla, "penalty": penalty}

    def pack(self, state: TrainState, pushed_params, train_losses) -> AdaptiveConstraintPacket:
        return AdaptiveConstraintPacket(
            params=pushed_params,
            loss_for_adaptation=train_losses["vanilla"],
        )

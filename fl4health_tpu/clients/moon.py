"""MOON client logic — model-contrastive federated learning.

Parity: /root/reference/fl4health/clients/moon_client.py:19. The client keeps
a buffer of up to ``len_old_models_buffer`` FROZEN previous local models plus
the frozen received global model; ``predict`` (:85-119) runs the input
through all of them to collect ``old_features`` / ``global_features`` and the
training loss adds ``contrastive_weight`` (mu) times the MOON contrastive
term (positive pair = global features, negatives = old local features).

TPU-native design: the buffer is a params pytree with a leading [buffer]
axis in ``extra`` (static length — scan/vmap friendly); a fill counter masks
not-yet-populated slots out of the contrastive logits, reproducing the
reference's "no contrastive loss until an old model exists" behavior without
dynamic shapes.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.clients.engine import Batch, ClientLogic, TrainState
from fl4health_tpu.core.types import Params
from fl4health_tpu.losses.contrastive import moon_contrastive_loss


@struct.dataclass
class MoonExtra:
    old_params: Params  # [buffer, ...] stacked previous local params
    n_valid: jax.Array  # scalar int — filled slots


@struct.dataclass
class MoonContext:
    global_params: Params  # frozen received global model


class MoonClientLogic(ClientLogic):
    """Pair with ``models.bases.MoonModel`` (features exposed under
    ``features``) and a FullExchanger."""

    extra_loss_keys = ("vanilla", "contrastive")

    def __init__(self, model, criterion, contrastive_weight: float = 1.0,
                 temperature: float = 0.5, buffer_len: int = 1):
        super().__init__(model, criterion)
        self.mu = contrastive_weight
        self.temperature = temperature
        self.buffer_len = buffer_len

    def init_extra(self, params: Params) -> MoonExtra:
        stacked = jax.tree_util.tree_map(
            lambda p: jnp.stack([p] * self.buffer_len), params
        )
        return MoonExtra(old_params=stacked, n_valid=jnp.zeros((), jnp.int32))

    def init_round_context(self, state: TrainState, payload) -> MoonContext:
        payload_params = payload.params if hasattr(payload, "params") else payload
        return MoonContext(global_params=payload_params)

    def _features_of(self, params, model_state, x, rng):
        (_, features), _ = self.model.apply(
            params, model_state, x, train=False, rng=rng
        )
        return features["features"]

    def training_loss(self, preds, features, batch: Batch, params, state,
                      ctx: MoonContext):
        vanilla = self.criterion(preds["prediction"], batch.y, batch.example_mask)
        rng = jax.random.fold_in(state.rng, 13)
        z = features["features"]  # current local features [B, D]
        z_glob = jax.lax.stop_gradient(
            self._features_of(ctx.global_params, state.model_state, batch.x, rng)
        )
        # Old-model features: vmap over the buffer axis -> [L, B, D].
        z_old = jax.lax.stop_gradient(
            jax.vmap(
                lambda p: self._features_of(p, state.model_state, batch.x, rng)
            )(state.extra.old_params)
        )
        # Mask invalid buffer slots out of the softmax (reference skips the
        # contrastive term entirely while the buffer is empty,
        # moon_client.py:85-119). finalize_round appends newest at the END, so
        # the last n_valid slots hold real previous models.
        slot_idx = jnp.arange(self.buffer_len)
        valid = (slot_idx >= self.buffer_len - state.extra.n_valid).astype(
            jnp.float32
        )  # [L]
        contrastive = moon_contrastive_loss(
            z, z_glob[None], z_old, self.temperature, batch.example_mask,
            negative_mask=valid,
        )
        contrastive = contrastive * (state.extra.n_valid > 0).astype(jnp.float32)
        total = vanilla + self.mu * contrastive
        return total, {"vanilla": vanilla, "contrastive": contrastive}

    def finalize_round(self, state: TrainState, ctx, local_steps) -> TrainState:
        # Shift the frozen-model buffer and append this round's final local
        # params (update_after_train in the reference).
        def shift(buf, p):
            return jnp.concatenate([buf[1:], p[None]], axis=0)

        new_buf = jax.tree_util.tree_map(shift, state.extra.old_params, state.params)
        n_valid = jnp.minimum(state.extra.n_valid + 1, self.buffer_len)
        return state.replace(extra=MoonExtra(old_params=new_buf, n_valid=n_valid))

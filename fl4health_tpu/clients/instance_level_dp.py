"""Instance-level DP client logic — per-example clipped + noised gradients.

Parity: /root/reference/fl4health/clients/instance_level_dp_client.py:17
(Opacus ``PrivacyEngine.make_private`` with flat clipping) and the DP-SCAFFOLD
combination /root/reference/fl4health/clients/scaffold_client.py:297
(``DPScaffoldClient`` = instance-level DP + control variates).

``InstanceLevelDpMixin`` overrides only ``value_and_grads``: the whole-batch
``value_and_grad`` becomes vmapped per-example gradients -> flat clip ->
Gaussian noise (privacy.dpsgd). Because it is a mixin over the ClientLogic
hook surface, it composes with any algorithm logic whose loss is a pure
function of (params, one example) — e.g. SCAFFOLD's gradient correction
(transform_gradients) still applies AFTER noising, matching the reference
order (Opacus noises inside optimizer.step; modify_grad ran before it on the
summed gradient — both orders commute since the correction is additive and
constant across the batch).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp

from fl4health_tpu.clients.engine import Batch, ClientLogic, TrainState
from fl4health_tpu.clients.scaffold import ScaffoldClientLogic
from fl4health_tpu.privacy import dpsgd


class InstanceLevelDpMixin:
    """Mix in BEFORE a ClientLogic subclass:

        class MyDpLogic(InstanceLevelDpMixin, MyLogic): ...

    kwargs consumed: ``clipping_bound`` (C), ``noise_multiplier`` (sigma).
    """

    # In-graph telemetry channel (observability/telemetry.py): when the
    # simulation compiles telemetry outputs it adds these keys to the loss
    # meter, so the per-step clip fraction below surfaces as a per-client
    # round statistic. Without telemetry the key is absent from the meter
    # and XLA dead-code-eliminates the computation.
    telemetry_loss_keys = ("clip_fraction",)

    def __init__(self, *args, clipping_bound: float, noise_multiplier: float, **kwargs):
        super().__init__(*args, **kwargs)
        self.clipping_bound = float(clipping_bound)
        self.noise_multiplier = float(noise_multiplier)

    def value_and_grads(self, state: TrainState, ctx: Any, batch: Batch, step_rng):
        dpsgd.validate_dp_safe_model_state(state.model_state)
        grad_rng, noise_rng = jax.random.split(step_rng)

        def single_loss(params, x1, y1):
            b1 = Batch(
                x=x1[None],
                y=y1[None],
                example_mask=jnp.ones((1,), jnp.float32),
                step_mask=batch.step_mask,
            )
            (preds, features), _ = self.predict(
                params, state.model_state, b1, grad_rng, train=True,
                extra=state.extra, ctx=ctx,
            )
            loss, additional = self.training_loss(
                preds, features, b1, params, state, ctx
            )
            return loss, (preds, additional)

        grad_fn = jax.vmap(
            jax.value_and_grad(single_loss, has_aux=True), in_axes=(None, 0, 0)
        )
        (per_losses, (per_preds, per_additional)), per_grads = grad_fn(
            state.params, batch.x, batch.y
        )

        grads, clip_fraction = dpsgd.noisy_clipped_mean_grads(
            per_grads, batch.example_mask, noise_rng,
            self.clipping_bound, self.noise_multiplier,
            return_clip_fraction=True,
        )

        m = batch.example_mask.astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(m), 1.0)
        backward = jnp.sum(per_losses * m) / denom
        # composed logics' auxiliary losses (extra_loss_keys) are per-example
        # scalars after vmap: masked-average them back to batch scalars
        additional = jax.tree_util.tree_map(
            lambda v: jnp.sum(v * m) / denom, per_additional
        )
        additional = {**additional, "clip_fraction": clip_fraction}
        # per-example predict ran on singleton batches: squeeze back to [B,...]
        preds = jax.tree_util.tree_map(lambda p: p[:, 0], per_preds)
        return (backward, (preds, additional, state.model_state)), grads


class InstanceLevelDpClientLogic(InstanceLevelDpMixin, ClientLogic):
    """Plain FedAvg client with instance-level DP-SGD
    (instance_level_dp_client.py:17)."""


class DpScaffoldClientLogic(InstanceLevelDpMixin, ScaffoldClientLogic):
    """DP-SCAFFOLD (scaffold_client.py:297): noisy per-example gradients with
    control-variate correction and variate updates."""

"""MMD-regularized personalization clients: Ditto/MR-MTL + MK-MMD or DeepMMD.

Parity targets:
- DittoMkMmdClient (/root/reference/fl4health/clients/mkmmd_clients/
  ditto_mkmmd_client.py:22): Ditto, plus an MK-MMD penalty pulling the
  personal model's intermediate features toward the features the *initial*
  (received, frozen) global model produces on the same batch. Kernel weights
  (betas) re-optimized every ``beta_global_update_interval`` steps; -1 means
  per-batch re-optimization inside the loss, 0 means never
  (ditto_mkmmd_client.py:94-101,340-344). Optional feature-l2-norm penalty
  (ditto_mkmmd_client.py:354-357).
- MrMtlMkMmdClient (mkmmd_clients/mr_mtl_mkmmd_client.py): same penalty
  between the personal model and the frozen round aggregate.
- DittoDeepMmdClient / MrMtlDeepMmdClient (deep_mmd_clients/*.py): the
  penalty is a learned deep-kernel MMD; ``mmd_kernel_train_interval``
  controls kernel training (-1 per batch before the loss, 0 never, N every
  N steps — ditto_deep_mmd_client.py:135-159).

TPU-native design:
- The reference extracts features with forward hooks into host-side buffers
  (model_bases/feature_extractor_buffer.py) and re-runs train batches to
  refresh them before each beta optimization. Here features are the model's
  returned feature dict (already part of the predict contract), the
  frozen-model features come from one extra compiled forward with the frozen
  params, and beta/kernel refreshes use the current step's batch inside
  ``lax.cond`` — streaming estimates instead of full-dataset host buffers, so
  the whole round stays one XLA program.
- The beta QP is solved on device (losses/mmd.py optimize_betas).
- All MMD statistics respect ``batch.example_mask`` so zero-padded rows of
  ragged batches never contribute (the torch reference always sees
  true-sized batches).
"""

from __future__ import annotations

from typing import Any, Mapping, Sequence

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.clients.ditto import DittoClientLogic, DittoContext, MrMtlClientLogic, MrMtlContext
from fl4health_tpu.clients.engine import Batch, ModelDef, TrainState
from fl4health_tpu.losses.mmd import DeepMmd, default_gammas, mkmmd, optimize_betas, uniform_betas


def _flat(x: jax.Array) -> jax.Array:
    return x.reshape(x.shape[0], -1)


def _branch_state(model_state: Any, branch: str) -> Any:
    """Slice a TwinModel's mutable collections down to one branch so the
    single-branch feature model can consume them (e.g. batch_stats)."""
    if not model_state:
        return {}
    return {coll: tree[branch] for coll, tree in model_state.items() if branch in tree}


@struct.dataclass
class DittoMmdContext(DittoContext):
    round_start_step: Any = 0
    # Round-start snapshot of mutable collections (batch_stats) so the frozen
    # target model is TRULY frozen (reference clone_and_freeze_model freezes
    # params AND buffers, ditto_mkmmd_client.py update_before_train).
    initial_model_state: Any = None


@struct.dataclass
class MrMtlMmdContext(MrMtlContext):
    round_start_step: Any = 0
    initial_model_state: Any = None


class _MkMmdMixin:
    """Shared MK-MMD machinery: betas in persistent extra state, interval
    refresh, per-layer penalty sum, optional feature-l2 penalty."""

    def _init_mkmmd(self, feature_keys: Sequence[str], mkmmd_weight: float,
                    beta_interval: int, gammas, normalize_features: bool,
                    feature_l2_norm_weight: float):
        self.feature_keys = tuple(feature_keys)
        self.mkmmd_weight = mkmmd_weight
        self.beta_interval = beta_interval
        self.gammas = default_gammas() if gammas is None else gammas
        self.normalize_features = normalize_features
        self.feature_l2_norm_weight = feature_l2_norm_weight
        if beta_interval < -1:
            raise ValueError("beta_global_update_interval must be -1, 0 or positive")

    def _init_betas(self) -> dict:
        k = self.gammas.shape[0]
        return {key: uniform_betas(k) for key in self.feature_keys}

    def _mkmmd_penalty(self, local_feats: Mapping[str, jax.Array],
                       target_feats: Mapping[str, jax.Array],
                       betas: Mapping[str, jax.Array], mask: jax.Array):
        total = jnp.asarray(0.0, jnp.float32)
        for key in self.feature_keys:
            total = total + mkmmd(
                _flat(local_feats[key]),
                jax.lax.stop_gradient(_flat(target_feats[key])),
                betas[key],
                self.gammas,
                normalize_features=self.normalize_features,
                mask=mask,
            )
        return total

    def _feature_l2_penalty(self, local_feats: Mapping[str, jax.Array],
                            mask: jax.Array) -> jax.Array:
        """Average feature l2 norm (ditto_mkmmd_client.py:354-357)."""
        f = _flat(local_feats[self.feature_keys[0]]) * mask[:, None]
        n_valid = jnp.maximum(jnp.sum(mask), 1.0)
        return jnp.linalg.norm(f) / n_valid

    def _optimized_betas(self, state: TrainState, ctx, batch: Batch) -> dict:
        local_f, target_f = self._mmd_features(state, ctx, batch)
        return {
            key: optimize_betas(
                _flat(local_f[key]),
                _flat(target_f[key]),
                self.gammas,
                normalize_features=self.normalize_features,
                mask=batch.example_mask,
            )
            for key in self.feature_keys
        }

    def update_before_step(self, state: TrainState, ctx, batch: Batch) -> TrainState:
        """interval == -1: re-optimize betas on every batch before the loss
        consumes them (ditto_mkmmd_client.py:340-344)."""
        if self.mkmmd_weight == 0 or self.beta_interval != -1:
            return state

        def recompute(extra):
            return {**extra, "mkmmd_betas": self._optimized_betas(state, ctx, batch)}

        new_extra = jax.lax.cond(batch.step_mask > 0, recompute, lambda e: e, state.extra)
        return state.replace(extra=new_extra)

    def update_after_step(self, state: TrainState, ctx, batch: Batch,
                          preds=None) -> TrainState:
        """interval > 0: refresh betas at the step interval
        (ditto_mkmmd_client.py:140-159)."""
        if self.mkmmd_weight == 0 or self.beta_interval <= 0:
            return state
        # state.step is already incremented when this hook runs; the reference
        # counter is passed pre-increment, so its (step-1) % I == 0 first fires
        # after the SECOND gradient step (basic_client.py:669,748-749).
        step_in_round = state.step - ctx.round_start_step  # 1-based at hook time
        do = (step_in_round - 2) % self.beta_interval == 0
        do = jnp.logical_and(do, batch.step_mask > 0)

        def recompute(extra):
            return {**extra, "mkmmd_betas": self._optimized_betas(state, ctx, batch)}

        new_extra = jax.lax.cond(do, recompute, lambda e: e, state.extra)
        return state.replace(extra=new_extra)


class DittoMkMmdClientLogic(_MkMmdMixin, DittoClientLogic):
    """Ditto + MK-MMD feature alignment (ditto_mkmmd_client.py:22).

    ``model`` is the TwinModel ModelDef (submodules must return a feature
    dict); ``feature_model`` is the single-branch architecture used to run the
    frozen initial-global params for target features.
    """

    extra_loss_keys = ("global_ce", "personal_ce", "penalty", "mkmmd")

    def __init__(self, model: ModelDef, criterion, feature_model: ModelDef,
                 lam: float = 1.0, mkmmd_loss_weight: float = 10.0,
                 feature_keys: Sequence[str] = ("features",),
                 beta_global_update_interval: int = 20,
                 gammas=None, normalize_features: bool = True,
                 feature_l2_norm_weight: float = 0.0,
                 adaptive: bool = False):
        DittoClientLogic.__init__(self, model, criterion, lam=lam, adaptive=adaptive)
        self.feature_model = feature_model
        self._init_mkmmd(feature_keys, mkmmd_loss_weight, beta_global_update_interval,
                         gammas, normalize_features, feature_l2_norm_weight)

    def init_extra(self, params):
        return {"mkmmd_betas": self._init_betas()}

    def init_round_context(self, state: TrainState, payload) -> DittoMmdContext:
        base = DittoClientLogic.init_round_context(self, state, payload)
        return DittoMmdContext(
            initial_global_params=base.initial_global_params,
            drift_penalty_weight=base.drift_penalty_weight,
            round_start_step=state.step,
            initial_model_state=state.model_state,
        )

    def _frozen_global_features(self, ctx, batch: Batch) -> dict:
        (_, feats), _ = self.feature_model.apply(
            ctx.initial_global_params,
            _branch_state(ctx.initial_model_state, "global_model"),
            batch.x, train=False,
        )
        return feats

    def _mmd_features(self, state: TrainState, ctx, batch: Batch):
        (_, pfeats), _ = self.feature_model.apply(
            state.params["personal_model"],
            _branch_state(state.model_state, "personal_model"),
            batch.x, train=False,
        )
        return pfeats, self._frozen_global_features(ctx, batch)

    def training_loss(self, preds, features, batch: Batch, params, state, ctx):
        total, parts = DittoClientLogic.training_loss(
            self, preds, features, batch, params, state, ctx
        )
        local_feats = {k: features[f"personal_{k}"] for k in self.feature_keys}
        target_feats = self._frozen_global_features(ctx, batch)
        mmd = self._mkmmd_penalty(local_feats, target_feats,
                                  state.extra["mkmmd_betas"], batch.example_mask)
        parts["mkmmd"] = mmd
        total = total + self.mkmmd_weight * mmd
        if self.feature_l2_norm_weight != 0:
            l2 = self._feature_l2_penalty(local_feats, batch.example_mask)
            parts["feature_l2_norm"] = l2
            total = total + self.feature_l2_norm_weight * l2
        return total, parts


class MrMtlMkMmdClientLogic(_MkMmdMixin, MrMtlClientLogic):
    """MR-MTL + MK-MMD alignment to the frozen aggregate
    (mkmmd_clients/mr_mtl_mkmmd_client.py)."""

    extra_loss_keys = ("vanilla", "penalty", "mkmmd")

    def __init__(self, model: ModelDef, criterion, lam: float = 1.0,
                 mkmmd_loss_weight: float = 10.0,
                 feature_keys: Sequence[str] = ("features",),
                 beta_global_update_interval: int = 20,
                 gammas=None, normalize_features: bool = True,
                 feature_l2_norm_weight: float = 0.0,
                 adaptive: bool = False):
        MrMtlClientLogic.__init__(self, model, criterion, lam=lam, adaptive=adaptive)
        self._init_mkmmd(feature_keys, mkmmd_loss_weight, beta_global_update_interval,
                         gammas, normalize_features, feature_l2_norm_weight)

    def init_extra(self, params):
        return {"mkmmd_betas": self._init_betas()}

    def init_round_context(self, state: TrainState, payload) -> MrMtlMmdContext:
        base = MrMtlClientLogic.init_round_context(self, state, payload)
        return MrMtlMmdContext(
            initial_params=base.initial_params,
            drift_penalty_weight=base.drift_penalty_weight,
            round_start_step=state.step,
            initial_model_state=state.model_state,
        )

    def _frozen_features(self, ctx, batch: Batch) -> dict:
        (_, feats), _ = self.model.apply(ctx.initial_params,
                                         ctx.initial_model_state,
                                         batch.x, train=False)
        return feats

    def _mmd_features(self, state: TrainState, ctx, batch: Batch):
        (_, feats), _ = self.model.apply(state.params, state.model_state,
                                         batch.x, train=False)
        return feats, self._frozen_features(ctx, batch)

    def training_loss(self, preds, features, batch: Batch, params, state, ctx):
        total, parts = MrMtlClientLogic.training_loss(
            self, preds, features, batch, params, state, ctx
        )
        local_feats = {k: features[k] for k in self.feature_keys}
        target_feats = self._frozen_features(ctx, batch)
        mmd = self._mkmmd_penalty(local_feats, target_feats,
                                  state.extra["mkmmd_betas"], batch.example_mask)
        parts["mkmmd"] = mmd
        total = total + self.mkmmd_weight * mmd
        if self.feature_l2_norm_weight != 0:
            l2 = self._feature_l2_penalty(local_feats, batch.example_mask)
            parts["feature_l2_norm"] = l2
            total = total + self.feature_l2_norm_weight * l2
        return total, parts


# ---------------------------------------------------------------------------
# Deep-kernel MMD variants
# ---------------------------------------------------------------------------

class _DeepMmdMixin:
    """Shared DeepMMD machinery: per-layer learned kernels in extra state.

    ``mmd_kernel_train_interval`` mirrors the reference knob
    (ditto_deep_mmd_client.py:135-159): -1 trains the kernel on every batch
    BEFORE the loss consumes it (deep_mmd_loss.py:304-311 forward protocol),
    0 never trains, and a positive interval trains every N steps — the
    reference trains on accumulated feature buffers there; this build uses
    the interval step's batch as a streaming estimate.
    """

    def _init_deep_mmd(self, feature_sizes: Mapping[str, int], weight: float,
                       lr: float, hidden_size: int, output_size: int,
                       optimization_steps: int, train_interval: int):
        self.deep_mmd_weight = weight
        self.kernel_train_interval = train_interval
        if train_interval < -1:
            raise ValueError("mmd_kernel_train_interval must be -1, 0 or positive")
        self.feature_keys = tuple(feature_sizes.keys())
        self.kernels = {
            key: DeepMmd(size, hidden_size=hidden_size, output_size=output_size,
                         lr=lr, optimization_steps=optimization_steps)
            for key, size in feature_sizes.items()
        }

    def _init_kernel_states(self, rng: jax.Array) -> dict:
        keys = jax.random.split(rng, max(len(self.feature_keys), 1))
        return {
            key: self.kernels[key].init(keys[i])
            for i, key in enumerate(self.feature_keys)
        }

    def _deep_mmd_penalty(self, local_feats, target_feats, kernel_states,
                          mask: jax.Array):
        total = jnp.asarray(0.0, jnp.float32)
        for key in self.feature_keys:
            total = total + self.kernels[key].value(
                kernel_states[key],
                _flat(local_feats[key]),
                jax.lax.stop_gradient(_flat(target_feats[key])),
                mask=mask,
            )
        return total

    def _trained_kernels(self, state: TrainState, ctx, batch: Batch, extra) -> dict:
        local_f, target_f = self._mmd_features(state, ctx, batch)
        rng = jax.random.fold_in(state.rng, state.step)
        new_states = {}
        for i, key in enumerate(self.feature_keys):
            new_states[key] = self.kernels[key].train(
                extra["deep_mmd"][key],
                _flat(local_f[key]),
                _flat(target_f[key]),
                jax.random.fold_in(rng, i),
                mask=batch.example_mask,
            )
        return {**extra, "deep_mmd": new_states}

    def update_before_step(self, state: TrainState, ctx, batch: Batch) -> TrainState:
        """interval == -1: train the kernels on this batch before the loss
        step (the reference trains inside forward, before the value)."""
        if self.deep_mmd_weight == 0 or self.kernel_train_interval != -1:
            return state
        new_extra = jax.lax.cond(
            batch.step_mask > 0,
            lambda e: self._trained_kernels(state, ctx, batch, e),
            lambda e: e,
            state.extra,
        )
        return state.replace(extra=new_extra)

    def update_after_step(self, state: TrainState, ctx, batch: Batch,
                          preds=None) -> TrainState:
        """interval > 0: train the kernels every N steps
        (ditto_deep_mmd_client.py:146-159)."""
        if self.deep_mmd_weight == 0 or self.kernel_train_interval <= 0:
            return state
        step_in_round = state.step - ctx.round_start_step  # 1-based at hook time
        do = (step_in_round - 2) % self.kernel_train_interval == 0
        do = jnp.logical_and(do, batch.step_mask > 0)
        new_extra = jax.lax.cond(
            do,
            lambda e: self._trained_kernels(state, ctx, batch, e),
            lambda e: e,
            state.extra,
        )
        return state.replace(extra=new_extra)


class DittoDeepMmdClientLogic(_DeepMmdMixin, DittoClientLogic):
    """Ditto + deep-kernel MMD (deep_mmd_clients/ditto_deep_mmd_client.py:23).

    ``feature_sizes`` maps feature keys to their flattened dimension (the
    reference's feature_extraction_layers_with_size).
    """

    extra_loss_keys = ("global_ce", "personal_ce", "penalty", "deep_mmd")

    def __init__(self, model: ModelDef, criterion, feature_model: ModelDef,
                 feature_sizes: Mapping[str, int], lam: float = 1.0,
                 deep_mmd_loss_weight: float = 10.0, lr: float = 0.001,
                 hidden_size: int = 10, output_size: int = 50,
                 optimization_steps: int = 5,
                 mmd_kernel_train_interval: int = 20,
                 adaptive: bool = False, seed: int = 0):
        DittoClientLogic.__init__(self, model, criterion, lam=lam, adaptive=adaptive)
        self.feature_model = feature_model
        self._seed = seed
        self._init_deep_mmd(feature_sizes, deep_mmd_loss_weight, lr,
                            hidden_size, output_size, optimization_steps,
                            mmd_kernel_train_interval)

    def init_extra(self, params):
        return {"deep_mmd": self._init_kernel_states(jax.random.PRNGKey(self._seed))}

    def init_round_context(self, state: TrainState, payload) -> DittoMmdContext:
        base = DittoClientLogic.init_round_context(self, state, payload)
        return DittoMmdContext(
            initial_global_params=base.initial_global_params,
            drift_penalty_weight=base.drift_penalty_weight,
            round_start_step=state.step,
            initial_model_state=state.model_state,
        )

    def _frozen_global_features(self, ctx, batch: Batch) -> dict:
        (_, feats), _ = self.feature_model.apply(
            ctx.initial_global_params,
            _branch_state(ctx.initial_model_state, "global_model"),
            batch.x, train=False,
        )
        return feats

    def _mmd_features(self, state: TrainState, ctx, batch: Batch):
        (_, pfeats), _ = self.feature_model.apply(
            state.params["personal_model"],
            _branch_state(state.model_state, "personal_model"),
            batch.x, train=False,
        )
        return pfeats, self._frozen_global_features(ctx, batch)

    def training_loss(self, preds, features, batch: Batch, params, state, ctx):
        total, parts = DittoClientLogic.training_loss(
            self, preds, features, batch, params, state, ctx
        )
        local_feats = {k: features[f"personal_{k}"] for k in self.feature_keys}
        target_feats = self._frozen_global_features(ctx, batch)
        mmd = self._deep_mmd_penalty(local_feats, target_feats,
                                     state.extra["deep_mmd"], batch.example_mask)
        parts["deep_mmd"] = mmd
        return total + self.deep_mmd_weight * mmd, parts


class MrMtlDeepMmdClientLogic(_DeepMmdMixin, MrMtlClientLogic):
    """MR-MTL + deep-kernel MMD (deep_mmd_clients/mr_mtl_deep_mmd_client.py)."""

    extra_loss_keys = ("vanilla", "penalty", "deep_mmd")

    def __init__(self, model: ModelDef, criterion,
                 feature_sizes: Mapping[str, int], lam: float = 1.0,
                 deep_mmd_loss_weight: float = 10.0, lr: float = 0.001,
                 hidden_size: int = 10, output_size: int = 50,
                 optimization_steps: int = 5,
                 mmd_kernel_train_interval: int = 20,
                 adaptive: bool = False, seed: int = 0):
        MrMtlClientLogic.__init__(self, model, criterion, lam=lam, adaptive=adaptive)
        self._seed = seed
        self._init_deep_mmd(feature_sizes, deep_mmd_loss_weight, lr,
                            hidden_size, output_size, optimization_steps,
                            mmd_kernel_train_interval)

    def init_extra(self, params):
        return {"deep_mmd": self._init_kernel_states(jax.random.PRNGKey(self._seed))}

    def init_round_context(self, state: TrainState, payload) -> MrMtlMmdContext:
        base = MrMtlClientLogic.init_round_context(self, state, payload)
        return MrMtlMmdContext(
            initial_params=base.initial_params,
            drift_penalty_weight=base.drift_penalty_weight,
            round_start_step=state.step,
            initial_model_state=state.model_state,
        )

    def _frozen_features(self, ctx, batch: Batch) -> dict:
        (_, feats), _ = self.model.apply(ctx.initial_params,
                                         ctx.initial_model_state,
                                         batch.x, train=False)
        return feats

    def _mmd_features(self, state: TrainState, ctx, batch: Batch):
        (_, feats), _ = self.model.apply(state.params, state.model_state,
                                         batch.x, train=False)
        return feats, self._frozen_features(ctx, batch)

    def training_loss(self, preds, features, batch: Batch, params, state, ctx):
        total, parts = MrMtlClientLogic.training_loss(
            self, preds, features, batch, params, state, ctx
        )
        local_feats = {k: features[k] for k in self.feature_keys}
        target_feats = self._frozen_features(ctx, batch)
        mmd = self._deep_mmd_penalty(local_feats, target_feats,
                                     state.extra["deep_mmd"], batch.example_mask)
        parts["deep_mmd"] = mmd
        return total + self.deep_mmd_weight * mmd, parts

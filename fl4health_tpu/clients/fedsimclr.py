"""FedSimCLR client — federated self-supervised contrastive pretraining.

Parity: /root/reference/examples/fedsimclr_example/
fedsimclr_pretraining_example/client.py + model_bases/fedsimclr_base.py:12
and losses/contrastive_loss.py:95 (NtXentLoss). Batches carry
(input_view, transformed_view) as (x, y) — the reference's SslTensorDataset
yields exactly that pairing, and its ``transform_target`` runs the model on
the target view (client.py:84-85). The fine-tuning stage
(pretrain=False + prediction head) is plain BasicClient classification.
"""

from __future__ import annotations

import jax

from fl4health_tpu.clients.engine import Batch, ClientLogic, TrainState
from fl4health_tpu.losses.contrastive import ntxent_loss


class FedSimClrClientLogic(ClientLogic):
    """Pretraining logic: NT-Xent between the projections of the two views.
    Pair with models.bases.FedSimClrModel(pretrain=True)."""

    def __init__(self, model, temperature: float = 0.5):
        super().__init__(model, criterion=None)
        self.temperature = temperature

    def predict(self, params, model_state, batch: Batch, rng, train: bool,
                extra=None, ctx=None):
        (preds, features), new_state = self.model.apply(
            params, model_state, batch.x, train=train, rng=rng
        )
        # transform_target equivalent: the second view through the same model,
        # with decorrelated stochasticity (fresh dropout/mask noise per view).
        view_rng = None if rng is None else jax.random.fold_in(rng, 1)
        (t_preds, _), new_state = self.model.apply(
            params, new_state, batch.y, train=train, rng=view_rng
        )
        preds = {**preds, "transformed": t_preds["prediction"]}
        return (preds, features), new_state

    def _ntxent(self, preds, batch: Batch):
        return ntxent_loss(
            preds["prediction"], preds["transformed"],
            temperature=self.temperature, mask=batch.example_mask,
        )

    def training_loss(self, preds, features, batch: Batch, params,
                      state: TrainState, ctx):
        return self._ntxent(preds, batch), {}

    def eval_loss(self, preds, features, batch: Batch, params,
                  state: TrainState, ctx):
        return self._ntxent(preds, batch), {}

"""Dynamic personalization wrapper — ``make_it_personal`` for client logics.

Parity: /root/reference/fl4health/mixins/personalized/__init__.py:19
(``make_it_personal(client_class, mode)``) and the Ditto / MR-MTL mixins
(mixins/personalized/ditto.py, mr_mtl.py): wrap ANY client in a personalized
variant without writing a combined subclass.  The reference builds a dynamic
class whose MRO injects the mixin; here personalization is a *logic
combinator*: ``make_it_personal(base_logic, PersonalizedMode.DITTO)`` returns
a new ``ClientLogic`` that

- DITTO: twins the base model (exchanged ``global_model`` + private
  ``personal_model``), runs the base logic's full loss machinery on the
  personal branch, trains the global branch with the plain criterion, and
  adds the l2 drift penalty pulling personal weights toward the received
  global weights (clients/ditto_client.py:20 semantics).
- MR_MTL: keeps the base model single, never overwrites local weights on
  pull (pair with ``KeepLocalExchanger``), and adds the drift penalty toward
  the received aggregate (clients/mr_mtl_client.py:18 semantics).

Scope: the wrapper composes with logics that use the default ``predict``
path (criterion + training_loss/eval_loss + extra/finalize hooks). Logics
whose forward signature is bespoke (APFL's alpha-blend, GPFL's conditional
inputs) are already personalized by construction and don't need wrapping —
the same boundary the reference's mixins have in practice.

TPU-native design: the twin is built at the ``ModelDef`` level (not a flax
module wrapper), so any base ModelDef — flax or hand-rolled — twins the same
way, and the base logic sees plain single-model params/state *views* of the
twin tree, keeping its own code byte-identical whether wrapped or not.
"""

from __future__ import annotations

import enum
from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.clients.ditto import KeepLocalExchanger
from fl4health_tpu.clients.engine import Batch, ClientLogic, ModelDef, TrainState
from fl4health_tpu.core.types import Params
from fl4health_tpu.exchange.packer import AdaptiveConstraintPacket
from fl4health_tpu.losses.drift import weight_drift_loss

GLOBAL = "global_model"
PERSONAL = "personal_model"


class PersonalizedMode(enum.Enum):
    DITTO = "ditto"
    MR_MTL = "mr_mtl"


def twin_model_def(base: ModelDef) -> ModelDef:
    """Two independent copies of a base ModelDef under ``global_model`` /
    ``personal_model`` subtrees (models.bases.TwinModel layout, but at the
    ModelDef level so non-flax models twin too)."""

    def init(rng, sample_x):
        rg, rp = jax.random.split(rng)
        pg, sg = base.init(rg, sample_x)
        pp, sp = base.init(rp, sample_x)
        return {GLOBAL: pg, PERSONAL: pp}, {GLOBAL: sg, PERSONAL: sp}

    def apply(params, model_state, x, train=True, rng=None, **kwargs):
        # Independent noise per branch (dropout/masks/VAE sampling must not
        # be correlated between the twins, matching flax TwinModel's
        # per-submodule rng folding).
        rng_g = rng_p = None
        if rng is not None:
            rng_g, rng_p = jax.random.split(rng)
        (g_preds, g_feats), g_ms = base.apply(
            params[GLOBAL], model_state[GLOBAL], x, train=train, rng=rng_g,
            **kwargs,
        )
        (p_preds, p_feats), p_ms = base.apply(
            params[PERSONAL], model_state[PERSONAL], x, train=train, rng=rng_p,
            **kwargs,
        )
        preds = {
            "global": g_preds["prediction"],
            "personal": p_preds["prediction"],
            # Validation / metrics run on the personal model (ditto_client.py
            # validate path).
            "prediction": p_preds["prediction"],
            "_global_preds": g_preds,
            "_personal_preds": p_preds,
        }
        features = {"global": g_feats, "personal": p_feats}
        return (preds, features), {GLOBAL: g_ms, PERSONAL: p_ms}

    return ModelDef(init=init, apply=apply)


def exchange_global_subtree(path: str) -> bool:
    """Exchange predicate for the twin tree (TwinModel.exchange_global_model)."""
    return path.startswith(GLOBAL)


@struct.dataclass
class _DittoWrapCtx:
    base_ctx: Any
    received_global: Params
    drift_penalty_weight: Any


class DittoPersonalizedLogic(ClientLogic):
    """``base`` logic on the personal branch + vanilla global branch + drift
    penalty. Pair with ``FixedLayerExchanger(exchange_global_subtree)``."""

    def __init__(self, base: ClientLogic, lam: float = 1.0, adaptive: bool = False):
        super().__init__(twin_model_def(base.model), base.criterion)
        self.base = base
        self.lam = lam
        self.adaptive = adaptive
        self.extra_loss_keys = ("global_loss", "penalty") + tuple(
            f"personal_{k}" for k in getattr(base, "extra_loss_keys", ())
        )
        self.eval_loss_keys = tuple(
            f"personal_{k}" for k in getattr(base, "eval_loss_keys", ())
        )

    # -- personal-branch views ---------------------------------------------
    def _view(self, state: TrainState, params: Params | None = None) -> TrainState:
        p = params if params is not None else state.params
        return state.replace(params=p[PERSONAL], model_state=state.model_state[PERSONAL])

    def init_extra(self, params: Params):
        return self.base.init_extra(params[PERSONAL])

    def augment(self, batch: Batch, rng, ctx: _DittoWrapCtx) -> Batch:
        """Forward the base logic's train-time augmentation (e.g. nnU-Net's
        on-device transforms) — a personalized wrapper must not silently
        drop the wrapped algorithm's regularization."""
        return self.base.augment(batch, rng, ctx.base_ctx)

    def init_round_context(self, state: TrainState, payload) -> _DittoWrapCtx:
        lam = getattr(payload, "drift_penalty_weight", None)
        if lam is None:
            lam = jnp.asarray(self.lam, jnp.float32)
        payload_params = payload.params if hasattr(payload, "params") else payload
        received = payload_params[GLOBAL]
        # The base logic sees the received global weights as ITS payload
        # (the reference mixin's base client snapshots the received model).
        base_ctx = self.base.init_round_context(self._view(state), received)
        return _DittoWrapCtx(
            base_ctx=base_ctx,
            received_global=received,
            drift_penalty_weight=lam,
        )

    def training_loss(self, preds, features, batch: Batch, params, state,
                      ctx: _DittoWrapCtx):
        if self.criterion is not None:
            global_loss = self.criterion(preds["global"], batch.y,
                                         batch.example_mask)
        else:
            # Criterion-less logics (e.g. nnU-Net's deep-supervision
            # composite): the global branch trains with the base's own
            # vanilla training loss, like the reference's nnunet_pfl combo.
            global_view = state.replace(
                params=params[GLOBAL], model_state=state.model_state[GLOBAL]
            )
            global_loss, _ = self.base.training_loss(
                preds["_global_preds"], features["global"], batch,
                params[GLOBAL], global_view, ctx.base_ctx,
            )
        personal_loss, personal_extra = self.base.training_loss(
            preds["_personal_preds"], features["personal"], batch,
            params[PERSONAL], self._view(state, params), ctx.base_ctx,
        )
        penalty = 0.5 * weight_drift_loss(
            params[PERSONAL], ctx.received_global, ctx.drift_penalty_weight
        )
        total = global_loss + personal_loss + penalty
        out = {"global_loss": global_loss, "penalty": penalty}
        out.update({f"personal_{k}": v for k, v in personal_extra.items()})
        return total, out

    def eval_loss(self, preds, features, batch: Batch, params, state, ctx):
        base_ctx = ctx.base_ctx if isinstance(ctx, _DittoWrapCtx) else ctx
        loss, extra = self.base.eval_loss(
            preds["_personal_preds"], features["personal"], batch,
            params[PERSONAL], self._view(state, params), base_ctx,
        )
        return loss, {f"personal_{k}": v for k, v in extra.items()}

    def transform_gradients(self, grads: Params, state: TrainState,
                            ctx: _DittoWrapCtx) -> Params:
        personal = self.base.transform_gradients(
            grads[PERSONAL], self._view(state), ctx.base_ctx
        )
        return {**grads, PERSONAL: personal}

    def _merge_hook(self, state: TrainState, new_view: TrainState) -> TrainState:
        # Hooks mutate extra/rng/step — params stay with the engine's step.
        return state.replace(extra=new_view.extra, rng=new_view.rng)

    def update_before_step(self, state, ctx: _DittoWrapCtx, batch):
        return self._merge_hook(
            state, self.base.update_before_step(self._view(state), ctx.base_ctx, batch)
        )

    def update_after_step(self, state, ctx: _DittoWrapCtx, batch, preds=None):
        base_preds = None if preds is None else preds["_personal_preds"]
        return self._merge_hook(
            state,
            self.base.update_after_step(
                self._view(state), ctx.base_ctx, batch, base_preds
            ),
        )

    def finalize_round(self, state, ctx: _DittoWrapCtx, local_steps):
        return self._merge_hook(
            state,
            self.base.finalize_round(self._view(state), ctx.base_ctx, local_steps),
        )

    def pack(self, state: TrainState, pushed_params, train_losses):
        if not self.adaptive:
            return pushed_params
        return AdaptiveConstraintPacket(
            params=pushed_params,
            loss_for_adaptation=train_losses["global_loss"],
        )


@struct.dataclass
class _MrMtlWrapCtx:
    base_ctx: Any
    initial_params: Params
    drift_penalty_weight: Any


class MrMtlPersonalizedLogic(ClientLogic):
    """``base`` logic + drift penalty toward the received aggregate; pair
    with ``KeepLocalExchanger`` so local weights are never overwritten.

    This generalizes ``MrMtlClientLogic`` (clients/ditto.py, kept separate
    for its reference-parity loss-key names); the two are pinned numerically
    identical on a plain base by
    tests/clients/test_make_it_personal.py::test_mr_mtl_personalized_plain_matches_mr_mtl_logic,
    so a change to the MR-MTL math in either place fails that test."""

    def __init__(self, base: ClientLogic, lam: float = 1.0, adaptive: bool = False):
        super().__init__(base.model, base.criterion)
        self.base = base
        self.lam = lam
        self.adaptive = adaptive
        # Base extras are namespaced (a base that itself reports "penalty",
        # e.g. FedProx, must not shadow the MR-MTL drift penalty).
        self.extra_loss_keys = ("base_loss", "penalty") + tuple(
            f"base_{k}" for k in getattr(base, "extra_loss_keys", ())
        )
        self.eval_loss_keys = tuple(getattr(base, "eval_loss_keys", ()))

    def init_extra(self, params: Params):
        return self.base.init_extra(params)

    def augment(self, batch: Batch, rng, ctx) -> Batch:
        """Forward the base logic's train-time augmentation (see the Ditto
        wrapper's note)."""
        base_ctx = ctx.base_ctx if isinstance(ctx, _MrMtlWrapCtx) else ctx
        return self.base.augment(batch, rng, base_ctx)

    def init_round_context(self, state: TrainState, payload) -> _MrMtlWrapCtx:
        lam = getattr(payload, "drift_penalty_weight", None)
        if lam is None:
            lam = jnp.asarray(self.lam, jnp.float32)
        payload_params = payload.params if hasattr(payload, "params") else payload
        base_ctx = self.base.init_round_context(state, payload)
        return _MrMtlWrapCtx(
            base_ctx=base_ctx,
            initial_params=payload_params,
            drift_penalty_weight=lam,
        )

    def predict(self, params, model_state, batch, rng, train, extra=None, ctx=None):
        base_ctx = ctx.base_ctx if isinstance(ctx, _MrMtlWrapCtx) else ctx
        return self.base.predict(params, model_state, batch, rng, train,
                                 extra=extra, ctx=base_ctx)

    def training_loss(self, preds, features, batch: Batch, params, state,
                      ctx: _MrMtlWrapCtx):
        base_loss, base_extra = self.base.training_loss(
            preds, features, batch, params, state, ctx.base_ctx
        )
        penalty = 0.5 * weight_drift_loss(
            params, ctx.initial_params, ctx.drift_penalty_weight
        )
        out = {"base_loss": base_loss, "penalty": penalty}
        out.update({f"base_{k}": v for k, v in base_extra.items()})
        return base_loss + penalty, out

    def eval_loss(self, preds, features, batch: Batch, params, state, ctx):
        base_ctx = ctx.base_ctx if isinstance(ctx, _MrMtlWrapCtx) else ctx
        return self.base.eval_loss(preds, features, batch, params, state, base_ctx)

    def transform_gradients(self, grads, state, ctx: _MrMtlWrapCtx):
        return self.base.transform_gradients(grads, state, ctx.base_ctx)

    def update_before_step(self, state, ctx: _MrMtlWrapCtx, batch):
        return self.base.update_before_step(state, ctx.base_ctx, batch)

    def update_after_step(self, state, ctx: _MrMtlWrapCtx, batch, preds=None):
        return self.base.update_after_step(state, ctx.base_ctx, batch, preds)

    def finalize_round(self, state, ctx: _MrMtlWrapCtx, local_steps):
        return self.base.finalize_round(state, ctx.base_ctx, local_steps)

    def pack(self, state: TrainState, pushed_params, train_losses):
        if not self.adaptive:
            return pushed_params
        return AdaptiveConstraintPacket(
            params=pushed_params,
            loss_for_adaptation=train_losses["base_loss"],
        )


def make_it_personal(
    base: ClientLogic,
    mode: PersonalizedMode,
    lam: float = 1.0,
    adaptive: bool = False,
) -> ClientLogic:
    """Wrap ``base`` into its personalized variant
    (mixins/personalized/__init__.py:19).

    Returns the wrapped logic; wire the matching exchanger:
    ``FixedLayerExchanger(exchange_global_subtree)`` for DITTO,
    ``KeepLocalExchanger()`` for MR_MTL (exported here for convenience).
    """
    # The wrappers compose via training_loss/eval_loss/hooks. A base that
    # overrides the gradient computation itself (DP logics' per-example
    # clip+noise) or — for DITTO — the forward, would be SILENTLY bypassed;
    # make that a loud error rather than e.g. a run that drops its privacy
    # guarantee.
    if type(base).value_and_grads is not ClientLogic.value_and_grads:
        raise TypeError(
            f"make_it_personal cannot wrap {type(base).__name__}: it overrides "
            "value_and_grads (e.g. DP per-example gradients), which the "
            "personalization wrapper would silently discard. Compose DP with "
            "the dedicated client instead (e.g. DittoClientLogic + "
            "InstanceLevelDpMixin)."
        )
    if mode is PersonalizedMode.DITTO:
        if type(base).predict is not ClientLogic.predict:
            raise TypeError(
                f"make_it_personal(DITTO) cannot wrap {type(base).__name__}: "
                "it overrides predict; the twin forward calls the base MODEL "
                "directly, so a bespoke forward (APFL/GPFL-style) would be "
                "bypassed. Those logics are already personalized by design."
            )
        return DittoPersonalizedLogic(base, lam=lam, adaptive=adaptive)
    if mode is PersonalizedMode.MR_MTL:
        return MrMtlPersonalizedLogic(base, lam=lam, adaptive=adaptive)
    raise ValueError(f"unknown personalization mode: {mode}")


__all__ = [
    "PersonalizedMode",
    "make_it_personal",
    "DittoPersonalizedLogic",
    "MrMtlPersonalizedLogic",
    "twin_model_def",
    "exchange_global_subtree",
    "KeepLocalExchanger",
]

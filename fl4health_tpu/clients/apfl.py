"""APFL client logic — adaptive personalized federated learning.

Parity: /root/reference/fl4health/clients/apfl_client.py:18 +
model_bases/apfl_base.py:9. Twin local/global models; the personal
prediction is the alpha-mixture of their logits. Each train step updates the
global model with the global loss and the local model with the personal
(mixed) loss; when ``adaptive_alpha`` is on, alpha takes its own gradient
step after each batch (``ApflModule.update_alpha``, apfl_base.py:86) and is
clipped to [0, 1].

TPU-native design: alpha lives in the persistent ``extra`` state (it never
crosses the wire); its gradient is taken by autodiff through the mixing —
the exact quantity the reference computes manually:
d(personal_loss)/d(alpha) = <dL/d(mix), local_logits - global_logits>.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.clients.engine import Batch, ClientLogic, TrainState


@struct.dataclass
class ApflExtra:
    alpha: jax.Array  # scalar in [0, 1]


class ApflClientLogic(ClientLogic):
    """Pair with ``models.bases.ApflModule`` and a FixedLayerExchanger on
    ``ApflModule.exchange_global_model``."""

    extra_loss_keys = ("global_ce", "personal_ce")

    def __init__(self, model, criterion, alpha: float = 0.5,
                 alpha_lr: float = 0.01, adaptive_alpha: bool = True):
        super().__init__(model, criterion)
        self.alpha0 = alpha
        self.alpha_lr = alpha_lr
        self.adaptive_alpha = adaptive_alpha

    def init_extra(self, params) -> ApflExtra:
        return ApflExtra(alpha=jnp.asarray(self.alpha0, jnp.float32))

    def predict(self, params, model_state, batch: Batch, rng, train: bool,
                extra=None, ctx=None):
        alpha = extra.alpha if extra is not None else jnp.asarray(self.alpha0)
        return self.model.apply(
            params, model_state, batch.x, train=train, rng=rng, alpha=alpha
        )

    def training_loss(self, preds, features, batch: Batch, params, state, ctx):
        # Global model learns from its own logits; the local model learns from
        # the mixture with the global branch frozen (the reference steps the
        # local optimizer on the personal loss only, apfl_client.py train_step).
        global_ce = self.criterion(preds["global"], batch.y, batch.example_mask)
        alpha = state.extra.alpha
        mixed = alpha * preds["local"] + (1.0 - alpha) * jax.lax.stop_gradient(
            preds["global"]
        )
        personal_ce = self.criterion(mixed, batch.y, batch.example_mask)
        return global_ce + personal_ce, {
            "global_ce": global_ce,
            "personal_ce": personal_ce,
        }

    def update_after_step(self, state: TrainState, ctx, batch: Batch,
                          preds=None) -> TrainState:
        if not self.adaptive_alpha:
            return state
        # alpha <- clip(alpha - lr * dL_personal/dalpha) (apfl_base.py:86).
        # The step's logits are reused, so the gradient only flows through the
        # mixing — d(personal)/d(alpha) = <dL/d(mix), local - global>, the
        # reference's analytic formula, at no extra model cost.
        local = jax.lax.stop_gradient(preds["local"])
        glob = jax.lax.stop_gradient(preds["global"])

        def personal_loss(alpha):
            mixed = alpha * local + (1.0 - alpha) * glob
            return self.criterion(mixed, batch.y, batch.example_mask)

        g = jax.grad(personal_loss)(state.extra.alpha)
        new_alpha = jnp.clip(state.extra.alpha - self.alpha_lr * g, 0.0, 1.0)
        # Padding steps must not move alpha.
        new_alpha = jnp.where(batch.step_mask > 0, new_alpha, state.extra.alpha)
        return state.replace(extra=ApflExtra(alpha=new_alpha))

    def eval_loss(self, preds, features, batch: Batch, params, state, ctx):
        return self.criterion(preds["personal"], batch.y, batch.example_mask), {}


def apfl_model_def(module):
    """ModelDef adapter for ApflModule — ``engine.from_flax`` forwards the
    alpha kwarg (and handles mutable collections) already."""
    from fl4health_tpu.clients.engine import from_flax

    return from_flax(module)

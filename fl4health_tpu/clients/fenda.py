"""FENDA, Constrained FENDA, FENDA+Ditto, and PerFCL client logics.

Parity targets:
- FENDA (/root/reference/fl4health/clients/fenda_client.py:17): a
  ParallelSplitModel whose ``second_feature_extractor`` is exchanged; no
  extra loss terms — vanilla FENDA is BasicClient + the FENDA exchanger.
- Constrained FENDA (constrained_fenda_client.py:22): optional auxiliary
  losses from fenda_loss_config.py — cosine-similarity between current local
  and global features, a MOON-style contrastive on local features, and/or
  the PerFCL pair.
- PerFCL (perfcl_client.py:20, losses/perfcl_loss.py:7): two MOON-style
  contrastive losses —
  global term: anchor = current global features z_s, positive = features of
  the AGGREGATED (received) global extractor z_g, negative = features of the
  previous round's FINAL global extractor;
  local term: anchor = current local features z_p, positive = previous
  round's final local features, negative = z_g.
- FENDA+Ditto (fenda_ditto_client.py:21): a FENDA personal model whose
  global extractor is drift-constrained toward a received global FENDA model.

All of these persist previous-round extractor params in ``extra`` and the
received params in the round context — pure pytree state under vmap, no
model cloning.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.clients.engine import Batch, ClientLogic, TrainState
from fl4health_tpu.core.types import Params
from fl4health_tpu.losses.contrastive import (
    cosine_similarity,
    moon_contrastive_loss,
)
from fl4health_tpu.losses.drift import weight_drift_loss


# FENDA needs no logic subclass: use ClientLogic with
# FixedLayerExchanger(ParallelSplitModel.exchange_global_extractor).
FendaClientLogic = ClientLogic


@struct.dataclass
class PerFclExtra:
    old_params: Params  # final params from the previous round
    have_old: jax.Array  # 0/1 — previous round exists


@struct.dataclass
class PerFclContext:
    # Snapshot of the post-pull params — the runnable AGGREGATED model
    # (init_round_context runs after exchanger.pull, so state.params is the
    # merged model at round start; perfcl_client.py update_before_train).
    initial_params: Params


class PerFclClientLogic(ClientLogic):
    """Pair with ``models.bases.PerFclModel`` (= ParallelSplitModel exposing
    ``local_features`` / ``global_features``) and the FENDA exchanger."""

    extra_loss_keys = ("vanilla", "global_contrastive", "local_contrastive")

    def __init__(self, model, criterion,
                 global_feature_loss_weight: float = 1.0,
                 local_feature_loss_weight: float = 1.0,
                 global_feature_loss_temperature: float = 0.5,
                 local_feature_loss_temperature: float = 0.5):
        super().__init__(model, criterion)
        self.mu = global_feature_loss_weight
        self.gamma = local_feature_loss_weight
        self.t_global = global_feature_loss_temperature
        self.t_local = local_feature_loss_temperature

    def init_extra(self, params: Params) -> PerFclExtra:
        return PerFclExtra(old_params=params, have_old=jnp.zeros((), jnp.float32))

    def init_round_context(self, state: TrainState, payload) -> PerFclContext:
        del payload
        return PerFclContext(
            initial_params=jax.lax.stop_gradient(state.params)
        )

    def _features(self, params, model_state, x, rng):
        (_, features), _ = self.model.apply(params, model_state, x, train=False, rng=rng)
        return features

    def training_loss(self, preds, features, batch: Batch, params, state,
                      ctx: PerFclContext):
        vanilla = self.criterion(preds["prediction"], batch.y, batch.example_mask)
        rng = jax.random.fold_in(state.rng, 17)
        # Frozen feature passes (perfcl_client.py predict gathers these).
        old_f = jax.lax.stop_gradient(
            self._features(state.extra.old_params, state.model_state, batch.x, rng)
        )
        init_f = jax.lax.stop_gradient(
            self._features(ctx.initial_params, state.model_state, batch.x, rng)
        )
        z_p = features["local_features"]
        z_s = features["global_features"]
        # Temperatures may differ per term, so call perfcl_loss's two halves
        # explicitly (losses/contrastive.py:perfcl_loss).
        g_term = moon_contrastive_loss(
            z_s, init_f["global_features"][None], old_f["global_features"][None],
            self.t_global, batch.example_mask,
        )
        l_term = moon_contrastive_loss(
            z_p, old_f["local_features"][None], init_f["global_features"][None],
            self.t_local, batch.example_mask,
        )
        have_old = state.extra.have_old
        g_term = g_term * have_old
        l_term = l_term * have_old
        total = vanilla + self.mu * g_term + self.gamma * l_term
        return total, {
            "vanilla": vanilla,
            "global_contrastive": g_term,
            "local_contrastive": l_term,
        }

    def finalize_round(self, state: TrainState, ctx, local_steps) -> TrainState:
        return state.replace(
            extra=PerFclExtra(old_params=state.params,
                              have_old=jnp.ones((), jnp.float32))
        )


@struct.dataclass
class ConstrainedFendaExtra:
    old_local_params: Params
    have_old: jax.Array


class ConstrainedFendaClientLogic(ClientLogic):
    """Constrained FENDA (constrained_fenda_client.py:22): vanilla FENDA plus
    any of — cosine-similarity loss between local and global features
    (minimizing |cos|, cosine_similarity_loss.py:5), a MOON contrastive on
    local features vs the previous round's local extractor, and the PerFCL
    pair (delegated to PerFclClientLogic when wanted alone)."""

    extra_loss_keys = ("vanilla", "cos_sim", "contrastive")

    def __init__(self, model, criterion,
                 cos_sim_loss_weight: float = 0.0,
                 contrastive_loss_weight: float = 0.0,
                 temperature: float = 0.5):
        super().__init__(model, criterion)
        self.cos_w = cos_sim_loss_weight
        self.con_w = contrastive_loss_weight
        self.temperature = temperature

    def init_extra(self, params: Params) -> ConstrainedFendaExtra:
        return ConstrainedFendaExtra(
            old_local_params=params, have_old=jnp.zeros((), jnp.float32)
        )

    def training_loss(self, preds, features, batch: Batch, params, state, ctx):
        vanilla = self.criterion(preds["prediction"], batch.y, batch.example_mask)
        m = batch.example_mask.astype(jnp.float32)
        z_p, z_s = features["local_features"], features["global_features"]
        # Squared cosine similarity pushes the two streams orthogonal
        # (cosine_similarity_loss.py:5).
        cos_sim = jnp.sum(jnp.square(cosine_similarity(z_p, z_s)) * m) / jnp.maximum(
            jnp.sum(m), 1.0
        )
        contrastive = jnp.zeros(())
        if self.con_w > 0.0:
            rng = jax.random.fold_in(state.rng, 19)
            (_, old_feats), _ = self.model.apply(
                state.extra.old_local_params, state.model_state, batch.x,
                train=False, rng=rng,
            )
            old_local = jax.lax.stop_gradient(old_feats["local_features"])
            # Positive = current global stream, negative = old local stream
            # (fenda_loss_config.py MoonContrastiveLossContainer usage).
            contrastive = moon_contrastive_loss(
                z_p, jax.lax.stop_gradient(z_s)[None], old_local[None],
                self.temperature, batch.example_mask,
            ) * state.extra.have_old
        total = vanilla + self.cos_w * cos_sim + self.con_w * contrastive
        return total, {"vanilla": vanilla, "cos_sim": cos_sim,
                       "contrastive": contrastive}

    def finalize_round(self, state: TrainState, ctx, local_steps) -> TrainState:
        return state.replace(
            extra=ConstrainedFendaExtra(
                old_local_params=state.params, have_old=jnp.ones((), jnp.float32)
            )
        )


@struct.dataclass
class FendaDittoContext:
    initial_global_params: Params  # received FENDA model (drift target for the
    # personal model's global extractor)
    drift_penalty_weight: jax.Array


class FendaDittoClientLogic(ClientLogic):
    """FENDA + Ditto (fenda_ditto_client.py:21): the personal FENDA model's
    GLOBAL extractor is drift-constrained toward the received global weights;
    the global model subtree is exchanged. Pair with models.bases.TwinModel
    wrapping two FENDA models, exchanging ``global_model.second_feature_extractor``."""

    extra_loss_keys = ("global_ce", "personal_ce", "penalty")

    def __init__(self, model, criterion, lam: float = 1.0):
        super().__init__(model, criterion)
        self.lam = lam

    def init_round_context(self, state: TrainState, payload) -> FendaDittoContext:
        lam = getattr(payload, "drift_penalty_weight", None)
        if lam is None:
            lam = jnp.asarray(self.lam, jnp.float32)
        payload_params = payload.params if hasattr(payload, "params") else payload
        return FendaDittoContext(
            initial_global_params=payload_params["global_model"][
                "second_feature_extractor"
            ],
            drift_penalty_weight=lam,
        )

    def training_loss(self, preds, features, batch: Batch, params, state,
                      ctx: FendaDittoContext):
        global_ce = self.criterion(preds["global"], batch.y, batch.example_mask)
        personal_ce = self.criterion(preds["personal"], batch.y, batch.example_mask)
        penalty = 0.5 * weight_drift_loss(
            params["personal_model"]["second_feature_extractor"],
            ctx.initial_global_params,
            ctx.drift_penalty_weight,
        )
        total = global_ce + personal_ce + penalty
        return total, {"global_ce": global_ce, "personal_ce": personal_ce,
                       "penalty": penalty}

    def eval_loss(self, preds, features, batch: Batch, params, state, ctx):
        return self.criterion(preds["personal"], batch.y, batch.example_mask), {}

"""FedPM client — trains Bernoulli scores over frozen weights, ships masks.

Parity: /root/reference/fl4health/clients/fedpm_client.py:18 + the
FedPmExchanger (parameter_exchange/fedpm_exchanger.py:10, sampling in
parameter_selection_criteria.py:202): training is the BasicClient loop over
a masked model; ``get_parameters`` samples binary masks from
sigmoid(scores); the server's Beta-posterior aggregate theta is loaded
DIRECTLY into the score tensors on pull (the reference deliberately allows
this score/probability aliasing — parameter_selection_criteria.py:230-234).

TPU-native design: the model is built from models.masked layers (scores are
ordinary flax params; frozen weights live in the ``frozen`` collection of
model_state), so the whole BasicClient machinery applies unchanged. Mask
sampling happens in ``pack`` with the client's traced PRNG (the reference's
exchanger-side scipy sampling would freeze into a jit constant here), and
the plain FullExchanger handles the theta pull.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fl4health_tpu.clients.engine import ClientLogic, TrainState
from fl4health_tpu.core.types import Params


def sample_masks(scores: Params, rng: jax.Array) -> Params:
    """Binary masks ~ Bernoulli(sigmoid(scores)) leaf-wise
    (parameter_selection_criteria.py:202-205)."""
    leaves, treedef = jax.tree_util.tree_flatten(scores)
    keys = jax.random.split(rng, len(leaves))
    sampled = [
        jax.random.bernoulli(k, jax.nn.sigmoid(leaf)).astype(jnp.float32)
        for k, leaf in zip(keys, leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, sampled)


class FedPmClientLogic(ClientLogic):
    """BasicClient training over a masked model (fedpm_client.py:18). The
    trainable params ARE the scores; per-forward mask sampling happens inside
    the masked layers (models/masked.py) via the ``mask`` rng stream; the
    wire packet is one sampled binary mask per score tensor."""

    def pack(self, state: TrainState, pushed_params: Params, train_losses: dict):
        return sample_masks(pushed_params, jax.random.fold_in(state.rng, state.step))

"""Client training engine — the reference's BasicClient loop, TPU-native.

Reference behavior (/root/reference/fl4health/clients/basic_client.py):
``train_by_epochs``/``train_by_steps`` (:627,:699) iterate a DataLoader in
eager PyTorch: train_step = zero_grad -> predict -> loss -> backward ->
transform_gradients -> step (:578-605), with hook methods before/after
steps/epochs (:1233-1302), loss meters + metric managers, and ``validate``
(:867) running val + optional test loaders.

TPU-native design: one local-training phase is ONE compiled program —
``lax.scan`` over a statically-shaped stack of batches. Heterogeneous client
data sizes are handled by padding to the cohort max with per-step and
per-example masks (empty-batch semantics of basic_client.py:660-662 become
mask arithmetic). Algorithm variants plug in as pure functions on a
``ClientLogic`` object; persistent aux state (control variates, personal
models) rides in ``TrainState.extra`` and is vmappable across the clients
axis, so N simulated clients train as one SPMD program.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp
import numpy as np
import optax
from flax import struct

from fl4health_tpu.core.pytree import tree_nbytes
from fl4health_tpu.core.types import Params, PRNGKey, PyTree
from fl4health_tpu.losses.containers import LossMeter
from fl4health_tpu.precision import policy as precision_policy
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.observability import stages as stage_attr
from fl4health_tpu.observability.registry import get_registry
from fl4health_tpu.observability.spans import get_tracer


# ---------------------------------------------------------------------------
# Data containers
# ---------------------------------------------------------------------------

@struct.dataclass
class Batch:
    """One step's data. Leading [steps] axis when stacked for scan.

    example_mask: [B] validity (ragged final batch -> zeros); step_mask: scalar
    0/1 (padding steps beyond a client's true data length are full no-ops).
    """

    x: jax.Array
    y: jax.Array
    example_mask: jax.Array
    step_mask: jax.Array


@struct.dataclass
class TrainState:
    """Scan carry for local training."""

    params: Params
    opt_state: Any
    model_state: Any  # mutable collections (batch_stats); empty dict if none
    rng: PRNGKey
    step: jax.Array
    extra: Any = None  # algorithm-specific persistent state
    # dynamic loss-scale state ({"scale", "growth", "skipped"}) when the
    # precision policy scales (fp16); None otherwise — an empty pytree
    # node, so precision-off states keep their legacy structure exactly
    loss_scale: Any = None


@struct.dataclass
class StepOutput:
    losses: Any  # dict of scalars (backward + additional)
    preds: jax.Array
    targets: jax.Array
    example_mask: jax.Array
    step_mask: jax.Array
    # global norm of the post-transform_gradients gradient — populated only
    # when the train maker was built with collect_telemetry=True (None is an
    # empty pytree node, so the default costs nothing)
    grad_norm: Any = None


# ---------------------------------------------------------------------------
# Model definition — framework-agnostic adapter (flax, haiku, hand-rolled)
# ---------------------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class ModelDef:
    """init(rng, sample_x) -> (params, model_state)
    apply(params, model_state, x, train, rng) -> ((preds, features), model_state)

    ``preds`` is a dict with at least key "prediction"; ``features`` is a dict
    of intermediate activations (reference predict() contract,
    basic_client.py:992).
    """

    init: Callable[[PRNGKey, jax.Array], tuple[Params, Any]]
    apply: Callable[..., tuple[tuple[dict, dict], Any]]


def from_flax(module, mutable: tuple[str, ...] = ("batch_stats",)) -> ModelDef:
    """Wrap a flax.linen module whose __call__ returns either an array or a
    (preds_dict, features_dict) pair."""

    def init(rng, sample_x):
        variables = module.init(
            {"params": rng, "dropout": rng, "mask": rng, "sampling": rng},
            sample_x, train=False,
        )
        params = variables["params"]
        model_state = {k: v for k, v in variables.items() if k != "params"}
        return params, model_state

    def apply(params, model_state, x, train=True, rng=None, **kwargs):
        # Extra kwargs (e.g. APFL's alpha, GPFL's conditional inputs) are
        # forwarded to the module so algorithm-specific forwards don't need
        # their own adapter. The extra rng streams serve masked layers
        # ("mask", models/masked.py) and VAE reparameterization ("sampling").
        variables = {"params": params, **(model_state or {})}
        # Stochastic streams only while training: eval uses the masked
        # layers' deterministic expectation and the VAEs' fixed noise so
        # repeated validation of identical params agrees (checkpoint/early-
        # stop selection must not ride sampling noise).
        rngs = {}
        if rng is not None:
            rngs["dropout"] = rng
            if train:
                rngs["mask"] = jax.random.fold_in(rng, 1)
                rngs["sampling"] = jax.random.fold_in(rng, 2)
        if train and model_state:
            out, new_state = module.apply(
                variables, x, train=True, rngs=rngs,
                mutable=list(model_state.keys()), **kwargs
            )
        else:
            out = module.apply(variables, x, train=train, rngs=rngs, **kwargs)
            new_state = model_state
        if isinstance(out, tuple):
            preds, features = out
        else:
            preds, features = {"prediction": out}, {}
        return (preds, features), new_state

    return ModelDef(init=init, apply=apply)


# ---------------------------------------------------------------------------
# Client logic — the algorithm plug-in surface
# ---------------------------------------------------------------------------

class ClientLogic:
    """Pure-function hook surface mirroring BasicClient's override points.

    Subclasses override any of these; all must stay jit-traceable. ``ctx`` is
    the per-round context (e.g. snapshot of the received global params, the
    drift penalty weight) built once per round by ``init_round_context``.
    """

    def __init__(self, model: ModelDef, criterion: Callable):
        self.model = model
        self.criterion = criterion  # (preds_array, targets, example_mask) -> scalar

    # -- round lifecycle ----------------------------------------------------
    def init_extra(self, params: Params) -> Any:
        """Persistent algorithm state created at client setup (round 1)."""
        return None

    def init_round_context(self, state: TrainState, server_payload: Any) -> Any:
        """Per-round constants (update_before_train, basic_client.py:1233)."""
        return None

    def finalize_round(self, state: TrainState, ctx: Any, local_steps: jax.Array) -> TrainState:
        """update_after_train (basic_client.py:1248) — e.g. SCAFFOLD variates."""
        return state

    # -- step ---------------------------------------------------------------
    def predict(self, params, model_state, batch: Batch, rng, train: bool,
                extra=None, ctx=None):
        """(basic_client.py:992). ``extra`` is the persistent algorithm state
        (e.g. APFL's alpha); ``ctx`` the per-round context (e.g. GPFL's frozen
        conditional inputs) for logics whose forward depends on them."""
        del extra, ctx
        return self.model.apply(params, model_state, batch.x, train=train, rng=rng)

    def training_loss(
        self, preds: dict, features: dict, batch: Batch, params: Params,
        state: TrainState, ctx: Any,
    ) -> tuple[jax.Array, dict]:
        """-> (backward_loss, additional dict) (compute_training_loss :1054)."""
        loss = self.criterion(preds["prediction"], batch.y, batch.example_mask)
        return loss, {}

    def eval_loss(
        self, preds: dict, features: dict, batch: Batch, params: Params,
        state: TrainState, ctx: Any,
    ) -> tuple[jax.Array, dict]:
        loss = self.criterion(preds["prediction"], batch.y, batch.example_mask)
        return loss, {}

    def transform_gradients(self, grads: Params, state: TrainState, ctx: Any) -> Params:
        """(basic_client.py:1294) — e.g. SCAFFOLD variate correction."""
        return grads

    def augment(self, batch: Batch, rng: PRNGKey, ctx: Any) -> Batch:
        """Per-step train-time data augmentation (the role of the reference's
        dataloader-side transform pipelines, e.g. nnunetv2's augmenters behind
        nnunet_utils.py:307). Runs inside the compiled scan, train only; the
        key is folded from the step key so the default identity leaves every
        existing RNG stream untouched."""
        del rng, ctx
        return batch

    def update_before_step(self, state: TrainState, ctx: Any, batch: Batch) -> TrainState:
        """(basic_client.py:1260 update_before_step) — runs before the
        gradient step; e.g. DeepMMD kernel training on the incoming batch.
        The engine masks this hook's state changes on padding steps
        (``batch.step_mask == 0``), but implementations should still gate
        expensive work on the mask to avoid wasted compute."""
        return state

    def _loss_fn(self, state: TrainState, ctx: Any, batch: Batch,
                 step_rng: PRNGKey):
        """The differentiated closure params -> (backward, (preds,
        additional, new_model_state)). ONE definition shared by the default
        ``value_and_grads`` below and the engine's fp16 loss-scaling path
        (which seeds its backward via ``jax.vjp``), so the scaled and
        unscaled gradient paths cannot silently drift apart."""

        def loss_fn(params):
            (preds, features), new_model_state = self.predict(
                params, state.model_state, batch, step_rng, train=True,
                extra=state.extra, ctx=ctx,
            )
            backward, additional = self.training_loss(
                preds, features, batch, params, state, ctx
            )
            return backward, (preds, additional, new_model_state)

        return loss_fn

    def value_and_grads(self, state: TrainState, ctx: Any, batch: Batch, step_rng: PRNGKey):
        """Compute ((backward, (preds, additional, new_model_state)), grads).

        Default: whole-batch ``value_and_grad``. DP logics override this with
        vmapped per-example gradients + clip + noise (the Opacus hook point,
        instance_level_dp_client.py:85-114 in the reference)."""
        return jax.value_and_grad(
            self._loss_fn(state, ctx, batch, step_rng), has_aux=True
        )(state.params)

    def update_after_step(self, state: TrainState, ctx: Any, batch: Batch,
                          preds: dict | None = None) -> TrainState:
        """(basic_client.py:1272) — e.g. APFL alpha update. ``preds`` is the
        step's prediction dict so hooks can reuse it without re-running the
        model."""
        return state

    # -- wire ---------------------------------------------------------------
    def pack(self, state: TrainState, pushed_params: Params, train_losses: dict) -> Any:
        """Build the packet sent to the server (get_parameters + packer,
        basic_client.py:153). Default: just the exchanged params."""
        return pushed_params


# ---------------------------------------------------------------------------
# Criteria
# ---------------------------------------------------------------------------

def masked_cross_entropy(logits: jax.Array, targets: jax.Array, mask: jax.Array) -> jax.Array:
    """Mean CE over valid examples; integer or one-hot targets."""
    if targets.ndim == logits.ndim:
        log_p = jax.nn.log_softmax(logits, axis=-1)
        per = -jnp.sum(targets * log_p, axis=-1)
    else:
        per = optax.softmax_cross_entropy_with_integer_labels(logits, targets)
    m = mask.astype(jnp.float32)
    return jnp.sum(per * m) / jnp.maximum(jnp.sum(m), 1.0)


def masked_mse(preds: jax.Array, targets: jax.Array, mask: jax.Array) -> jax.Array:
    per = jnp.mean(
        jnp.square(preds - targets).reshape(preds.shape[0], -1), axis=-1
    )
    m = mask.astype(jnp.float32)
    return jnp.sum(per * m) / jnp.maximum(jnp.sum(m), 1.0)


def masked_bce_with_logits(logits: jax.Array, targets: jax.Array, mask: jax.Array) -> jax.Array:
    logits = logits.reshape(logits.shape[0], -1)
    targets = targets.reshape(targets.shape[0], -1).astype(jnp.float32)
    per = jnp.mean(optax.sigmoid_binary_cross_entropy(logits, targets), axis=-1)
    m = mask.astype(jnp.float32)
    return jnp.sum(per * m) / jnp.maximum(jnp.sum(m), 1.0)


# ---------------------------------------------------------------------------
# Engine: compiled train / eval phases
# ---------------------------------------------------------------------------

def create_train_state(
    logic: ClientLogic, tx: optax.GradientTransformation, rng: PRNGKey,
    sample_x: jax.Array,
    precision: Any = None,
) -> TrainState:
    """``precision`` (a PrecisionConfig, optional): params/opt state are
    ALWAYS created f32 master (init runs in the model's native dtypes); a
    scaling policy additionally seeds the carried loss-scale state."""
    params, model_state = logic.model.init(rng, sample_x)
    return TrainState(
        params=params,
        opt_state=tx.init(params),
        model_state=model_state,
        rng=rng,
        step=jnp.zeros((), jnp.int32),
        extra=logic.init_extra(params),
        loss_scale=precision_policy.loss_scale_init(precision),
    )


def _mask_tree(new: PyTree, old: PyTree, keep_new: jax.Array) -> PyTree:
    """Select new where keep_new==1 (real step) else old (padding no-op)."""
    return jax.tree_util.tree_map(lambda n, o: jnp.where(keep_new > 0, n, o), new, old)


def _microbatched_value_and_grads(logic, tx, state, ctx, batch, step_rng):
    """ZeRO-2 gradient path: split the batch into ``tx.n_shards``
    microbatches, compute per-microbatch grads, and hand the UNREDUCED
    [n_shards]-leading stack to ``tx.update`` — its psum_scatter does the
    reduction without ever materializing the summed gradient
    (parallel/zero.py Zero2ShardedOptimizer; the DeepSpeed-zero2 role of the
    reference's fedllm example).

    Exactness contract: each microbatch grad is pre-scaled by
    ``n * M_k / M_total`` (M_k = valid examples in microbatch k) so the
    optimizer's uniform mean reproduces the full-batch masked-mean gradient
    bit-for-math. This is exact for losses that are masked example-means
    plus state-only penalty terms (CE/MSE, FedProx/Ditto/MR-MTL penalties —
    weights sum to 1) and for affine transform_gradients hooks (SCAFFOLD's
    variate correction). Batch-coupled losses (contrastive normalizers over
    the whole batch) and non-affine gradient transforms (DP clipping) change
    semantics under microbatching — same caveat as any grad-accumulation
    scheme — and mutable model_state (batch stats) takes the LAST
    microbatch's update.
    """
    n = tx.n_shards
    b = batch.example_mask.shape[0]
    if b % n != 0:
        raise ValueError(
            f"ZeRO-2 engine path needs batch size divisible by n_shards: "
            f"batch={b}, n_shards={n}"
        )
    m = b // n

    def split(leaf):
        return leaf.reshape((n, m) + leaf.shape[1:])

    micro = Batch(
        x=jax.tree_util.tree_map(split, batch.x),
        y=jax.tree_util.tree_map(split, batch.y),
        example_mask=split(batch.example_mask),
        step_mask=jnp.broadcast_to(batch.step_mask, (n,)),
    )

    def one(mb, rng_k):
        (bw, aux), g = logic.value_and_grads(state, ctx, mb, rng_k)
        g = logic.transform_gradients(g, state, ctx)
        return (bw, aux), g

    # independent rng per microbatch: a shared key would draw IDENTICAL
    # dropout masks in every microbatch (correlated noise); per-fold keys
    # match grad-accumulation convention (still a different stream than the
    # full-batch draw — stochastic layers are approximate here, like the
    # other microbatching caveats above)
    rngs = jax.vmap(lambda i: jax.random.fold_in(step_rng, i))(jnp.arange(n))
    (bw_k, (preds_k, add_k, mstate_k)), grads_k = jax.vmap(one)(micro, rngs)

    m_k = jnp.sum(micro.example_mask.astype(jnp.float32), axis=1)  # [n]
    m_tot = jnp.maximum(jnp.sum(m_k), 1.0)
    w = n * m_k / m_tot  # uniform mean of w_k·g_k == masked-mean grad
    grads_scaled = jax.tree_util.tree_map(
        lambda g: g * w.reshape((n,) + (1,) * (g.ndim - 1)), grads_k
    )
    recombine = lambda v: jnp.sum((w / n) * v)  # noqa: E731 — Σ (M_k/M_tot)·v_k
    backward = recombine(bw_k)
    additional = {k: recombine(v) for k, v in add_k.items()}
    preds = jax.tree_util.tree_map(
        lambda p: p.reshape((b,) + p.shape[2:]), preds_k
    )
    new_model_state = jax.tree_util.tree_map(lambda s: s[-1], mstate_k)
    return backward, preds, additional, new_model_state, grads_scaled


def make_train_step(logic: ClientLogic, tx: optax.GradientTransformation,
                    collect_telemetry: bool = False, precision: Any = None):
    """Returns step(state, ctx, batch) -> (state, StepOutput) — jit/scan-safe.

    ``collect_telemetry`` additionally populates ``StepOutput.grad_norm``
    with the global norm of the gradient AFTER ``transform_gradients`` (what
    the optimizer actually consumes — SCAFFOLD correction, DP noise etc.
    included). A pure extra output: the parameter update math is untouched,
    so telemetry-on trajectories stay bit-identical to telemetry-off
    (tests/observability/test_telemetry.py).

    ``precision`` (a :class:`~fl4health_tpu.precision.PrecisionConfig`, or
    None): the engine-level mixed-precision policy. With a low-precision
    compute dtype the logic's model apply is wrapped so float params AND
    float inputs are cast at apply time — the forward/backward runs in
    bf16/fp16 for EVERY logic routing through ``logic.model`` (the default
    path, DP per-example gradients, dual forwards) while gradients come
    back f32 at the parameter boundary (the cast's VJP) and optax applies
    them to the f32 master weights. fp16 adds in-graph loss scaling: the
    backward is seeded with the scale as the loss cotangent, gradients are
    unscaled in f32, a non-finite gradient skips the step (params,
    optimizer and model_state untouched) and the scale/growth/skip state
    evolves in ``TrainState.loss_scale``. ``None`` (or an inactive config)
    builds the exact legacy step — bit-identical, pinned by
    tests/precision/."""
    precision = precision_policy.resolve(precision)
    if precision is not None and precision.casts_compute:
        logic = precision_policy.wrap_logic_compute(
            logic, precision.compute_jnp_dtype
        )
    scaling = precision is not None and precision.scaling_active
    unreduced = getattr(tx, "expects_unreduced_grads", False)
    if scaling:
        if unreduced:
            raise ValueError(
                "loss scaling cannot compose with the ZeRO-2 microbatched "
                "gradient path (expects_unreduced_grads): the per-microbatch "
                "finite screen would skip shards independently and the "
                "pre-scaled recombination no longer holds — use bf16 (no "
                "scaling) with ZeRO-2"
            )
        if type(logic).value_and_grads is not ClientLogic.value_and_grads:
            # A logic that owns its gradient computation (DP per-example
            # clip+noise) would see SCALED gradients inside its mechanism —
            # the clip bound and noise sigma would silently mis-calibrate.
            # bf16 (range of f32, no scaling needed) composes fine.
            raise TypeError(
                f"in-graph loss scaling wraps the engine's default gradient "
                f"path only: {type(logic).__name__} overrides "
                "value_and_grads (e.g. DP per-example gradients), whose "
                "clip/noise calibration breaks under a scaled backward — "
                "use compute_dtype='bfloat16' with loss_scale='none'"
            )
    if unreduced:
        # The microbatch pre-scaling assumes the optimizer's uniform MEAN
        # reduction; a reduce="sum" ZeRO-2 would silently apply n_shards x
        # the true gradient (an effective-LR inflation).
        if getattr(tx, "reduce", "mean") != "mean":
            raise ValueError(
                "expects_unreduced_grads optimizers must use reduce='mean' "
                f"through the engine (got {tx.reduce!r}) — the microbatch "
                "weighting is calibrated for a uniform mean"
            )
        # A logic that overrides the gradient computation itself (DP
        # per-example clip+noise) would run it once PER MICROBATCH — noise
        # drawn n times and recombined no longer matches the (eps, delta)
        # accounting. Same loud-error policy as personalized.py.
        if type(logic).value_and_grads is not ClientLogic.value_and_grads:
            raise TypeError(
                f"ZeRO-2 microbatching cannot wrap {type(logic).__name__}: "
                "it overrides value_and_grads (e.g. DP per-example "
                "gradients), whose semantics change under microbatching"
            )

    def step(state: TrainState, ctx: Any, batch: Batch):
        state = _mask_tree(
            logic.update_before_step(state, ctx, batch), state, batch.step_mask
        )
        rng, step_rng = jax.random.split(state.rng)
        batch = logic.augment(batch, jax.random.fold_in(step_rng, 0xA6), ctx)
        finite = None
        if unreduced:
            backward, preds, additional, new_model_state, grads = (
                _microbatched_value_and_grads(
                    logic, tx, state, ctx, batch, step_rng
                )
            )
        elif scaling:
            ls = state.loss_scale
            if ls is None:
                raise ValueError(
                    "loss scaling needs the carried scaler state: build the "
                    "TrainState with create_train_state(..., precision=...) "
                    "(FederatedSimulation(precision=...) does this)"
                )

            # THE default-path loss closure (logic._loss_fn — one shared
            # definition), driven through jax.vjp so the backward can be
            # SEEDED with the scale as the loss cotangent — mathematically
            # identical to scaling the loss (gradients are linear in the
            # cotangent) but it reaches every intermediate fp16 cotangent,
            # which is where the underflow lives. The primal loss stays
            # unscaled, so meters/telemetry report true values.
            backward, vjp_fn, (preds, additional, new_model_state) = jax.vjp(
                logic._loss_fn(state, ctx, batch, step_rng),
                state.params, has_aux=True,
            )
            grads = vjp_fn(ls["scale"].astype(backward.dtype))[0]
            # unscale in f32 (grads are f32 at the master-param boundary);
            # the finite screen runs on the UNSCALED gradient so a huge
            # scale can't masquerade as overflow
            inv = 1.0 / ls["scale"]
            grads = jax.tree_util.tree_map(lambda g: g * inv, grads)
            finite = precision_policy.tree_all_finite(grads)
            grads = logic.transform_gradients(grads, state, ctx)
        else:
            (backward, (preds, additional, new_model_state)), grads = (
                logic.value_and_grads(state, ctx, batch, step_rng)
            )
            grads = logic.transform_gradients(grads, state, ctx)
        updates, new_opt_state = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)

        keep = batch.step_mask  # padding steps must not move anything
        # a non-finite scaled gradient additionally skips the optimizer
        # step (master weights, optimizer state and batch stats untouched)
        keep_update = keep if finite is None else keep * finite
        new_state = state.replace(
            params=_mask_tree(new_params, state.params, keep_update),
            opt_state=_mask_tree(new_opt_state, state.opt_state, keep_update),
            model_state=_mask_tree(
                new_model_state, state.model_state, keep_update
            ),
            rng=rng,
            step=state.step + keep_update.astype(jnp.int32),
        )
        if scaling:
            # scaler state advances on REAL steps only (padding steps are
            # full no-ops); it advances on skipped steps too — that is how
            # the scale backs off and recovers
            new_ls = precision_policy.loss_scale_step(
                state.loss_scale, finite, precision
            )
            new_state = new_state.replace(
                loss_scale=_mask_tree(new_ls, state.loss_scale, keep)
            )
        new_state = logic.update_after_step(new_state, ctx, batch, preds=preds)
        grad_norm = None
        if collect_telemetry:
            if unreduced:
                # ZeRO-2 hands the optimizer an UNREDUCED [n_shards] stack;
                # the true gradient is its uniform mean (the pre-scaling is
                # calibrated for exactly that reduction)
                grad_norm = optax.global_norm(
                    jax.tree_util.tree_map(lambda g: jnp.mean(g, axis=0), grads)
                )
            else:
                grad_norm = optax.global_norm(grads)
        out = StepOutput(
            losses={"backward": backward, **additional},
            preds=preds["prediction"],
            targets=batch.y,
            example_mask=batch.example_mask * keep,
            step_mask=keep,
            grad_norm=grad_norm,
        )
        return new_state, out

    return step


# -- in-scan telemetry accumulation (observability/telemetry.py consumers) --

def telemetry_acc_init() -> dict:
    """Scan-carry accumulator for per-client loss min/max + grad-norm
    statistics. NaN losses propagate through min/max by design — a poisoned
    step must surface in the telemetry, not be filtered out of it."""
    inf = jnp.asarray(jnp.inf, jnp.float32)
    zero = jnp.zeros((), jnp.float32)
    return {"loss_min": inf, "loss_max": -inf, "gn_sum": zero, "gn_max": zero}


def telemetry_acc_update(acc: dict, out: StepOutput) -> dict:
    loss = jnp.asarray(out.losses["backward"], jnp.float32)
    gn = jnp.asarray(out.grad_norm, jnp.float32)
    live = out.step_mask > 0  # padding steps must not move the stats
    return {
        "loss_min": jnp.minimum(acc["loss_min"], jnp.where(live, loss, jnp.inf)),
        "loss_max": jnp.maximum(acc["loss_max"], jnp.where(live, loss, -jnp.inf)),
        "gn_sum": acc["gn_sum"] + jnp.where(live, gn, 0.0),
        "gn_max": jnp.maximum(acc["gn_max"], jnp.where(live, gn, 0.0)),
    }


def telemetry_acc_finalize(acc: dict, n_steps: jax.Array) -> dict:
    """-> the engine's share of a RoundTelemetry row. A client that executed
    zero steps reports NaN (not the init sentinels)."""
    ran = n_steps > 0
    nan = jnp.asarray(jnp.nan, jnp.float32)
    return {
        "train_loss_min": jnp.where(ran, acc["loss_min"], nan),
        "train_loss_max": jnp.where(ran, acc["loss_max"], nan),
        "grad_norm_mean": jnp.where(
            ran, acc["gn_sum"] / jnp.maximum(n_steps, 1.0), nan
        ),
        "grad_norm_max": jnp.where(ran, acc["gn_max"], nan),
    }


def make_local_train(
    logic: ClientLogic,
    tx: optax.GradientTransformation,
    metric_manager: MetricManager,
    loss_keys: tuple[str, ...] = ("backward",),
    collect_telemetry: bool = False,
    precision: Any = None,
):
    """Compiled local-training phase: scan the train step over stacked batches.

    Returns train(state, ctx, batches) -> (state, loss_dict, metric_dict,
    n_steps). ``batches`` is a Batch pytree with a leading [steps] axis.
    With ``collect_telemetry`` a fifth output is appended: the engine's
    telemetry dict (loss min/max, grad-norm mean/max over executed steps) —
    extra scan outputs only; the training math is byte-for-byte the same.
    ``precision`` threads the mixed-precision policy into every step (see
    :func:`make_train_step`); telemetry stats are computed from the f32
    boundary values (unscaled grads, f32 losses) either way.
    """
    step_fn = make_train_step(logic, tx, collect_telemetry=collect_telemetry,
                              precision=precision)
    meter_proto = LossMeter.create(loss_keys)

    def _train(state: TrainState, ctx: Any, batches: Batch):
        def body(carry, batch):
            st, meter, mstate, acc = carry
            st, out = step_fn(st, ctx, batch)
            meter = meter.update(out.losses, weight=out.step_mask)
            mstate = metric_manager.update(
                mstate, out.preds, out.targets, out.example_mask
            )
            if collect_telemetry:
                acc = telemetry_acc_update(acc, out)
            return (st, meter, mstate, acc), out.losses

        acc0 = telemetry_acc_init() if collect_telemetry else None
        (state, meter, mstate, acc), _ = jax.lax.scan(
            body, (state, meter_proto, metric_manager.init(), acc0), batches
        )
        n_steps = jnp.sum(batches.step_mask)
        state = logic.finalize_round(state, ctx, n_steps)
        outs = (state, meter.compute(), metric_manager.compute(mstate), n_steps)
        if collect_telemetry:
            return (*outs, telemetry_acc_finalize(acc, n_steps))
        return outs

    def train(state: TrainState, ctx: Any, batches: Batch):
        with stage_attr.stage("local_train"):
            return _train(state, ctx, batches)

    return train


def make_local_eval(
    logic: ClientLogic,
    metric_manager: MetricManager,
    loss_keys: tuple[str, ...] = ("checkpoint",),
):
    """Compiled evaluation phase (validate, basic_client.py:867)."""
    meter_proto = LossMeter.create(loss_keys)

    def evaluate(state: TrainState, ctx: Any, batches: Batch):
        def body(carry, batch):
            meter, mstate, rng = carry
            rng, step_rng = jax.random.split(rng)
            (preds, features), _ = logic.predict(
                state.params, state.model_state, batch, step_rng, train=False,
                extra=state.extra, ctx=ctx,
            )
            loss, additional = logic.eval_loss(
                preds, features, batch, state.params, state, ctx
            )
            meter = meter.update(
                {"checkpoint": loss, **{k: additional[k] for k in meter.sums if k != "checkpoint"}},
                weight=batch.step_mask,
            )
            mstate = metric_manager.update(
                mstate, preds["prediction"], batch.y, batch.example_mask * batch.step_mask
            )
            return (meter, mstate, rng), loss

        (meter, mstate, _), _ = jax.lax.scan(
            body, (meter_proto, metric_manager.init(), state.rng), batches
        )
        return meter.compute(), metric_manager.compute(mstate)

    return evaluate


@dataclasses.dataclass(frozen=True)
class EarlyStoppingConfig:
    """Reference EarlyStopper (utils/early_stopper.py:14): snapshot the best
    state every ``interval_steps`` local steps; stop when validation hasn't
    improved for ``patience`` consecutive checks; restore the best snapshot."""

    interval_steps: int
    patience: int


def make_local_train_with_early_stopping(
    logic: ClientLogic,
    tx: optax.GradientTransformation,
    metric_manager: MetricManager,
    config: EarlyStoppingConfig,
    loss_keys: tuple[str, ...] = ("backward",),
    collect_telemetry: bool = False,
    precision: Any = None,
):
    """Early-stopped local training as ONE compiled program.

    The step stream is chunked into [n_chunks, interval_steps]; after each
    chunk the client validates, tracks the best params snapshot in the scan
    carry, and raises a ``stopped`` flag once patience runs out — subsequent
    chunks have their step_mask zeroed, making them no-ops (the TPU-native
    replacement for breaking out of the reference's Python batch loop,
    basic_client.py:676,755).

    Returns train(state, ctx, batches, val_batches) with the same outputs as
    ``make_local_train`` (including the telemetry dict when
    ``collect_telemetry``; stats cover executed steps only — batches after
    the stop flag have their step_mask zeroed and never touch the
    accumulator). ``precision`` applies to the TRAIN steps only: the
    in-scan validation (and the best-snapshot selection it drives) scores
    the f32 master weights, matching ``fit()``'s eval rounds.
    """
    step_fn = make_train_step(logic, tx, collect_telemetry=collect_telemetry,
                              precision=precision)
    evaluate = make_local_eval(logic, metric_manager)
    meter_proto = LossMeter.create(loss_keys)
    interval = config.interval_steps

    def train(state: TrainState, ctx: Any, batches: Batch, val_batches: Batch):
        total = batches.step_mask.shape[0]
        n_chunks = -(-total // interval)
        pad = n_chunks * interval - total
        if pad:
            batches = jax.tree_util.tree_map(
                lambda x: jnp.concatenate(
                    [x, jnp.zeros((pad, *x.shape[1:]), x.dtype)]
                ),
                batches,
            )
        chunked = jax.tree_util.tree_map(
            lambda x: x.reshape((n_chunks, interval) + x.shape[1:]), batches
        )

        def chunk_body(carry, chunk: Batch):
            (st, meter, mstate, acc, best_state, best_score, bad, stopped,
             executed) = carry
            chunk = chunk.replace(step_mask=chunk.step_mask * (1.0 - stopped))

            def body(c, b):
                st2, meter2, ms2, acc2 = c
                st2, out = step_fn(st2, ctx, b)
                meter2 = meter2.update(out.losses, weight=out.step_mask)
                ms2 = metric_manager.update(
                    ms2, out.preds, out.targets, out.example_mask
                )
                if collect_telemetry:
                    acc2 = telemetry_acc_update(acc2, out)
                return (st2, meter2, ms2, acc2), None

            (st, meter, mstate, acc), _ = jax.lax.scan(
                body, (st, meter, mstate, acc), chunk
            )
            executed = executed + jnp.sum(chunk.step_mask)

            val_losses, _ = evaluate(st, ctx, val_batches)
            score = val_losses["checkpoint"]
            live = stopped < 0.5
            improved = (score < best_score) & live
            best_state = _mask_tree(st, best_state, improved)
            best_score = jnp.where(improved, score, best_score)
            bad = jnp.where(live, jnp.where(improved, 0, bad + 1), bad)
            stopped = jnp.maximum(
                stopped, (bad >= config.patience).astype(jnp.float32)
            )
            return (st, meter, mstate, acc, best_state, best_score, bad,
                    stopped, executed), score

        init = (
            state,
            meter_proto,
            metric_manager.init(),
            telemetry_acc_init() if collect_telemetry else None,
            state,
            jnp.asarray(jnp.inf, jnp.float32),
            jnp.zeros((), jnp.int32),
            jnp.zeros((), jnp.float32),
            jnp.zeros((), jnp.float32),
        )
        (final, meter, mstate, acc, best_state, _, _, _, executed), _ = (
            jax.lax.scan(chunk_body, init, chunked)
        )
        # restore the FULL best snapshot — params, optimizer, model_state and
        # algorithm extra move together (the reference snapshots model AND
        # optimizer state, early_stopper.py:46,90); keep the advanced RNG so
        # randomness is never replayed. finalize_round then runs on the
        # restored state, matching update_after_train-after-restore ordering.
        state = best_state.replace(rng=final.rng)
        state = logic.finalize_round(state, ctx, executed)
        outs = (state, meter.compute(), metric_manager.compute(mstate), executed)
        if collect_telemetry:
            return (*outs, telemetry_acc_finalize(acc, executed))
        return outs

    return train


# ---------------------------------------------------------------------------
# Host-side batching: DataLoader equivalent producing static-shaped stacks
# ---------------------------------------------------------------------------
#
# Index construction is pure numpy (zero device dispatches); the only device
# work per round is ONE gather per array. At 64 clients the previous per-step
# jnp indexing was thousands of tiny dispatches per round — the reference's
# eager-DataLoader dispatch pattern this build exists to eliminate.


def _entropy_from_key(rng: PRNGKey) -> list[int]:
    """Stable integer entropy from a JAX PRNG key (legacy uint32 or typed)."""
    try:
        data = np.asarray(jax.random.key_data(rng))
    except (TypeError, ValueError):
        data = np.asarray(rng)
    return [int(v) for v in data.ravel()]


def epoch_index_plan(
    entropy: list[int],
    n: int,
    batch_size: int,
    n_steps: int | None = None,
    shuffle: bool = True,
    drop_last: bool = False,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Vectorized batch-index plan: (idx [S,B] i32, example_mask [S,B] f32,
    step_mask [S] f32), all numpy.

    Semantics match the reference loader: one epoch (or exactly n_steps,
    wrapping with a fresh shuffle at each epoch boundary — train_by_steps
    cycles its loader, basic_client.py:699); ragged final batch rows get
    example_mask 0.
    """
    steps_per_epoch = max(1, n // batch_size if drop_last else -(-n // batch_size))
    total = n_steps if n_steps is not None else steps_per_epoch
    n_epochs = -(-total // steps_per_epoch)

    rng = np.random.default_rng(np.random.SeedSequence(entropy))
    if shuffle:
        orders = rng.permuted(
            np.tile(np.arange(n, dtype=np.int32), (n_epochs, 1)), axis=1
        )
    else:
        orders = np.tile(np.arange(n, dtype=np.int32), (n_epochs, 1))

    padded_len = steps_per_epoch * batch_size
    if padded_len <= n:
        epoch_idx = orders[:, :padded_len]
        epoch_mask = np.ones((padded_len,), np.float32)
    else:
        pad = padded_len - n
        epoch_idx = np.concatenate(
            [orders, np.zeros((n_epochs, pad), np.int32)], axis=1
        )
        epoch_mask = np.concatenate(
            [np.ones((n,), np.float32), np.zeros((pad,), np.float32)]
        )

    idx = epoch_idx.reshape(n_epochs * steps_per_epoch, batch_size)[:total]
    example_mask = np.tile(
        epoch_mask.reshape(steps_per_epoch, batch_size), (n_epochs, 1)
    )[:total]
    # A step with zero valid examples (e.g. an empty client dataset) is a full
    # no-op: the engine gates optimizer/meter updates on step_mask.
    step_mask = (example_mask.sum(axis=1) > 0).astype(np.float32)
    return idx, example_mask, step_mask


def epoch_batches(
    rng: PRNGKey,
    x: jax.Array,
    y: jax.Array,
    batch_size: int,
    n_steps: int | None = None,
    shuffle: bool = True,
    drop_last: bool = False,
) -> Batch:
    """Build a [steps, B, ...] Batch stack for one epoch (or exactly n_steps).

    If n_steps exceeds one epoch, batches wrap around (reference
    train_by_steps cycles its loader); if it's shorter, the epoch is truncated.
    Padding rows get example_mask 0; padding steps get step_mask 0.
    ``x``/``y`` may be pytrees of arrays sharing axis 0 (dict inputs).
    """
    # x AND y leaves must agree on axis-0 size: without this, the gather
    # below would CLAMP out-of-range indices on short leaves — silently
    # repeating rows instead of erroring (direct callers like the
    # fedprox_cluster silo handler bypass FederatedSimulation's nx==ny check)
    ns = {
        leaf.shape[0]
        for tree in (x, y)
        for leaf in jax.tree_util.tree_leaves(tree)
    }
    if len(ns) > 1:
        raise ValueError(
            f"epoch_batches: x/y leaves disagree on example count: {sorted(ns)}"
        )
    idx, example_mask, step_mask = epoch_index_plan(
        _entropy_from_key(rng), data_rows(x), batch_size, n_steps, shuffle,
        drop_last,
    )
    idx_arr = jnp.asarray(idx)
    take = lambda a: a[idx_arr]  # noqa: E731
    return Batch(
        x=jax.tree_util.tree_map(take, x),
        y=jax.tree_util.tree_map(take, y),
        example_mask=jnp.asarray(example_mask),
        step_mask=jnp.asarray(step_mask),
    )


def multi_client_index_plans(
    entropies: list[list[int]],
    ns: list[int],
    batch_size: int,
    n_steps: int | None = None,
    local_epochs: int | None = None,
    shuffle: bool = True,
    pad_steps: int | None = None,
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Cohort-wide batch plan: (idx [C,S,B], example_mask [C,S,B],
    step_mask [C,S]) numpy arrays, padded to the cohort's max step count.

    Pure host-side index math — the per-client DataLoader loop collapsed into
    one plan that feeds a single device gather (``gather_batches``).

    ``pad_steps`` pins the step axis to a FIXED length instead of the
    cohort's max (extra steps carry step_mask 0, full no-ops). Cohort-slot
    rounds (``server/registry.py``) pad every round's plan to the
    REGISTRY-wide step budget so the compiled slot program's shape never
    depends on which clients were sampled. Raises if any client's plan
    exceeds it.
    """
    plans = []
    for ent, n in zip(entropies, ns):
        if local_epochs is not None:
            parts = [
                epoch_index_plan([*ent, e], n, batch_size, None, shuffle)
                for e in range(local_epochs)
            ]
            idx = np.concatenate([p[0] for p in parts], axis=0)
            em = np.concatenate([p[1] for p in parts], axis=0)
            sm = np.concatenate([p[2] for p in parts], axis=0)
        else:
            idx, em, sm = epoch_index_plan(ent, n, batch_size, n_steps, shuffle)
        plans.append((idx, em, sm))
    n_clients = len(plans)
    max_steps = max(p[0].shape[0] for p in plans)
    if pad_steps is not None:
        if max_steps > pad_steps:
            raise ValueError(
                f"pad_steps={pad_steps} is smaller than the largest "
                f"client plan ({max_steps} steps); the fixed step budget "
                "must cover every client in the registry"
            )
        max_steps = pad_steps
    idx_all = np.zeros((n_clients, max_steps, batch_size), np.int32)
    em_all = np.zeros((n_clients, max_steps, batch_size), np.float32)
    sm_all = np.zeros((n_clients, max_steps), np.float32)
    for c, (idx, em, sm) in enumerate(plans):
        s = idx.shape[0]
        idx_all[c, :s] = idx
        em_all[c, :s] = em
        sm_all[c, :s] = sm
    return idx_all, em_all, sm_all


def data_rows(tree) -> int:
    """Example count of a data pytree (axis-0 length of its first leaf) —
    the one place "how many rows" is defined for array and dict data alike."""
    return int(jax.tree_util.tree_leaves(tree)[0].shape[0])


def pad_and_stack_data(arrays: list, name: str = "data"):
    """Zero-pad along axis 0 to the max length and stack -> [C, max_n, ...],
    leafwise over a data PYTREE (a plain array, or a dict of arrays — the
    reference's DictionaryDataset role, utils/dataset.py:DictionaryDataset:
    multi-input models take {"input_ids": ..., "attention_mask": ...}-style
    batches; here any pytree x flows through the same stacked-gather path).

    Setup-time only; padding rows are never selected by a valid index plan.
    Assembly happens on HOST (numpy) with a single device transfer at the
    end. Pass numpy arrays in ClientDataset to avoid any device round-trip.
    """
    treedef = jax.tree_util.tree_structure(arrays[0])
    for i, a in enumerate(arrays):
        if jax.tree_util.tree_structure(a) != treedef:
            raise ValueError(
                f"client {i}'s {name} pytree structure "
                f"{jax.tree_util.tree_structure(a)} differs from client 0's "
                f"{treedef}; every client must provide the same input keys."
            )
    flat = [jax.tree_util.tree_flatten_with_path(a)[0] for a in arrays]
    # within each client, every leaf must carry the same number of examples
    for i, leaves in enumerate(flat):
        ns = {path_str(path): leaf.shape[0] for path, leaf in leaves}
        if len(set(ns.values())) > 1:
            raise ValueError(
                f"client {i}'s {name} leaves disagree on example count: {ns}"
            )
    # data-staging observability: this is the DataLoader-boundary cost (host
    # assembly + one device transfer), paid at setup / per-round refresh —
    # the span is a shared no-op while the process tracer is disabled
    with get_tracer().span(
        "pad_and_stack", cat="data", dataset=name, clients=len(arrays)
    ) as sp:
        out_leaves = [
            _pad_and_stack_leaf(
                [leaves[j][1] for leaves in flat],
                name + path_str(flat[0][j][0]),
            )
            for j in range(len(flat[0]))
        ]
        staged = tree_nbytes(out_leaves)
        sp.set(staged_bytes=staged)
    get_registry().counter(
        "engine_staged_bytes_total",
        help="bytes staged into client-stacked device arrays "
             "(setup + per-round data refresh)",
    ).inc(staged)
    return jax.tree_util.tree_unflatten(treedef, out_leaves)


def path_str(path) -> str:
    """Readable suffix for a tree path in error messages ("" for the root,
    i.e. plain-array data). Delegates to jax's canonical renderer."""
    return jax.tree_util.keystr(path) if path else ""


def _pad_and_stack_leaf(arrays: list[jax.Array], name: str) -> jax.Array:
    host = [np.asarray(a) for a in arrays]
    # The cohort shares one compiled program: every client's example shape
    # and dtype must agree. Name the offending client and array instead of
    # letting numpy's broadcast error (or a silent cast — float labels
    # truncated into an int slot) surface from deep inside setup.
    base = host[0].shape[1:]
    for i, a in enumerate(host):
        if a.shape[1:] != base:
            raise ValueError(
                f"client {i}'s {name} has per-example shape {a.shape[1:]} "
                f"but client 0 has {base}; all clients in a cohort must "
                "share one example shape (align features before building "
                "the simulation — e.g. the tabular feature-alignment "
                "protocol)."
            )
        if a.dtype != host[0].dtype:
            raise ValueError(
                f"client {i}'s {name} has dtype {a.dtype} but client 0 has "
                f"{host[0].dtype}; stacking would silently cast — convert "
                "the clients' data to one dtype first."
            )
    max_n = max(a.shape[0] for a in host)
    stack = np.zeros((len(host), max_n, *base), host[0].dtype)
    for i, a in enumerate(host):
        stack[i, : a.shape[0]] = a
    return jnp.asarray(stack)


def gather_batches(
    x_stack,
    y_stack,
    idx: np.ndarray,
    example_mask: np.ndarray,
    step_mask: np.ndarray,
) -> Batch:
    """One device-side gather from pre-stacked data -> [C,S,B,...] Batch.
    ``x_stack``/``y_stack`` may be pytrees (dict inputs); the same index
    plan gathers every leaf."""
    idx_arr = jnp.asarray(idx)
    c = jnp.arange(idx_arr.shape[0])[:, None, None]
    gather = lambda s: s[c, idx_arr]  # noqa: E731
    return Batch(
        x=jax.tree_util.tree_map(gather, x_stack),
        y=jax.tree_util.tree_map(gather, y_stack),
        example_mask=jnp.asarray(example_mask),
        step_mask=jnp.asarray(step_mask),
    )


def pad_batch_stacks(stacks: list[Batch]) -> Batch:
    """Pad per-client Batch stacks to a common [steps] length and stack along a
    new leading clients axis -> [clients, steps, B, ...]."""
    max_steps = max(b.step_mask.shape[0] for b in stacks)

    def pad_leaf(a, pad):
        return jnp.concatenate([a, jnp.zeros((pad, *a.shape[1:]), a.dtype)])

    def pad_one(b: Batch) -> Batch:
        pad = max_steps - b.step_mask.shape[0]
        if pad == 0:
            return b
        # x/y may be pytrees (dict inputs) — pad every leaf
        return Batch(
            x=jax.tree_util.tree_map(lambda a: pad_leaf(a, pad), b.x),
            y=jax.tree_util.tree_map(lambda a: pad_leaf(a, pad), b.y),
            example_mask=jnp.concatenate(
                [b.example_mask, jnp.zeros((pad, *b.example_mask.shape[1:]), jnp.float32)]
            ),
            step_mask=jnp.concatenate([b.step_mask, jnp.zeros((pad,), jnp.float32)]),
        )

    padded = [pad_one(b) for b in stacks]
    return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, axis=0), *padded)

"""GPFL client logic.

Parity: /root/reference/fl4health/clients/gpfl_client.py:23. Per round the
client freezes the received GCE embedding table and computes two conditional
inputs from it and the client's class-sample proportions
(``compute_conditional_inputs`` :213-233):
    g = E_frozen^T @ uniform / C        (global conditional)
    p = E_frozen^T @ class_props / C    (personalized conditional)
The combined training loss (:334+) is
    CE(head(personal_features), y)
  + GCE softmax loss (CE over cosine logits of the general features)
  + lam * magnitude-level loss ||general_features - E_frozen[y]||_2
with mu realized as L2 weight decay on the GCE and CoV parameters (the
reference sets optimizer weight_decay=mu for those groups :144-152; here it
is an explicit loss term over the same subtrees — identical gradients).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import optax
from flax import struct

from fl4health_tpu.clients.engine import Batch, ClientLogic, TrainState
from fl4health_tpu.core import pytree as ptu
from fl4health_tpu.core.types import Params


@struct.dataclass
class GpflContext:
    frozen_embeddings: jax.Array  # [C, D] received GCE table
    p_cond: jax.Array  # [D]
    g_cond: jax.Array  # [D]


class GpflClientLogic(ClientLogic):
    """Pair with ``models.bases.GpflModel`` via ``gpfl_model_def`` and
    FixedLayerExchanger(GpflModel.exchange_shared)."""

    extra_loss_keys = ("prediction_ce", "gce_softmax", "magnitude")

    def __init__(self, model, criterion, n_classes: int,
                 class_proportions: jnp.ndarray | None = None,
                 lam: float = 0.01, mu: float = 0.01):
        super().__init__(model, criterion)
        self.n_classes = n_classes
        # Per-client label marginal (calculate_class_sample_proportions,
        # gpfl_client.py:169). Uniform if unknown.
        self.class_proportions = (
            jnp.asarray(class_proportions, jnp.float32)
            if class_proportions is not None
            else jnp.full((n_classes,), 1.0 / n_classes)
        )
        self.lam = lam
        self.mu = mu

    def init_round_context(self, state: TrainState, payload) -> GpflContext:
        payload_params = payload.params if hasattr(payload, "params") else payload
        # After pull, state.params holds the merged model; the frozen table is
        # the received one — identical to state at round start.
        emb = state.params["gce"]["embedding"]
        del payload_params
        # g = sum_c E_c / C ; p = E^T @ class_props / C
        # (gpfl_client.py:213-233 compute_conditional_inputs).
        g_cond = jnp.sum(emb, axis=0) / self.n_classes
        p_cond = emb.T @ self.class_proportions / self.n_classes
        return GpflContext(
            frozen_embeddings=jax.lax.stop_gradient(emb),
            p_cond=jax.lax.stop_gradient(p_cond),
            g_cond=jax.lax.stop_gradient(g_cond),
        )

    def predict(self, params, model_state, batch: Batch, rng, train: bool,
                extra=None, ctx=None):
        p_cond = ctx.p_cond if ctx is not None else None
        g_cond = ctx.g_cond if ctx is not None else None
        return self.model.apply(
            params, model_state, batch.x, train=train, rng=rng,
            p_cond=p_cond, g_cond=g_cond,
        )

    def training_loss(self, preds, features, batch: Batch, params, state,
                      ctx: GpflContext):
        m = batch.example_mask.astype(jnp.float32)
        denom = jnp.maximum(jnp.sum(m), 1.0)
        ce = self.criterion(preds["prediction"], batch.y, batch.example_mask)
        # GCE softmax loss over the cosine logits (gpfl_base.py:29-58).
        per = optax.softmax_cross_entropy_with_integer_labels(
            preds["gce_logits"], batch.y
        )
        gce_loss = jnp.sum(per * m) / denom
        # Magnitude-level loss vs frozen embedding lookup (gpfl_client.py:311).
        target_emb = ctx.frozen_embeddings[batch.y]  # [B, D]
        diff = (features["general_features"] - target_emb) * m[:, None]
        magnitude = jnp.linalg.norm(diff)
        # mu-weight-decay on GCE + CoV subtrees (gpfl_client.py:144-152).
        l2 = 0.0
        if self.mu > 0.0:
            gce_cov = [params["gce"], params["cov"]]
            l2 = 0.5 * sum(
                jnp.sum(jnp.square(leaf))
                for t in gce_cov
                for leaf in jax.tree_util.tree_leaves(t)
            )
        total = ce + gce_loss + self.lam * magnitude + self.mu * l2
        return total, {"prediction_ce": ce, "gce_softmax": gce_loss,
                       "magnitude": magnitude}


def gpfl_model_def(module):
    """ModelDef adapter for GpflModel — ``engine.from_flax`` forwards the
    conditional-input kwargs (and handles mutable collections) already."""
    from fl4health_tpu.clients.engine import from_flax

    return from_flax(module)

"""Flash client — gamma-thresholded per-epoch early stopping, compiled.

Parity surface (/root/reference/fl4health/clients/flash_client.py:18
``FlashClient``): epoch-wise training only (step-wise raises, :71-95); after
every local epoch the client validates and STOPS when the validation-loss
improvement falls below ``gamma / (epoch + 1)`` (:152-160). Unlike the
generic EarlyStopper there is no best-state restore — Flash simply breaks
out of the epoch loop and returns the current state.

TPU-native design: the epoch loop is a ``lax.scan`` over [n_epochs,
steps_per_epoch] chunks; the stop decision is a carried flag that zeroes the
step_mask of later epochs (full no-ops), replacing the Python ``break`` with
mask arithmetic — the same compilation pattern as
engine.make_local_train_with_early_stopping.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp

from fl4health_tpu.clients import engine
from fl4health_tpu.clients.engine import Batch, ClientLogic, TrainState
from fl4health_tpu.losses.containers import LossMeter
from fl4health_tpu.metrics.base import MetricManager


@dataclasses.dataclass(frozen=True)
class FlashEarlyStopConfig:
    """gamma: the improvement threshold (flash_client.py:66); None disables
    the stop rule entirely (the client then behaves exactly like
    BasicClient, :117-118 — all epochs run).
    n_epochs must match the simulation's local_epochs — the stop rule is
    defined per epoch. With heterogeneous client data sizes the chunk
    boundaries follow the cohort-padded max length."""

    gamma: float | None
    n_epochs: int


def make_flash_local_train(
    logic: ClientLogic,
    tx,
    metric_manager: MetricManager,
    config: FlashEarlyStopConfig,
    loss_keys: tuple[str, ...] = ("backward",),
    precision=None,
):
    """Returns train(state, ctx, batches, val_batches) with the engine's
    standard outputs (state, loss_dict, metric_dict, n_steps).
    ``precision`` threads the engine's mixed-precision policy into the
    train steps (the per-epoch gamma-rule validation scores f32 master
    weights, like the other early-stop paths)."""
    step_fn = engine.make_train_step(logic, tx, precision=precision)
    evaluate = engine.make_local_eval(logic, metric_manager)
    meter_proto = LossMeter.create(loss_keys)
    n_epochs = config.n_epochs

    def train(state: TrainState, ctx: Any, batches: Batch, val_batches: Batch):
        total = batches.step_mask.shape[0]
        steps_per_epoch = total // n_epochs
        assert steps_per_epoch * n_epochs == total, (
            f"batch stream ({total} steps) must divide into n_epochs={n_epochs}"
        )
        chunked = jax.tree_util.tree_map(
            lambda x: x.reshape((n_epochs, steps_per_epoch) + x.shape[1:]), batches
        )

        def epoch_body(carry, chunk: Batch):
            st, meter, mstate, prev_loss, stopped, epochs_run, executed = carry
            chunk = chunk.replace(step_mask=chunk.step_mask * (1.0 - stopped))

            def body(c, b):
                st2, meter2, ms2 = c
                st2, out = step_fn(st2, ctx, b)
                meter2 = meter2.update(out.losses, weight=out.step_mask)
                ms2 = metric_manager.update(ms2, out.preds, out.targets, out.example_mask)
                return (st2, meter2, ms2), None

            (st, meter, mstate), _ = jax.lax.scan(body, (st, meter, mstate), chunk)
            executed = executed + jnp.sum(chunk.step_mask)

            val_losses, _ = evaluate(st, ctx, val_batches)
            current = val_losses["checkpoint"]
            live = stopped < 0.5
            if config.gamma is not None:
                # stop rule denominator = this LIVE epoch's 0-based index + 1
                # (flash_client.py:152 `gamma / (local_epoch + 1)`)
                threshold = config.gamma / (epochs_run + 1.0)
                should_stop = ((prev_loss - current) < threshold) & live
                stopped = jnp.maximum(stopped, should_stop.astype(jnp.float32))
            prev_loss = jnp.where(live, current, prev_loss)
            epochs_run = epochs_run + live.astype(jnp.float32)
            return (st, meter, mstate, prev_loss, stopped, epochs_run, executed), current

        init = (
            state,
            meter_proto,
            metric_manager.init(),
            jnp.asarray(jnp.inf, jnp.float32),
            jnp.zeros((), jnp.float32),
            jnp.zeros((), jnp.float32),
            jnp.zeros((), jnp.float32),
        )
        (state, meter, mstate, _, _, _, executed), _ = jax.lax.scan(
            epoch_body, init, chunked
        )
        state = logic.finalize_round(state, ctx, executed)
        return state, meter.compute(), metric_manager.compute(mstate), executed

    return train

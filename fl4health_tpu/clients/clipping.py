"""Client-level DP clipping client — clipped weight-update deltas.

Parity: /root/reference/fl4health/clients/clipping_client.py:22
(clip_parameters :86, compute_weight_update_and_clip :113): after local
training compute delta = w_local - w_received, flat-clip it to the bound C
received from the server (factor = min(1, C / ||delta||_2)), and send
(clipped delta, clipping bit). Reference convention (clip_parameters :86):
bit = 1.0 when the norm is BELOW the bound (the server's adaptive-bound
update estimates P(||delta|| < C) ~ quantile, Andrew et al. 1905.03871), and
is forced to 0.0 when adaptive clipping is off to avoid leaking norms.
"""

from __future__ import annotations

import jax.numpy as jnp
from flax import struct

from fl4health_tpu.clients.engine import ClientLogic, TrainState
from fl4health_tpu.core import pytree as ptu
from fl4health_tpu.core.types import Params
from fl4health_tpu.exchange.packer import ClippingBitPacket


@struct.dataclass
class ClippingContext:
    initial_params: Params
    clipping_bound: jnp.ndarray


class ClippingClientLogic(ClientLogic):
    def __init__(self, model, criterion, adaptive_clipping: bool = False):
        super().__init__(model, criterion)
        self.adaptive_clipping = adaptive_clipping

    def init_round_context(self, state: TrainState, payload) -> ClippingContext:
        return ClippingContext(
            initial_params=state.params,
            clipping_bound=payload.clipping_bound,
        )

    def init_extra(self, params: Params):
        return {"delta": ptu.tree_zeros_like(params),
                "clipping_bit": jnp.zeros((), jnp.float32)}

    def finalize_round(self, state: TrainState, ctx: ClippingContext, local_steps):
        delta = ptu.tree_sub(state.params, ctx.initial_params)
        norm = ptu.global_norm(delta)
        bound = jnp.asarray(ctx.clipping_bound, jnp.float32)
        factor = jnp.minimum(1.0, bound / jnp.maximum(norm, 1e-12))
        clipped = ptu.tree_scale(delta, factor)
        bit = (norm <= bound).astype(jnp.float32)
        if not self.adaptive_clipping:
            bit = jnp.zeros((), jnp.float32)  # don't leak norms when unused
        return state.replace(extra={"delta": clipped, "clipping_bit": bit})

    def pack(self, state: TrainState, pushed_params, train_losses) -> ClippingBitPacket:
        # delta + bit were stashed by finalize_round (which runs inside the
        # compiled round right after the last local step)
        return ClippingBitPacket(
            params=state.extra["delta"], clipping_bit=state.extra["clipping_bit"]
        )

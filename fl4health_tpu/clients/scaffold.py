"""SCAFFOLD client logic — control-variate-corrected local SGD.

Parity: /root/reference/fl4health/clients/scaffold_client.py:23.
- Requires vanilla SGD with a known learning rate (asserted there).
- Per step the gradient is corrected: g <- g - c_i + c
  (modify_grad, scaffold_client.py).
- After local training, option-II variate update (update_control_variates
  :137):  c_i+ = c_i - c + (x - y_i) / (K * lr);  delta_c_i = c_i+ - c_i.
- Packs (weights, delta_c_i) (get_parameters :79-100).
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.clients.engine import Batch, ClientLogic, TrainState
from fl4health_tpu.core import pytree as ptu
from fl4health_tpu.core.types import Params
from fl4health_tpu.exchange.packer import ControlVariatesPacket


@struct.dataclass
class ScaffoldExtra:
    client_variates: Params  # c_i
    delta: Params  # delta_c_i from the last finished round


@struct.dataclass
class ScaffoldContext:
    initial_params: Params  # x (received global)
    server_variates: Params  # c


class ScaffoldClientLogic(ClientLogic):
    """Must be paired with optax.sgd(learning_rate) — plain SGD, no momentum
    (reference asserts this, scaffold_client.py)."""

    def __init__(self, model, criterion, learning_rate: float):
        super().__init__(model, criterion)
        self.learning_rate = learning_rate

    def init_extra(self, params: Params) -> ScaffoldExtra:
        zeros = ptu.tree_zeros_like(params)
        return ScaffoldExtra(client_variates=zeros, delta=zeros)

    def init_round_context(self, state: TrainState, payload) -> ScaffoldContext:
        return ScaffoldContext(
            initial_params=payload.params,
            server_variates=payload.control_variates,
        )

    def transform_gradients(self, grads, state: TrainState, ctx: ScaffoldContext):
        # g - c_i + c
        return jax.tree_util.tree_map(
            lambda g, ci, c: g - ci + c,
            grads, state.extra.client_variates, ctx.server_variates,
        )

    def finalize_round(self, state: TrainState, ctx: ScaffoldContext, local_steps):
        k_lr = jnp.maximum(local_steps.astype(jnp.float32), 1.0) * self.learning_rate
        # c_i+ = c_i - c + (x - y_i) / (K * lr)
        new_ci = jax.tree_util.tree_map(
            lambda ci, c, x, y: ci - c + (x - y) / k_lr,
            state.extra.client_variates,
            ctx.server_variates,
            ctx.initial_params,
            state.params,
        )
        delta = ptu.tree_sub(new_ci, state.extra.client_variates)
        return state.replace(
            extra=ScaffoldExtra(client_variates=new_ci, delta=delta)
        )

    def pack(self, state: TrainState, pushed_params, train_losses) -> ControlVariatesPacket:
        return ControlVariatesPacket(
            params=pushed_params, control_variates=state.extra.delta
        )

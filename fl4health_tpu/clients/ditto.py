"""Ditto and MR-MTL client logics — drift-constrained personal models.

Parity targets:
- Ditto (/root/reference/fl4health/clients/ditto_client.py:20): trains a
  GLOBAL model (exchanged, vanilla loss) and a PERSONAL model (private) with
  an l2 drift constraint pulling the personal weights toward the weights
  received from the server this round; two optimizers. Validation/metrics run
  on the personal model. The adaptive variant packs the global-model vanilla
  train loss so the server can adapt lambda
  (adaptive_drift_constraint_client.py:82-106).
- MR-MTL (/root/reference/fl4health/clients/mr_mtl_client.py:18): a single
  personal model that is NEVER overwritten by the server; the received
  aggregate is only the drift target. The personal weights are still sent up
  for averaging.

TPU-native design: Ditto's twin models are one param tree with
``global_model`` / ``personal_model`` subtrees (models.bases.TwinModel);
one grad pass over the combined loss yields exactly the two reference
backward passes because the two loss terms touch disjoint subtrees.
"""

from __future__ import annotations

from typing import Any

import jax
import jax.numpy as jnp
from flax import struct

from fl4health_tpu.clients.engine import Batch, ClientLogic, TrainState
from fl4health_tpu.core.types import Params
from fl4health_tpu.exchange.packer import AdaptiveConstraintPacket
from fl4health_tpu.losses.drift import weight_drift_loss


@struct.dataclass
class DittoContext:
    initial_global_params: Params  # received global-model weights (drift target)
    drift_penalty_weight: Any  # lambda


class DittoClientLogic(ClientLogic):
    """Pair with ``models.bases.TwinModel`` (params have ``global_model`` /
    ``personal_model`` subtrees) and a FixedLayerExchanger on
    ``TwinModel.exchange_global_model``.

    Reference: clients/ditto_client.py:20 (loss composition at
    compute_training_loss — global vanilla CE + personal CE +
    lam/2 * ||personal - received||^2).
    """

    extra_loss_keys = ("global_ce", "personal_ce", "penalty")

    def __init__(self, model, criterion, lam: float = 1.0, adaptive: bool = False):
        super().__init__(model, criterion)
        self.lam = lam
        self.adaptive = adaptive

    def init_round_context(self, state: TrainState, payload) -> DittoContext:
        lam = getattr(payload, "drift_penalty_weight", None)
        if lam is None:
            lam = jnp.asarray(self.lam, jnp.float32)
        payload_params = payload.params if hasattr(payload, "params") else payload
        return DittoContext(
            initial_global_params=payload_params["global_model"],
            drift_penalty_weight=lam,
        )

    def training_loss(self, preds, features, batch: Batch, params, state, ctx: DittoContext):
        global_ce = self.criterion(preds["global"], batch.y, batch.example_mask)
        personal_ce = self.criterion(preds["personal"], batch.y, batch.example_mask)
        penalty = 0.5 * weight_drift_loss(
            params["personal_model"], ctx.initial_global_params,
            ctx.drift_penalty_weight,
        )
        total = global_ce + personal_ce + penalty
        return total, {
            "global_ce": global_ce,
            "personal_ce": personal_ce,
            "penalty": penalty,
        }

    def eval_loss(self, preds, features, batch: Batch, params, state, ctx):
        # Validation is on the personal model (ditto_client.py validate path).
        loss = self.criterion(preds["personal"], batch.y, batch.example_mask)
        return loss, {}

    def pack(self, state: TrainState, pushed_params, train_losses):
        if not self.adaptive:
            return pushed_params
        return AdaptiveConstraintPacket(
            params=pushed_params,
            loss_for_adaptation=train_losses["global_ce"],
        )


@struct.dataclass
class MrMtlContext:
    initial_params: Params  # received aggregate (drift target only)
    drift_penalty_weight: Any


class KeepLocalExchanger:
    """MR-MTL wire behavior: push the personal weights for aggregation, but
    NEVER overwrite them on pull — the aggregate is consumed as a drift
    target inside the loss (mr_mtl_client.py:18 setup: model weights are not
    set from the server after round 1)."""

    def push(self, params: Params, initial_params: Params | None = None) -> Params:
        del initial_params
        return params

    def pull(self, payload: Params, local: Params) -> Params:
        del payload
        return local


class MrMtlClientLogic(ClientLogic):
    """Mean-regularized multi-task learning. Pair with KeepLocalExchanger.

    Reference: clients/mr_mtl_client.py:18 — loss = vanilla +
    lam/2 * ||w - w_aggregate||^2, with the adaptive variant packing the
    vanilla loss.
    """

    extra_loss_keys = ("vanilla", "penalty")

    def __init__(self, model, criterion, lam: float = 1.0, adaptive: bool = False):
        super().__init__(model, criterion)
        self.lam = lam
        self.adaptive = adaptive

    def init_round_context(self, state: TrainState, payload) -> MrMtlContext:
        lam = getattr(payload, "drift_penalty_weight", None)
        if lam is None:
            lam = jnp.asarray(self.lam, jnp.float32)
        payload_params = payload.params if hasattr(payload, "params") else payload
        return MrMtlContext(initial_params=payload_params, drift_penalty_weight=lam)

    def training_loss(self, preds, features, batch: Batch, params, state, ctx: MrMtlContext):
        vanilla = self.criterion(preds["prediction"], batch.y, batch.example_mask)
        penalty = 0.5 * weight_drift_loss(
            params, ctx.initial_params, ctx.drift_penalty_weight
        )
        return vanilla + penalty, {"vanilla": vanilla, "penalty": penalty}

    def pack(self, state: TrainState, pushed_params, train_losses):
        if not self.adaptive:
            return pushed_params
        return AdaptiveConstraintPacket(
            params=pushed_params,
            loss_for_adaptation=train_losses["vanilla"],
        )

"""Shape bucketing — the sweep compiles O(buckets), not O(cells).

A compiled round program's identity is its SHAPES plus its closure
constants. Across a scenario grid the shape-relevant facts are: the
cohort axis length, the data banks' padded row counts, the scan length
(rounds) and the per-round plan shapes (local_steps x batch). Everything
else — seeds, partition contents, per-client sample counts, hoisted
scalars — enters as program inputs. This module groups cells by the facts
that DO force a distinct executable:

- strategy name and client-algorithm name (different aggregation/client
  math => different program structure);
- fault-plan name (the chaos layer compiles into the round closure);
- the cohort's shape BUCKET (smallest configured bucket >= cohort; cells
  pad to it with phantom clients that are masked to zero weight and zero
  sample count — the fractional-mask machinery the repo already trusts
  for sampling/quarantine/async discounting);
- the group's bank ROW BUDGET (max padded example rows over its cells —
  each cell's stacked banks zero-pad up to it; padding rows are never
  indexed by a valid plan, so gathered batches are bit-identical).

Fault plans with probabilistic faults draw a ``[n_clients]`` uniform
vector, so padding the cohort would change the draws for REAL clients;
padded buckets therefore reject probability<1 fault plans loudly
(deterministic faults are per-client-stable under padding).
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from fl4health_tpu.clients import engine
from fl4health_tpu.sweep.spec import SweepCell, SweepSpec


@dataclasses.dataclass(frozen=True)
class GroupKey:
    """Identity of one shared executable (one program group)."""

    strategy: str
    client: str
    fault: str
    bucket: int

    def label(self) -> str:
        parts = [self.strategy, self.client]
        if self.fault != "none":
            parts.append(self.fault)
        parts.append(f"b{self.bucket}")
        return "/".join(parts)


@dataclasses.dataclass
class SweepGroup:
    key: GroupKey
    cells: list[SweepCell]
    train_row_budget: int = 0
    val_row_budget: int = 0


@dataclasses.dataclass
class SweepPlan:
    """The up-front bucket plan — reported before any compile happens."""

    groups: list[SweepGroup]
    n_cells: int

    @property
    def buckets(self) -> list[int]:
        return sorted({g.key.bucket for g in self.groups})

    def describe(self) -> dict:
        return {
            "cells": self.n_cells,
            "groups": len(self.groups),
            "buckets": self.buckets,
            "group_cells": {g.key.label(): len(g.cells) for g in self.groups},
        }


def _require_padding_safe_fault(fault_plan, fault_name: str,
                                cohort: int, bucket: int) -> None:
    if fault_plan is None or bucket == cohort:
        return
    bad = [
        f for f in getattr(fault_plan, "client_faults", ())
        if getattr(f, "probability", 1.0) < 1.0
    ]
    if bad:
        raise ValueError(
            f"fault plan {fault_name!r} has probabilistic faults "
            f"(probability < 1), whose per-round uniform draw is shaped "
            f"[n_clients] — padding cohort {cohort} to bucket {bucket} "
            "would change the draws for REAL clients and break the "
            "standalone-reproduction contract. Use probability-1 faults "
            "with padded buckets, or give this cohort its own bucket."
        )


def _require_padding_safe_manager(spec: SweepSpec, cell: SweepCell,
                                  bucket: int) -> None:
    """Probability<1 Poisson managers are rejected under padded buckets —
    the fault-plan padding POLICY applied to sampling draws.

    Today the runner draws masks host-side from a manager built over the
    REAL cohort and only zero-pads the result, so padding does not
    actually shift the draws. The rule exists as a contract, not a
    present-day hazard: probabilistic per-client draws are the one
    manager family whose realization is coupled to the population shape,
    and any future in-graph or bucket-shaped sampling (the natural next
    optimization: folding the mask draw into the cell program, exactly
    where the fault plans already live) would silently change REAL
    clients' draws under padding. Rejecting now keeps the axis's
    composability promise identical to the fault plans' and makes that
    refactor non-breaking."""
    if bucket == cell.cohort:
        return
    from fl4health_tpu.server.client_manager import PoissonSamplingManager

    manager = spec.client_managers[cell.manager](cell.cohort)
    if (isinstance(manager, PoissonSamplingManager)
            and manager.fraction < 1.0):
        raise ValueError(
            f"client manager {cell.manager!r} is Poisson with "
            f"probability {manager.fraction} < 1: probabilistic "
            "per-client draws are shape-coupled to the population, and "
            f"padding cohort {cell.cohort} to bucket {bucket} is "
            "excluded by the same rule as probabilistic fault plans "
            "(see bucketing._require_padding_safe_manager). Give this "
            "cohort its own bucket, or use a fixed-fraction manager."
        )


def plan_groups(spec: SweepSpec, cells: list[SweepCell],
                data_for) -> SweepPlan:
    """Group cells into shared-executable buckets and size each group's
    bank row budgets. ``data_for(partitioner, cohort)`` returns the cell's
    (unpadded) datasets — memoized by the caller so each partition is
    materialized once."""
    groups: dict[GroupKey, SweepGroup] = {}
    for cell in cells:
        bucket = spec.bucket_for(cell.cohort)
        _require_padding_safe_fault(
            spec.fault_plans[cell.fault], cell.fault, cell.cohort, bucket
        )
        _require_padding_safe_manager(spec, cell, bucket)
        key = GroupKey(strategy=cell.strategy, client=cell.client,
                       fault=cell.fault, bucket=bucket)
        groups.setdefault(key, SweepGroup(key=key, cells=[])).cells.append(
            cell
        )
    for g in groups.values():
        for cell in g.cells:
            datasets = data_for(cell.partitioner, cell.cohort)
            g.train_row_budget = max(
                g.train_row_budget,
                max(engine.data_rows(d.x_train) for d in datasets),
            )
            g.val_row_budget = max(
                g.val_row_budget,
                max(engine.data_rows(d.x_val) for d in datasets),
            )
    return SweepPlan(groups=list(groups.values()), n_cells=len(cells))


# -- padding helpers --------------------------------------------------------

def pad_datasets(datasets: list, bucket: int) -> list:
    """Pad a cohort to ``bucket`` clients with copies of client 0 — the
    phantom clients train on real-shaped data (their packets stay finite)
    but are masked to zero aggregation weight, zero sample count and zero
    eval count by the runner, so they cannot influence any real client or
    the server state."""
    if len(datasets) >= bucket:
        return list(datasets)
    return list(datasets) + [datasets[0]] * (bucket - len(datasets))


def pad_stack_rows(stack, rows: int):
    """Zero-pad a ``[C, n, ...]`` client-stacked data bank along the row
    axis up to the group's row budget. Padding rows are never selected by
    a valid index plan, so the gathered batches — and therefore the cell's
    trajectory — are bit-identical to the unpadded bank's."""
    def pad(leaf):
        n = leaf.shape[1]
        if n >= rows:
            return leaf
        width = [(0, 0), (0, rows - n)] + [(0, 0)] * (leaf.ndim - 2)
        return jnp.pad(leaf, width)

    return jax.tree_util.tree_map(pad, stack)


def padded_mask(mask: np.ndarray, bucket: int) -> np.ndarray:
    """Extend a [C] participation mask with zeros for phantom clients."""
    c = mask.shape[-1]
    if c >= bucket:
        return mask
    pad = [(0, 0)] * (mask.ndim - 1) + [(0, bucket - c)]
    return np.pad(mask, pad)

"""SweepSpec — the declarative scenario grid.

One frozen-ish dataclass names every axis of a
{strategy x client algorithm x non-IID partitioner x cohort size x fault
plan x seed x scalar hyperparameter} grid, as FACTORIES (fresh objects per
program group — strategies and logic are stateful Python objects, sharing
one instance across groups would leak trace-time rebinds between them).
``expand_cells`` materializes the cartesian product into
:class:`SweepCell` rows; scalar axes apply only to cells whose strategy
chain can rebind them (``fl4health_tpu/sweep/hoisting.py`` registry) and
collapse to a single cell where they don't — the grid never silently
sweeps a knob that cannot take effect.

Design constraints (v1, enforced loudly):

- ``local_steps`` only: per-epoch plans derive their step count from each
  partition's size, which would make the compiled scan length a function
  of the partitioner — exactly the shape drift the sweep exists to avoid.
- sampling managers ARE sweepable (``client_managers`` axis): masks are
  host-drawn from the standalone run's exact PRNG stream, so a manager
  cell reproduces ``FederatedSimulation(client_manager=...)`` bit-for-bit
  and never changes program shapes. The one exclusion: probability<1
  Poisson managers under a PADDED bucket (the fault-plan padding policy
  applied to sampling draws — see
  ``bucketing._require_padding_safe_manager`` for why this is a contract
  rather than a present-day draw hazard).
- test splits are not swept (val split only) — one eval program per group.
"""

from __future__ import annotations

import dataclasses
import itertools
from typing import Any, Callable, Mapping, Sequence

from fl4health_tpu.sweep.hoisting import SCALAR_BINDINGS, applicable_scalars, binding


@dataclasses.dataclass(frozen=True)
class SweepCell:
    """One grid cell — everything needed to reproduce it standalone."""

    index: int
    strategy: str
    client: str
    partitioner: str
    cohort: int
    fault: str
    seed: int
    scalars: tuple[tuple[str, float], ...] = ()
    manager: str = "full"

    @property
    def scalar_dict(self) -> dict[str, float]:
        return dict(self.scalars)

    def label(self) -> str:
        parts = [self.strategy, self.client, self.partitioner,
                 f"c{self.cohort}"]
        if self.fault != "none":
            parts.append(self.fault)
        if self.manager != "full":
            # absent for the default axis value, so pre-manager-axis
            # grids keep their exact labels (and thus ledger fingerprints)
            parts.append(f"m:{self.manager}")
        parts.append(f"s{self.seed}")
        parts += [f"{k}={v:g}" for k, v in self.scalars]
        return "/".join(parts)


@dataclasses.dataclass
class SweepSpec:
    """Declarative grid over the scenario axes.

    ``strategies`` / ``clients``: name -> zero-arg factory returning a
    fresh ``Strategy`` / ``ClientLogic``.
    ``partitioners``: name -> ``f(cohort_size) -> [ClientDataset, ...]``;
    must be deterministic per (name, cohort) — the standalone-reproduction
    contract depends on it.
    ``tx``: zero-arg factory for the client optimizer.
    ``metrics``: zero-arg factory for the ``MetricManager`` (default: no
    metrics).
    ``scalars``: hoisted-scalar axes by registered name
    (``sweep.hoisting.SCALAR_BINDINGS``) -> values; cells whose strategy
    chain lacks the knob collapse to one cell per remaining combo.
    ``client_managers``: sampling-manager axis — name ->
    ``f(cohort_size) -> ClientManager | None`` (None = full
    participation, the default). Masks are drawn host-side from the SAME
    PRNG stream a standalone run with that manager would use
    (``fold_in(rng, 2000 + round)``), so manager cells keep the
    standalone-reproduction contract; the manager never changes program
    shapes, so it composes with bucketing — EXCEPT probability<1 Poisson
    managers under a padded bucket, which are rejected loudly (the
    fault-plan padding policy applied to sampling draws; rationale in
    ``bucketing._require_padding_safe_manager``). The name ``"full"`` is
    reserved for full participation (factory returning None): cell labels
    omit it, keeping pre-axis ledger fingerprints valid.
    ``cohort_buckets``: optional ascending shape buckets; each cell runs
    padded to the smallest bucket >= its cohort (phantom clients are
    zero-weight — pure perf, never semantics). Default: one bucket per
    distinct cohort size (no padding).
    ``pack``: stack cells sharing an executable+bucket along a leading
    cell axis and dispatch each pack as ONE batched chunked-scan run;
    ``max_pack`` bounds the stacked memory.
    ``target_eval_loss``: optional leaderboard target for the
    rounds-to-target column.
    """

    strategies: Mapping[str, Callable[[], Any]]
    clients: Mapping[str, Callable[[], Any]]
    partitioners: Mapping[str, Callable[[int], Sequence[Any]]]
    rounds: int
    batch_size: int
    local_steps: int
    tx: Callable[[], Any]
    metrics: Callable[[], Any] | None = None
    seeds: Sequence[int] = (42,)
    cohort_sizes: Sequence[int] = ()
    fault_plans: Mapping[str, Any] = dataclasses.field(
        default_factory=lambda: {"none": None}
    )
    scalars: Mapping[str, Sequence[float]] = dataclasses.field(
        default_factory=dict
    )
    client_managers: Mapping[str, Callable[[int], Any]] = dataclasses.field(
        default_factory=lambda: {"full": lambda cohort: None}
    )
    cohort_buckets: Sequence[int] | None = None
    pack: bool = True
    max_pack: int = 8
    target_eval_loss: float | None = None

    def __post_init__(self):
        for name, m in (("strategies", self.strategies),
                        ("clients", self.clients),
                        ("partitioners", self.partitioners)):
            if not m:
                raise ValueError(f"SweepSpec.{name} must be non-empty")
        if self.rounds < 1:
            raise ValueError(f"rounds must be >= 1; got {self.rounds}")
        if self.local_steps < 1:
            raise ValueError(
                f"local_steps must be >= 1; got {self.local_steps} "
                "(per-epoch plans are not sweepable: the scan length "
                "would depend on the partition sizes)"
            )
        if self.batch_size < 1:
            raise ValueError(f"batch_size must be >= 1; got {self.batch_size}")
        if not self.seeds:
            raise ValueError("SweepSpec.seeds must be non-empty")
        if not self.cohort_sizes:
            raise ValueError(
                "SweepSpec.cohort_sizes must name at least one cohort size"
            )
        if self.max_pack < 1:
            raise ValueError(f"max_pack must be >= 1; got {self.max_pack}")
        if not self.client_managers:
            raise ValueError(
                "SweepSpec.client_managers must be non-empty (use the "
                "default {'full': lambda cohort: None} for full "
                "participation)"
            )
        if "full" in self.client_managers:
            # The NAME "full" is reserved: cell labels omit it (so
            # pre-manager-axis grids keep their exact labels and thus
            # ledger fingerprints), which means a sampling manager hiding
            # behind it would fingerprint-collide with a genuine
            # full-participation grid and restore the wrong trajectories
            # on resume. Probe the factory once to enforce the contract.
            probe = self.client_managers["full"](2)
            if probe is not None:
                raise ValueError(
                    "client_managers name 'full' is reserved for full "
                    "participation (its factory must return None — cell "
                    f"labels omit it); got {type(probe).__name__} — "
                    "register the sampling manager under another name"
                )
        for name in self.scalars:
            binding(name)  # raises with the registered-name list
        if self.cohort_buckets is not None:
            buckets = sorted(self.cohort_buckets)
            if not buckets:
                raise ValueError("cohort_buckets, when given, must be "
                                 "non-empty")
            too_big = [c for c in self.cohort_sizes if c > buckets[-1]]
            if too_big:
                raise ValueError(
                    f"cohort sizes {too_big} exceed the largest bucket "
                    f"{buckets[-1]}; add a bucket that fits them"
                )

    # ------------------------------------------------------------------
    def bucket_for(self, cohort: int) -> int:
        if self.cohort_buckets is None:
            return cohort
        for b in sorted(self.cohort_buckets):
            if b >= cohort:
                return b
        raise AssertionError("validated in __post_init__")

    def applicable_scalar_axes(self) -> dict[str, list[str]]:
        """strategy name -> swept scalar axes its chain can rebind
        (probed on one throwaway instance per strategy factory)."""
        out = {}
        for name, factory in self.strategies.items():
            probe = factory()
            applicable = set(applicable_scalars(probe))
            out[name] = [a for a in SCALAR_BINDINGS if a in self.scalars
                         and a in applicable]
        return out

    def expand_cells(self) -> list[SweepCell]:
        """The grid, deterministic order (strategy-major, seed-minor)."""
        by_strategy = self.applicable_scalar_axes()
        cells: list[SweepCell] = []
        idx = 0
        for strat, client, part, cohort, fault, manager in itertools.product(
            self.strategies, self.clients, self.partitioners,
            self.cohort_sizes, self.fault_plans, self.client_managers,
        ):
            axes = by_strategy[strat]
            combos: list[tuple[tuple[str, float], ...]] = [()]
            if axes:
                combos = [
                    tuple(zip(axes, values))
                    for values in itertools.product(
                        *[self.scalars[a] for a in axes]
                    )
                ]
            for combo, seed in itertools.product(combos, self.seeds):
                cells.append(SweepCell(
                    index=idx, strategy=strat, client=client,
                    partitioner=part, cohort=int(cohort), fault=fault,
                    seed=int(seed), scalars=combo, manager=manager,
                ))
                idx += 1
        return cells

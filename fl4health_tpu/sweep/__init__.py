"""Shared-compilation scenario sweeps (ROADMAP item 5).

A declarative grid runner over
{strategy x client algorithm x non-IID partitioner x cohort size x fault
plan x seed x scalar hyperparameter} that executes every cell through the
repo's chunked-scan round programs while compiling once per SHAPE BUCKET,
not once per cell (FedJAX's shared-compilation argument,
arXiv:2108.02117). Three mechanisms carry it:

1. trace-time hyperparameter hoisting (:mod:`.hoisting`) — scalars that
   would bake into the jaxpr become traced program inputs / state leaves;
2. shape bucketing (:mod:`.bucketing`) — cohorts pad to buckets with
   zero-weight phantom clients, banks pad to a group row budget;
3. cell packing (:mod:`.runner`) — cells sharing an executable stack
   along a leading cell axis and dispatch as one batched scan run.

Every cell reproduces its standalone ``FederatedSimulation.fit()``
trajectory bit-identically (tests/sweep/) — packing and padding are pure
perf, never semantics. See ``docs/module_guides/sweeps.md``.
"""

from fl4health_tpu.sweep.bucketing import GroupKey, SweepGroup, SweepPlan
from fl4health_tpu.sweep.hoisting import (
    SCALAR_BINDINGS,
    ScalarBinding,
    applicable_scalars,
    apply_state_scalars,
    bind_traced_scalars,
)
from fl4health_tpu.sweep.runner import (
    CellResult,
    SweepLedger,
    SweepResult,
    SweepRunner,
    run_sweep,
)
from fl4health_tpu.sweep.spec import SweepCell, SweepSpec

__all__ = [
    "CellResult",
    "SweepLedger",
    "GroupKey",
    "SCALAR_BINDINGS",
    "ScalarBinding",
    "SweepCell",
    "SweepGroup",
    "SweepPlan",
    "SweepResult",
    "SweepRunner",
    "SweepSpec",
    "applicable_scalars",
    "apply_state_scalars",
    "bind_traced_scalars",
    "run_sweep",
]

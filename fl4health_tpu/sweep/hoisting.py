"""Trace-time scalar hyperparameter hoisting — the sweep's compile saver.

A jitted round program bakes every Python-scalar hyperparameter it reads at
trace time into the jaxpr as a constant, so a grid sweeping "server lr x
trim fraction" recompiles per cell even though nothing about the program's
SHAPE changed. FedJAX (arXiv:2108.02117) identifies exactly this as the
dominant cost of federated-simulation grids. This module is the repo's
fix: a registry of hoistable scalars plus two rebind mechanisms that turn
them into *traced values* of one shared executable:

- **state leaves** — scalars that already live in the carried server
  state (FedProx's ``drift_penalty_weight``) or were moved there
  (``fed_adam``-family server lr via ``optax.inject_hyperparams`` ->
  ``opt_state.hyperparams``). Rebinding is pure state surgery
  (:func:`apply_state_scalars`); every compiled program — standalone
  pipelined, chunked, or sweep cell — picks the new value up as an input.
- **attr injection** — scalars read off a strategy attribute at trace
  time (``RobustFedAvg.trim_fraction``/``max_update_norm``,
  ``FedBuff.staleness_exponent``, ``CompressingStrategy``'s adaptive
  top-k schedule endpoints). :func:`bind_traced_scalars` temporarily sets
  the attribute to a TRACER while the sweep's cell program traces, so the
  jaxpr takes the scalar as a program input (the per-cell ``hvec``); the
  async round programs additionally feed ``staleness_exponent`` as a live
  dispatch input so even a standalone async run rebinds it recompile-free.

Shape-affecting knobs stay static by design and are NOT registered here:
``CompressionConfig.topk_fraction`` (sizes the top-k selection and wire
sidecar), ``quant_bits`` (wire format), ``AsyncConfig.buffer_size`` /
``max_staleness`` (event-plan identity), Krum's ``num_byzantine`` /
``multi_krum_m`` (selection arithmetic is static config by contract).
Sweeping those is still legal — the runner just gives each value its own
program group (an honest compile, reported in the bucket plan).
"""

from __future__ import annotations

import contextlib
import dataclasses
from typing import Any, Callable

import jax
import jax.numpy as jnp


def wrapper_chain(strategy) -> list:
    """``[strategy, strategy.inner, ...]`` down to the innermost."""
    chain = [strategy]
    while hasattr(chain[-1], "inner"):
        chain.append(chain[-1].inner)
    return chain


def _find_owner(strategy, owner_type):
    for s in wrapper_chain(strategy):
        if isinstance(s, owner_type):
            return s
    return None


def _replace_owned_state(strategy, state, owner_type, fn):
    """Apply ``fn(owner_strategy, owner_state) -> new_owner_state`` at the
    wrapper-chain level owning the scalar, rebuilding wrapper states above
    it. Wrappers whose state IS the inner state (RobustFedAvg, FedBuff)
    have no ``.inner`` state level and pass straight through."""
    if isinstance(strategy, owner_type):
        return fn(strategy, state)
    if not hasattr(strategy, "inner"):
        raise KeyError(f"no {owner_type.__name__} in the strategy chain")
    if hasattr(state, "inner"):
        return state.replace(inner=_replace_owned_state(
            strategy.inner, state.inner, owner_type, fn
        ))
    return _replace_owned_state(strategy.inner, state, owner_type, fn)


@dataclasses.dataclass(frozen=True)
class ScalarBinding:
    """One hoistable scalar hyperparameter.

    ``kind="attr"``: read off ``owner().attr`` at trace time; the sweep
    injects a tracer for it (``bind_traced_scalars``), so it becomes an
    ``hvec`` program input. ``kind="state"``: already a leaf of the
    carried server state; ``set_state(owner, owner_state, value)`` rebinds
    it. ``owner`` is a zero-arg callable returning the owning strategy
    TYPE (lazy import, keeps this module cycle-free)."""

    name: str
    kind: str  # "attr" | "state"
    owner: Callable[[], type]
    attr: str = ""
    set_state: Callable[[Any, Any, float], Any] | None = None
    validate: Callable[[float], None] | None = None
    #: optional owner-aware validation (e.g. a schedule endpoint against
    #: its config's static ceiling) — runs wherever a CONCRETE value is
    #: bound (the sweep's cell-input resolution), since a traced hvec
    #: slice can only be range-clamped in-graph
    validate_owner: Callable[[Any, float], None] | None = None
    doc: str = ""

    def find(self, strategy):
        return _find_owner(strategy, self.owner())

    def check(self, strategy, value: float) -> None:
        """Validate a concrete value for this knob on this strategy chain."""
        if self.validate is not None:
            self.validate(float(value))
        if self.validate_owner is not None:
            owner = self.find(strategy)
            if owner is not None:
                self.validate_owner(owner, float(value))

    def applies(self, strategy) -> bool:
        owner = self.find(strategy)
        if owner is None:
            return False
        if self.kind == "attr":
            # an attr whose default is None encodes "feature not enabled"
            # (e.g. no topk_schedule configured) — not sweepable then
            return getattr(owner, self.attr, None) is not None
        return True

    def default(self, strategy) -> float:
        owner = self.find(strategy)
        if self.kind == "attr":
            return float(getattr(owner, self.attr))
        return float(self._state_default(owner))

    def _state_default(self, owner) -> float:
        raise NotImplementedError  # overridden per-binding below


def _validate_fraction_half(v: float) -> None:
    if not 0.0 <= v < 0.5:
        raise ValueError(f"trim_fraction must be in [0, 0.5); got {v}")


def _validate_positive(name: str):
    def check(v: float) -> None:
        if v <= 0:
            raise ValueError(f"{name} must be positive; got {v}")
    return check


def _validate_nonnegative(name: str):
    def check(v: float) -> None:
        if v < 0:
            raise ValueError(f"{name} must be >= 0; got {v}")
    return check


def _validate_unit(name: str):
    def check(v: float) -> None:
        if not 0.0 < v <= 1.0:
            raise ValueError(f"{name} must be in (0, 1]; got {v}")
    return check


def _validate_under_topk_ceiling(name: str):
    """Schedule endpoints above the static ``topk_fraction`` ceiling would
    be silently clamped in-graph — two 'different' sweep cells running the
    identical config. Reject at bind time instead, mirroring
    ``CompressionConfig.__post_init__``'s static-schedule rule."""
    def check(owner, v: float) -> None:
        ceiling = owner.config.topk_fraction
        if ceiling is not None and v > float(ceiling):
            raise ValueError(
                f"{name}={v} exceeds the static topk_fraction ceiling "
                f"{ceiling} — the effective fraction would clamp to the "
                "ceiling and the cell would silently duplicate the "
                f"ceiling config; sweep values <= {ceiling}, or raise "
                "topk_fraction"
            )
    return check


# -- state-kind setters -----------------------------------------------------

def _injected_hyperparams(opt_state) -> dict:
    """The ``inject_hyperparams`` leaf dict of a FedOpt opt_state, or a
    helpful error naming the factories that provide it."""
    hp = getattr(opt_state, "hyperparams", None)
    if hp is None or "learning_rate" not in hp:
        raise ValueError(
            "server_lr hoisting needs the server optimizer built through "
            "optax.inject_hyperparams (the fed_adam/fed_yogi/fed_adagrad/"
            "fed_avg_m factories do this); this FedOpt's opt_state has no "
            "hyperparams['learning_rate'] leaf to rebind"
        )
    return hp


def _set_server_lr(owner, owner_state, value: float):
    opt_state = owner_state.opt_state
    hp = _injected_hyperparams(opt_state)
    lr = hp["learning_rate"]
    new_hp = dict(hp)
    new_hp["learning_rate"] = jnp.asarray(value, lr.dtype)
    return owner_state.replace(opt_state=opt_state._replace(hyperparams=new_hp))


def _set_proximal_weight(owner, owner_state, value: float):
    return owner_state.replace(
        drift_penalty_weight=jnp.asarray(
            value, owner_state.drift_penalty_weight.dtype
        )
    )


# -- the registry -----------------------------------------------------------

def _fedopt_type():
    from fl4health_tpu.strategies.fedopt import FedOpt
    return FedOpt


def _adaptive_constraint_type():
    from fl4health_tpu.strategies.fedprox import FedAvgWithAdaptiveConstraint
    return FedAvgWithAdaptiveConstraint


def _robust_type():
    from fl4health_tpu.resilience.aggregators import RobustFedAvg
    return RobustFedAvg


def _fedbuff_type():
    from fl4health_tpu.strategies.fedbuff import FedBuff
    return FedBuff


def _compressing_type():
    from fl4health_tpu.compression.strategy import CompressingStrategy
    return CompressingStrategy


class _ServerLrBinding(ScalarBinding):
    def _state_default(self, owner) -> float:
        # the factory-time value lives in the (not-yet-initialized)
        # transform; read it from a throwaway init on a scalar template
        state = owner.tx.init(jnp.zeros((1,), jnp.float32))
        return float(_injected_hyperparams(state)["learning_rate"])


class _MuBinding(ScalarBinding):
    def _state_default(self, owner) -> float:
        return float(owner.mu0)


SCALAR_BINDINGS: dict[str, ScalarBinding] = {
    b.name: b
    for b in (
        _ServerLrBinding(
            name="server_lr", kind="state", owner=_fedopt_type,
            set_state=_set_server_lr,
            validate=_validate_positive("server_lr"),
            doc="FedOpt-family server learning rate "
                "(opt_state.hyperparams['learning_rate'] leaf)",
        ),
        _MuBinding(
            name="proximal_weight", kind="state",
            owner=_adaptive_constraint_type,
            set_state=_set_proximal_weight,
            validate=_validate_nonnegative("proximal_weight"),
            doc="FedProx drift-penalty weight mu "
                "(AdaptiveConstraintState.drift_penalty_weight leaf, "
                "broadcast to clients in the payload)",
        ),
        ScalarBinding(
            name="trim_fraction", kind="attr", owner=_robust_type,
            attr="trim_fraction", validate=_validate_fraction_half,
            doc="RobustFedAvg trimmed-mean per-end trim fraction "
                "(rank weights over the sorted clients axis)",
        ),
        ScalarBinding(
            name="max_update_norm", kind="attr", owner=_robust_type,
            attr="max_update_norm",
            validate=_validate_positive("max_update_norm"),
            doc="RobustFedAvg norm-bounded-mean clip bound on each "
                "client's update norm",
        ),
        ScalarBinding(
            name="staleness_exponent", kind="attr", owner=_fedbuff_type,
            attr="staleness_exponent",
            validate=_validate_nonnegative("staleness_exponent"),
            doc="FedBuff staleness discount exponent 1/(1+s)^e (async "
                "round programs feed it as a live dispatch input)",
        ),
        ScalarBinding(
            name="topk_f_start", kind="attr", owner=_compressing_type,
            attr="topk_f_start", validate=_validate_unit("topk_f_start"),
            validate_owner=_validate_under_topk_ceiling("topk_f_start"),
            doc="CompressingStrategy adaptive top-k schedule start "
                "fraction (requires CompressionConfig.topk_schedule)",
        ),
        ScalarBinding(
            name="topk_f_end", kind="attr", owner=_compressing_type,
            attr="topk_f_end", validate=_validate_unit("topk_f_end"),
            validate_owner=_validate_under_topk_ceiling("topk_f_end"),
            doc="CompressingStrategy adaptive top-k schedule end "
                "fraction (requires CompressionConfig.topk_schedule)",
        ),
    )
}


def binding(name: str) -> ScalarBinding:
    try:
        return SCALAR_BINDINGS[name]
    except KeyError:
        raise KeyError(
            f"unknown sweep scalar {name!r}; registered hoistable scalars: "
            f"{sorted(SCALAR_BINDINGS)}"
        ) from None


def applicable_scalars(strategy) -> list[str]:
    """Registered scalar names the given strategy chain can rebind,
    registry order."""
    return [n for n, b in SCALAR_BINDINGS.items() if b.applies(strategy)]


#: attr-kind scalars that standalone round programs already read as live
#: dispatch inputs (no retrace needed) — currently only FedBuff's staleness
#: exponent, which async dispatch feeds per event.
LIVE_ATTR_SCALARS = ("staleness_exponent",)


def live_rebind_kind(strategy, name: str, *, async_active: bool = False) -> str:
    """How (whether) the admin plane can rebind ``name`` on a LIVE run.

    - ``"state"`` — a server-state leaf; ``apply_state_scalars`` rebinds it
      at a round boundary with zero recompiles.
    - ``"live_attr"`` — an attr the compiled program already takes as a
      dispatch input (async staleness exponent); a plain ``setattr`` lands
      at the next dispatch.
    - ``"static"`` — an attr-kind scalar baked into the trace as a constant
      outside a sweep cell; a live rebind would silently not take effect.
    - ``"inapplicable"`` — no owner in this strategy chain.

    Unknown names raise ``KeyError`` (via :func:`binding`).
    """
    b = binding(name)
    if not b.applies(strategy):
        return "inapplicable"
    if b.kind == "state":
        return "state"
    if name in LIVE_ATTR_SCALARS and async_active:
        return "live_attr"
    return "static"


def apply_state_scalars(strategy, server_state, values: dict[str, float]):
    """Rebind state-kind scalars on a freshly-initialized server state —
    the sweep's per-cell override for hyperparameters that live as state
    leaves. Values are validated; unknown names raise."""
    for name, value in values.items():
        b = binding(name)
        if b.kind != "state":
            raise ValueError(
                f"{name} is an attr-kind scalar; it rebinds through "
                "bind_traced_scalars / the cell program's hvec input"
            )
        b.check(strategy, value)
        server_state = _replace_owned_state(
            strategy, server_state, b.owner(),
            lambda owner, st: b.set_state(owner, st, float(value)),
        )
    return server_state


@contextlib.contextmanager
def bind_traced_scalars(strategy, values: dict[str, Any]):
    """Temporarily set attr-kind scalars on their owning strategy objects
    — typically to TRACERS, inside the trace of a sweep cell program, so
    the jaxpr reads them as program inputs instead of baked constants.
    Restores the original attributes on exit (also on error), so the
    strategy object is unchanged for any later trace."""
    saved: list[tuple[Any, str, Any]] = []
    try:
        for name, value in values.items():
            b = binding(name)
            if b.kind != "attr":
                raise ValueError(
                    f"{name} is a state-kind scalar; rebind it with "
                    "apply_state_scalars on the cell's server state"
                )
            owner = b.find(strategy)
            if owner is None:
                raise ValueError(
                    f"scalar {name!r} does not apply to this strategy "
                    f"chain ({'/'.join(type(s).__name__ for s in wrapper_chain(strategy))})"
                )
            saved.append((owner, b.attr, getattr(owner, b.attr)))
            setattr(owner, b.attr, value)
        yield
    finally:
        for owner, attr, old in reversed(saved):
            setattr(owner, attr, old)

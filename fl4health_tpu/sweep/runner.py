"""SweepRunner — execute a scenario grid in O(buckets) compiles.

Execution model (FedJAX's shared-compilation argument, arXiv:2108.02117):

1. ``SweepSpec.expand_cells`` materializes the grid;
   ``bucketing.plan_groups`` partitions it into PROGRAM GROUPS — cells
   that can share one compiled executable (same strategy/client/fault
   structure, same cohort bucket, same bank row budget).
2. Per group, ONE template :class:`FederatedSimulation` is built and its
   round closures (``_build_round_fns``) are wrapped into a *cell
   program*: a chunked ``lax.scan`` over rounds whose per-cell variation
   — seeds (initial states), data partitions (banks + index plans +
   sample counts), participation masks, hoisted scalars (``hvec`` +
   state leaves) — enters exclusively through PROGRAM INPUTS.
3. Cells of a group either dispatch sequentially through the one jitted
   cell program, or (``spec.pack=True``) stack along a new leading cell
   axis and run as one ``lax.scan``-over-cells dispatch per pack — the
   body is the very same cell-program closure, so packing is pure
   dispatch amortization, never semantics.

The standalone-reproduction contract: every cell's loss trajectory is
bit-identical to ``FederatedSimulation.fit()`` on the same configuration
(same seeds => same trajectory), pinned by
tests/sweep/test_sweep.py::TestParity on both execution modes. Compile accounting rides the repo's
``CompileMonitor`` (jax.monitoring backend-compile events), so the
"compiles O(buckets) not O(cells)" claim is a measured artifact (the
bench ``sweep`` block and ``fl_sweep_*`` metrics), not an assertion.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import time
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

from fl4health_tpu.clients import engine
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.observability.jaxmon import CompileMonitor
from fl4health_tpu.observability.registry import MetricsRegistry
from fl4health_tpu.server.client_manager import FullParticipationManager
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.sweep import bucketing
from fl4health_tpu.sweep.bucketing import SweepGroup, SweepPlan
from fl4health_tpu.sweep.hoisting import (
    SCALAR_BINDINGS,
    apply_state_scalars,
    bind_traced_scalars,
    binding,
)
from fl4health_tpu.sweep.spec import SweepCell, SweepSpec

logger = logging.getLogger(__name__)


@dataclasses.dataclass
class CellResult:
    """One cell's leaderboard row."""

    cell: SweepCell
    bucket: int
    group: str
    fit_losses: list[float]
    eval_losses: list[float]
    final_fit_loss: float
    final_eval_loss: float
    best_eval_loss: float
    rounds_to_target: int | None
    steps_per_s: float
    wall_s: float
    compiles_attributed: float

    def row(self) -> dict:
        """JSON-able leaderboard row (the ``sweep`` JSONL event body)."""
        return {
            "cell": self.cell.index,
            "label": self.cell.label(),
            "strategy": self.cell.strategy,
            "client": self.cell.client,
            "partitioner": self.cell.partitioner,
            "cohort": self.cell.cohort,
            "bucket": self.bucket,
            "fault": self.cell.fault,
            "manager": self.cell.manager,
            "seed": self.cell.seed,
            "scalars": dict(self.cell.scalars),
            "final_fit_loss": self.final_fit_loss,
            "final_eval_loss": self.final_eval_loss,
            "best_eval_loss": self.best_eval_loss,
            "rounds_to_target": self.rounds_to_target,
            "steps_per_s": self.steps_per_s,
            "wall_s": self.wall_s,
            "compiles_attributed": self.compiles_attributed,
        }


@dataclasses.dataclass
class SweepResult:
    """Everything a leaderboard / bench block needs.

    ``programs_compiled`` counts XLA backend compiles during CELL-PROGRAM
    DISPATCH — the executables the grid actually runs through, the number
    shape bucketing + scalar hoisting exist to amortize. One-time host
    staging warmup (per-cell state init, bank stacking: small eager ops
    each compiling once per process regardless of grid size) is reported
    separately as ``setup_compiles`` so neither number launders the
    other."""

    cells: list[CellResult]
    plan: SweepPlan
    programs_compiled: int
    compile_s_total: float
    setup_compiles: int
    setup_compile_s: float
    wall_s: float
    pack: bool
    # cells restored from a completion ledger instead of re-run (resume)
    resumed_cells: int = 0

    @property
    def cells_per_compile(self) -> float | None:
        if self.programs_compiled <= 0:
            return None
        return len(self.cells) / self.programs_compiled

    def leaderboard(self) -> list[CellResult]:
        """Cells sorted best-final-eval-loss first (NaNs last)."""
        def sort_key(r: CellResult):
            v = r.final_eval_loss
            return (not np.isfinite(v), v)
        return sorted(self.cells, key=sort_key)

    def bench_block(self) -> dict:
        """The bench artifact's ``sweep`` block — the compile-amortization
        claim as measured numbers."""
        block = {
            "cells": len(self.cells),
            "buckets": self.plan.buckets,
            "groups": len(self.plan.groups),
            "programs_compiled": self.programs_compiled,
            "compile_s_total": self.compile_s_total,
            "cells_per_compile": self.cells_per_compile,
            "setup_compiles": self.setup_compiles,
            "setup_compile_s": self.setup_compile_s,
            "wall_s": self.wall_s,
            "packed": self.pack,
        }
        if self.resumed_cells:
            # resumed grids only — fresh runs keep the legacy block shape
            block["resumed_cells"] = self.resumed_cells
        return block


def _spec_fingerprint(spec: SweepSpec, cells: list[SweepCell]) -> str:
    """Grid identity a completion ledger binds to: the fully-expanded cell
    labels (strategy/client/partitioner/cohort/fault/seed/scalars) plus
    the per-cell run shape. Factories are opaque callables, so the labels
    — not the factory objects — ARE the checkable identity; a ledger from
    a different grid must never silently skip this grid's cells."""
    from fl4health_tpu.observability.manifest import config_hash

    return config_hash({
        "cells": [c.label() for c in cells],
        "rounds": spec.rounds,
        "batch_size": spec.batch_size,
        "local_steps": spec.local_steps,
    })


class SweepLedger:
    """Crash-consistent per-cell completion ledger (append-only JSONL).

    One ``header`` line binds the file to a grid fingerprint; one ``cell``
    line per completed cell carries its full leaderboard row AND loss
    trajectories, so a resumed run reconstructs the cell's
    :class:`CellResult` without re-dispatching it. Each append is
    flush+fsync'd — a SIGKILL can tear at most the line being written,
    and ``load_completed`` skips unparseable (torn) lines, so the worst a
    crash costs is the pack in flight."""

    def __init__(self, path: str, fingerprint: str):
        self.path = path
        self.fingerprint = fingerprint
        self._fh = None

    def load_completed(self) -> dict[int, dict]:
        """{cell index: ledger row} of completed cells. Raises ValueError
        when the ledger belongs to a different grid (fingerprint mismatch)
        or carries cell rows with no verifiable header."""
        if not os.path.exists(self.path):
            return {}
        rows: dict[int, dict] = {}
        saw_header = False
        with open(self.path) as f:
            for lineno, line in enumerate(f, 1):
                line = line.strip()
                if not line:
                    continue
                try:
                    rec = json.loads(line)
                except json.JSONDecodeError:
                    # a torn tail from the killed run — the pack it
                    # described re-runs
                    logger.warning(
                        "%s:%d: skipping torn ledger line", self.path,
                        lineno,
                    )
                    continue
                kind = rec.get("kind")
                if kind == "header":
                    if rec.get("spec_hash") != self.fingerprint:
                        raise ValueError(
                            f"sweep ledger {self.path} was written for a "
                            f"different grid (spec_hash "
                            f"{rec.get('spec_hash')} != "
                            f"{self.fingerprint}); point ledger_path at a "
                            "fresh file or delete the stale ledger"
                        )
                    saw_header = True
                elif kind == "cell":
                    rows[int(rec["cell"])] = rec
        if rows and not saw_header:
            raise ValueError(
                f"sweep ledger {self.path} has cell rows but no header — "
                "not a ledger this grid can verify; delete or move it"
            )
        return rows

    def open_for_append(self) -> None:
        write_header = not os.path.exists(self.path) or os.path.getsize(
            self.path) == 0
        os.makedirs(os.path.dirname(os.path.abspath(self.path)),
                    exist_ok=True)
        self._fh = open(self.path, "a")
        if write_header:
            self._write({"kind": "header", "spec_hash": self.fingerprint,
                         "version": 1})

    def append(self, result: CellResult) -> None:
        self._write({
            "kind": "cell",
            **result.row(),
            "fit_losses": result.fit_losses,
            "eval_losses": result.eval_losses,
        })

    def _write(self, rec: dict) -> None:
        self._fh.write(json.dumps(rec) + "\n")
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class SweepRunner:
    """Execute a :class:`SweepSpec`; see the module docstring.

    ``observability``: optional armed :class:`Observability` — when
    enabled, the runner logs one ``sweep_plan`` event up front, one
    ``sweep`` event per cell (the leaderboard rows
    ``tools/perf_report.py --sweep`` renders) and one ``sweep_summary``
    event, plus ``fl_sweep_*`` registry metrics. Compile accounting uses
    the runner's own private registry/CompileMonitor either way, so the
    measured ``programs_compiled`` never depends on observability being
    on.

    ``ledger_path``: optional :class:`SweepLedger` file. Completed cells
    append to it (flush+fsync per pack) and a re-run of the same grid
    restores them instead of re-dispatching — a killed grid re-runs only
    unfinished packs, against the executables the surviving cells of each
    group already share.
    """

    def __init__(self, spec: SweepSpec, observability: Any = None,
                 ledger_path: str | None = None):
        self.spec = spec
        self.obs = observability
        self.ledger_path = ledger_path
        self._data_cache: dict[tuple[str, int], list[ClientDataset]] = {}
        # staged device banks + eval batching, keyed by everything that
        # shapes them — cells differing only in seeds/scalars reuse the
        # exact same staged arrays instead of re-stacking per cell
        self._bank_cache: dict[tuple, tuple] = {}

    # -- data ----------------------------------------------------------
    def _data_for(self, partitioner: str, cohort: int) -> list[ClientDataset]:
        key = (partitioner, cohort)
        if key not in self._data_cache:
            datasets = list(self.spec.partitioners[partitioner](cohort))
            if len(datasets) != cohort:
                raise ValueError(
                    f"partitioner {partitioner!r} returned {len(datasets)} "
                    f"datasets for cohort {cohort}"
                )
            self._data_cache[key] = datasets
        return self._data_cache[key]

    # -- group machinery ------------------------------------------------
    def _template_sim(self, group: SweepGroup) -> FederatedSimulation:
        spec, key = self.spec, group.key
        cell0 = group.cells[0]
        datasets = bucketing.pad_datasets(
            self._data_for(cell0.partitioner, cell0.cohort), key.bucket
        )
        metrics = spec.metrics() if spec.metrics is not None else (
            MetricManager(())
        )
        return FederatedSimulation(
            logic=spec.clients[key.client](),
            tx=spec.tx(),
            strategy=spec.strategies[key.strategy](),
            datasets=datasets,
            batch_size=spec.batch_size,
            metrics=metrics,
            local_steps=spec.local_steps,
            seed=cell0.seed,
            fault_plan=spec.fault_plans[key.fault],
        )

    def _group_hoisted_axes(self, sim: FederatedSimulation) -> list[str]:
        """attr-kind hoisted scalars this group's cell program takes as
        its ``hvec`` input — every applicable attr-kind binding (swept or
        not: un-swept ones ride at their defaults, so the hvec layout is
        a property of the GROUP, not of which cells sweep what)."""
        return [
            name for name, b in SCALAR_BINDINGS.items()
            if b.kind == "attr" and b.applies(sim.strategy)
        ]

    def _build_cell_program(self, sim: FederatedSimulation,
                            hoisted: list[str]):
        """The group's shared cell program: a chunked fit+eval scan over
        rounds, with sample counts and the hoisted scalars as traced
        inputs. Body math mirrors ``_make_chunked_fit_with_eval`` (minus
        telemetry/test-split), so a cell's trajectory is the standalone
        chunked ``fit()`` trajectory bit-for-bit."""
        fit_round, eval_round = sim._build_round_fns(False)
        strategy = sim.strategy

        def cell_body(cell):
            overrides = {
                name: cell["hvec"][i] for i, name in enumerate(hoisted)
            }
            with bind_traced_scalars(strategy, overrides):
                def body(carry, per_round):
                    server_state, client_states, r = carry
                    idx_r, em_r, sm_r, mask_r = per_round
                    batches = engine.gather_batches(
                        cell["x_bank"], cell["y_bank"], idx_r, em_r, sm_r
                    )
                    (server_state, client_states, fit_losses, fit_metrics,
                     _per) = fit_round(
                        server_state, client_states, batches, mask_r, r,
                        cell["val_batches"], cell["sample_counts"],
                    )
                    (client_states, ev_losses, ev_metrics, _pl,
                     _pm) = eval_round(
                        server_state, client_states, cell["val_batches"],
                        cell["val_counts"],
                    )
                    out = {
                        "fit_losses": fit_losses,
                        "fit_metrics": fit_metrics,
                        "eval_losses": ev_losses,
                        "eval_metrics": ev_metrics,
                    }
                    return (server_state, client_states, r + 1), out

                (_, _, _), outs = jax.lax.scan(
                    body,
                    (cell["server_state"], cell["client_states"],
                     jnp.asarray(1, jnp.int32)),
                    (cell["idx"], cell["em"], cell["sm"], cell["masks"]),
                )
            return outs

        def packed(cells_in):
            def body(carry, cell):
                return carry, cell_body(cell)

            _, outs = jax.lax.scan(body, 0, cells_in)
            return outs

        return jax.jit(cell_body), jax.jit(packed)

    def _staged_banks(self, cell: SweepCell, group: SweepGroup,
                      datasets: list) -> tuple:
        """Staged device banks + eval batching + count vectors for one
        cell — memoized on everything that shapes them (partitioner,
        cohort, bucket, group row budgets), so a seed/scalar sweep reuses
        the identical staged arrays instead of re-stacking them per cell.
        Safe to share across dispatches: the cell programs never donate
        their inputs."""
        spec, bucket = self.spec, group.key.bucket
        key = (cell.partitioner, cell.cohort, bucket,
               group.train_row_budget, group.val_row_budget)
        if key in self._bank_cache:
            return self._bank_cache[key]
        # data banks, padded to the group's shared row budgets
        x_bank = bucketing.pad_stack_rows(
            engine.pad_and_stack_data([d.x_train for d in datasets],
                                      "x_train"),
            group.train_row_budget,
        )
        y_bank = bucketing.pad_stack_rows(
            engine.pad_and_stack_data([d.y_train for d in datasets],
                                      "y_train"),
            group.train_row_budget,
        )
        # eval split: fixed-order full pass, padded to the group's val
        # step budget with zero-mask steps (never scored)
        ns_val = [engine.data_rows(d.x_val) for d in datasets]
        v_idx, v_em, v_sm = engine.multi_client_index_plans(
            [[0]] * bucket, ns_val, spec.batch_size, shuffle=False
        )
        val_steps = -(-group.val_row_budget // spec.batch_size)
        pad_steps = val_steps - v_idx.shape[1]
        if pad_steps > 0:
            v_idx = np.pad(v_idx, ((0, 0), (0, pad_steps), (0, 0)))
            v_em = np.pad(v_em, ((0, 0), (0, pad_steps), (0, 0)))
            v_sm = np.pad(v_sm, ((0, 0), (0, pad_steps)))
        x_val = bucketing.pad_stack_rows(
            engine.pad_and_stack_data([d.x_val for d in datasets], "x_val"),
            group.val_row_budget,
        )
        y_val = bucketing.pad_stack_rows(
            engine.pad_and_stack_data([d.y_val for d in datasets], "y_val"),
            group.val_row_budget,
        )
        val_batches = engine.gather_batches(x_val, y_val, v_idx, v_em, v_sm)
        val_counts = np.asarray(ns_val, np.float32)
        sample_counts = np.asarray(
            [d.n_train for d in datasets], np.float32
        )
        if bucket > cell.cohort:
            # phantom clients: zero aggregation weight, zero eval weight
            val_counts[cell.cohort:] = 0.0
            sample_counts[cell.cohort:] = 0.0
        staged = (x_bank, y_bank, val_batches,
                  jnp.asarray(val_counts), jnp.asarray(sample_counts))
        self._bank_cache[key] = staged
        return staged

    def _cell_inputs(self, sim: FederatedSimulation, group: SweepGroup,
                     cell: SweepCell, hoisted: list[str]) -> dict:
        """Build one cell's program inputs: re-seed the template sim's
        states exactly as a standalone construction would, stage the
        cell's padded banks/plans, and resolve scalar overrides."""
        spec, bucket = self.spec, group.key.bucket
        datasets = bucketing.pad_datasets(
            self._data_for(cell.partitioner, cell.cohort), bucket
        )
        # per-cell state init — the constructor's exact derivation
        sim.datasets = datasets
        sim.rng = jax.random.PRNGKey(cell.seed)
        sim._base_entropy = engine._entropy_from_key(sim.rng)
        sim._init_states()
        server_state = apply_state_scalars(
            sim.strategy, sim.server_state,
            {k: v for k, v in cell.scalars if binding(k).kind == "state"},
        )
        (x_bank, y_bank, val_batches, val_counts,
         sample_counts) = self._staged_banks(cell, group, datasets)
        # train plans (same PRNG-stream derivation as the standalone fit)
        plans = [sim._round_plan(r) for r in range(1, spec.rounds + 1)]
        idx = np.stack([p[0] for p in plans])
        em = np.stack([p[1] for p in plans])
        sm = np.stack([p[2] for p in plans])
        # participation: the cell's sampling manager (default: full
        # participation), drawn over the REAL cohort from the standalone
        # run's exact PRNG stream (fold_in(rng, 2000+round)), then
        # zero-padded for phantom clients — a standalone
        # FederatedSimulation(client_manager=...) run draws the same
        # masks for its real clients
        manager = (spec.client_managers[cell.manager](cell.cohort)
                   or FullParticipationManager(cell.cohort))
        if manager.n_clients != cell.cohort:
            raise ValueError(
                f"client manager {cell.manager!r} covers "
                f"{manager.n_clients} clients but the cell's cohort is "
                f"{cell.cohort}; the factory must size the manager from "
                "its cohort argument"
            )
        masks = np.stack([
            bucketing.padded_mask(
                np.asarray(manager.sample(
                    jax.random.fold_in(sim.rng, 2000 + r), r
                )),
                bucket,
            )
            for r in range(1, spec.rounds + 1)
        ])
        # hoisted attr scalars: cell overrides or the strategy's defaults
        defaults = {
            name: SCALAR_BINDINGS[name].default(sim.strategy)
            for name in hoisted
        }
        overrides = {
            k: v for k, v in cell.scalars
            if binding(k).kind == "attr"
        }
        for k, v in overrides.items():
            binding(k).check(sim.strategy, v)
        hvec = np.asarray(
            [overrides.get(name, defaults[name]) for name in hoisted],
            np.float32,
        )
        return {
            "server_state": server_state,
            "client_states": sim.client_states,
            "x_bank": x_bank,
            "y_bank": y_bank,
            "idx": jnp.asarray(idx),
            "em": jnp.asarray(em),
            "sm": jnp.asarray(sm),
            "masks": jnp.asarray(masks),
            "val_batches": val_batches,
            "val_counts": jnp.asarray(val_counts),
            "sample_counts": jnp.asarray(sample_counts),
            "hvec": jnp.asarray(hvec),
        }

    # -- execution -------------------------------------------------------
    def run(self) -> SweepResult:
        spec = self.spec
        cells = spec.expand_cells()
        plan = bucketing.plan_groups(spec, cells, self._data_for)
        obs = self.obs if (self.obs is not None
                           and getattr(self.obs, "enabled", False)) else None
        # completion ledger (resume): restore finished cells, re-run only
        # the rest — the surviving cells of each group still share its
        # compiled executables
        ledger: SweepLedger | None = None
        completed: dict[int, dict] = {}
        if self.ledger_path is not None:
            ledger = SweepLedger(self.ledger_path,
                                 _spec_fingerprint(spec, cells))
            completed = ledger.load_completed()
        cell_by_index = {c.index: c for c in cells}
        resumed = [
            self._restore_cell_result(cell_by_index[i], row)
            for i, row in sorted(completed.items())
            if i in cell_by_index
        ]
        if completed:
            logger.info(
                "sweep resume: %d/%d cells restored from %s",
                len(resumed), len(cells), self.ledger_path,
            )
        if obs is not None:
            # restored cells get their `sweep` leaderboard events too —
            # the resumed run's log must render the FULL grid, matching
            # the sweep_summary it emits (re-run cells log in _run_group)
            for r in resumed:
                obs.log_event("sweep", **r.row())
        # private compile accounting: the claim must not depend on
        # observability being configured
        registry = MetricsRegistry()
        monitor = CompileMonitor(registry).install()
        logger.info(
            "sweep: %d cells -> %d program groups (buckets %s)",
            plan.n_cells, len(plan.groups), plan.buckets,
        )
        if obs is not None:
            obs.log_event(
                "sweep_plan", **plan.describe(),
                pack=spec.pack, max_pack=spec.max_pack,
            )
        t_start = time.perf_counter()
        compiles0 = registry.counter("jax_backend_compiles_total").value
        compile_s0 = registry.counter(
            "jax_backend_compiles_seconds_total").value
        results: list[CellResult] = list(resumed)
        dispatch_compiles = 0.0
        dispatch_compile_s = 0.0
        try:
            if ledger is not None:
                ledger.open_for_append()
            for group in plan.groups:
                remaining = [c for c in group.cells
                             if c.index not in completed]
                if not remaining:
                    continue  # whole group restored — nothing to compile
                if len(remaining) < len(group.cells):
                    group = dataclasses.replace(group, cells=remaining)
                group_results, g_compiles, g_compile_s = self._run_group(
                    group, registry, obs, ledger=ledger
                )
                results.extend(group_results)
                dispatch_compiles += g_compiles
                dispatch_compile_s += g_compile_s
        finally:
            monitor.uninstall()
            if ledger is not None:
                ledger.close()
        wall_s = time.perf_counter() - t_start
        total_compiles = (
            registry.counter("jax_backend_compiles_total").value - compiles0
        )
        total_compile_s = (
            registry.counter("jax_backend_compiles_seconds_total").value
            - compile_s0
        )
        results.sort(key=lambda r: r.cell.index)
        out = SweepResult(
            cells=results, plan=plan,
            programs_compiled=int(dispatch_compiles),
            compile_s_total=dispatch_compile_s,
            setup_compiles=int(total_compiles - dispatch_compiles),
            setup_compile_s=max(0.0, total_compile_s - dispatch_compile_s),
            wall_s=wall_s, pack=spec.pack,
            resumed_cells=len(resumed),
        )
        if obs is not None:
            obs.log_event("sweep_summary", **out.bench_block())
            reg = obs.registry
            reg.counter(
                "fl_sweep_cells_total",
                help="sweep grid cells executed",
            ).inc(len(results))
            reg.gauge(
                "fl_sweep_programs_compiled",
                help="XLA backend compiles the sweep's cell dispatches "
                     "paid (shared across cells via shape bucketing + "
                     "scalar hoisting)",
            ).set(float(out.programs_compiled))
            if out.cells_per_compile is not None:
                reg.gauge(
                    "fl_sweep_cells_per_compile",
                    help="grid cells amortized per compiled program",
                ).set(float(out.cells_per_compile))
            reg.counter(
                "fl_sweep_compile_seconds_total",
                help="XLA compile seconds of the sweep's cell dispatches",
            ).inc(max(0.0, float(out.compile_s_total)))
            reg.gauge(
                "fl_sweep_wall_seconds",
                help="wall seconds of the whole sweep run",
            ).set(float(out.wall_s))
        return out

    def _run_group(self, group: SweepGroup, registry: MetricsRegistry,
                   obs, ledger: "SweepLedger | None" = None,
                   ) -> tuple[list[CellResult], float, float]:
        """Run one program group; returns (cell results, dispatch-bracket
        compile count, dispatch-bracket compile seconds). The compile
        brackets open right before each jitted cell/pack dispatch — input
        staging (per-cell state init, bank stacking: one-time eager-op
        warmup independent of grid size) is measured by the caller as
        ``setup_compiles`` instead. Each completed pack's results append
        to the ``ledger`` (when given) BEFORE the next pack dispatches,
        so a kill mid-grid re-runs only unfinished packs."""
        spec = self.spec
        sim = self._template_sim(group)
        hoisted = self._group_hoisted_axes(sim)
        cell_jit, packed_jit = self._build_cell_program(sim, hoisted)
        results: list[CellResult] = []
        t_group = time.perf_counter()
        compiles = registry.counter("jax_backend_compiles_total")
        compile_s = registry.counter("jax_backend_compiles_seconds_total")
        group_compiles = group_compile_s = 0.0

        def finish(cell, cell_outs, wall, attributed):
            r = self._cell_result(group, cell, cell_outs, wall, attributed)
            results.append(r)
            if ledger is not None:
                ledger.append(r)
            return r

        # inputs are staged one PACK at a time (not the whole group): a
        # cell's inputs hold full padded data banks, so group-wide staging
        # would scale device memory with the grid instead of the pack
        if spec.pack:
            # ONE pack size per group: the remainder chunk pads to the
            # group's pack size by repeating its first cell (duplicate
            # outputs discarded) — a little redundant compute instead of
            # a second multi-second XLA compile for the odd shape
            pack_size = min(spec.max_pack, len(group.cells))
            for i in range(0, len(group.cells), pack_size):
                chunk = group.cells[i:i + pack_size]
                inputs = [self._cell_inputs(sim, group, cell, hoisted)
                          for cell in chunk]
                inputs += [inputs[0]] * (pack_size - len(chunk))
                stacked = jax.tree_util.tree_map(
                    lambda *xs: jnp.stack(xs), *inputs
                ) if len(inputs) > 1 else jax.tree_util.tree_map(
                    lambda x: jnp.expand_dims(x, 0), inputs[0]
                )
                del inputs
                jax.block_until_ready(stacked)
                c0, s0 = compiles.value, compile_s.value
                t0 = time.perf_counter()
                outs = packed_jit(stacked)
                outs = jax.device_get(jax.block_until_ready(outs))
                wall = time.perf_counter() - t0
                del stacked
                pack_compiles = compiles.value - c0
                pack_compile_s = compile_s.value - s0
                group_compiles += pack_compiles
                group_compile_s += pack_compile_s
                # honest per-cell wall: the first dispatch's XLA compile
                # lands in compile_s_total, never in throughput numbers
                per_cell_wall = max(wall - pack_compile_s, 0.0) / len(chunk)
                attributed = pack_compiles / len(chunk)
                for j, cell in enumerate(chunk):
                    cell_outs = jax.tree_util.tree_map(
                        lambda a: a[j], outs
                    )
                    finish(cell, cell_outs, per_cell_wall, attributed)
        else:
            for cell in group.cells:
                inp = self._cell_inputs(sim, group, cell, hoisted)
                jax.block_until_ready(inp)
                c0, s0 = compiles.value, compile_s.value
                t0 = time.perf_counter()
                outs = cell_jit(inp)
                outs = jax.device_get(jax.block_until_ready(outs))
                wall = time.perf_counter() - t0
                cell_compiles = compiles.value - c0
                cell_compile_s = compile_s.value - s0
                del inp
                group_compiles += cell_compiles
                group_compile_s += cell_compile_s
                finish(cell, outs, max(wall - cell_compile_s, 0.0),
                       cell_compiles)
        if obs is not None:
            for r in results:
                obs.log_event("sweep", **r.row())
        logger.info(
            "sweep group %s: %d cells, %d program compiles, %.2fs",
            group.key.label(), len(group.cells), int(group_compiles),
            time.perf_counter() - t_group,
        )
        return results, group_compiles, group_compile_s

    def _restore_cell_result(self, cell: SweepCell, row: dict) -> CellResult:
        """Rebuild a completed cell's :class:`CellResult` from its ledger
        row — the resume path's no-recompute restore."""
        if row.get("label") != cell.label():
            # the spec fingerprint should make this unreachable; fail loud
            # rather than attribute a stale trajectory to the wrong cell
            raise ValueError(
                f"ledger row for cell {cell.index} is labeled "
                f"{row.get('label')!r} but the grid expands it as "
                f"{cell.label()!r}"
            )
        return CellResult(
            cell=cell,
            bucket=int(row.get("bucket", cell.cohort)),
            group=str(row.get("group", "")),
            fit_losses=[float(v) for v in row.get("fit_losses", [])],
            eval_losses=[float(v) for v in row.get("eval_losses", [])],
            final_fit_loss=float(row.get("final_fit_loss", float("nan"))),
            final_eval_loss=float(row.get("final_eval_loss", float("nan"))),
            best_eval_loss=float(row.get("best_eval_loss", float("nan"))),
            rounds_to_target=row.get("rounds_to_target"),
            steps_per_s=float(row.get("steps_per_s", 0.0)),
            wall_s=float(row.get("wall_s", 0.0)),
            compiles_attributed=float(row.get("compiles_attributed", 0.0)),
        )

    def _cell_result(self, group: SweepGroup, cell: SweepCell, outs: dict,
                     wall: float, compiles_attributed: float) -> CellResult:
        spec = self.spec
        fit_traj = [float(v) for v in
                    np.asarray(outs["fit_losses"]["backward"])]
        eval_traj = [float(v) for v in
                     np.asarray(outs["eval_losses"]["checkpoint"])]
        finite = [v for v in eval_traj if np.isfinite(v)]
        best = min(finite) if finite else float("nan")
        rtt = None
        if spec.target_eval_loss is not None:
            for i, v in enumerate(eval_traj):
                if np.isfinite(v) and v <= spec.target_eval_loss:
                    rtt = i + 1
                    break
        steps = spec.rounds * spec.local_steps * cell.cohort
        return CellResult(
            cell=cell,
            bucket=group.key.bucket,
            group=group.key.label(),
            fit_losses=fit_traj,
            eval_losses=eval_traj,
            final_fit_loss=fit_traj[-1],
            final_eval_loss=eval_traj[-1],
            best_eval_loss=best,
            rounds_to_target=rtt,
            steps_per_s=steps / wall if wall > 0 else 0.0,
            wall_s=wall,
            compiles_attributed=compiles_attributed,
        )


def run_sweep(spec: SweepSpec, observability: Any = None,
              ledger_path: str | None = None) -> SweepResult:
    """Convenience one-shot:
    ``SweepRunner(spec, observability, ledger_path).run()``."""
    return SweepRunner(spec, observability, ledger_path=ledger_path).run()

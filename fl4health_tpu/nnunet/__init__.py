"""nnU-Net-class federated 3D segmentation, TPU-native.

Replaces the reference's nnunetv2/MONAI integration
(/root/reference/fl4health/clients/nnunet_client.py,
servers/nnunet_server.py, utils/nnunet_utils.py) with a self-contained
stack: numpy experiment planner + fingerprint (plans.py), host-side
normalization/patching (data.py), a flax plain-conv U-Net with deep
supervision (models/unet.py), masked multi-scale Dice+CE
(losses/segmentation.py), and the plans-negotiation protocol
(clients/nnunet.py + server/nnunet.py).
"""

from fl4health_tpu.nnunet.augment import augment_patch_batch
from fl4health_tpu.nnunet.data import (
    extract_patch_dataset,
    make_patch_resampler,
    normalize_volume,
)
from fl4health_tpu.nnunet.inference import (
    gaussian_importance_map,
    sliding_window_predict,
)
from fl4health_tpu.nnunet.plans import (
    default_configuration,
    extract_fingerprint,
    generate_plans,
    localize_plans,
    nnunet_optimizer,
    plans_from_bytes,
    plans_to_bytes,
    poly_lr_schedule,
)

__all__ = [
    "default_configuration",
    "extract_fingerprint",
    "generate_plans",
    "localize_plans",
    "nnunet_optimizer",
    "plans_from_bytes",
    "plans_to_bytes",
    "poly_lr_schedule",
    "augment_patch_batch",
    "extract_patch_dataset",
    "make_patch_resampler",
    "normalize_volume",
    "gaussian_importance_map",
    "sliding_window_predict",
]

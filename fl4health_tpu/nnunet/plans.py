"""nnU-Net-style experiment planning — fingerprint, plans, polyLR.

Parity surface (/root/reference/fl4health/clients/nnunet_client.py:388
``create_plans``, :521 ``maybe_extract_fingerprint``;
/root/reference/fl4health/utils/nnunet_utils.py:491 ``PolyLRSchedulerWrapper``):
the reference drives nnunetv2's ExperimentPlanner + fingerprint extractor on
the client's local dataset, then ships the resulting plans dict (pickled
bytes) to the server during the pre-round-1 ``get_properties`` handshake.

TPU-native re-design: the planner is re-derived from the published nnU-Net
heuristics as pure numpy (no nnunetv2 dependency), and plans serialize as
JSON bytes (never pickle — the wire must not execute code). The heuristics
kept are the ones that matter for a compiled SPMD trainer:

- target spacing  = per-axis median of dataset spacings,
- patch size      = median resampled shape, shrunk to a voxel budget and
                    rounded so every axis divides by its pooling factor
                    (XLA needs static, tileable shapes — this rounding is
                    load-bearing here, not cosmetic),
- pooling depth   = halve each axis while it stays >= 2*min_axis_extent,
                    capped at ``max_stages`` total stages,
- features        = base * 2^stage, capped (320 for 3D, 512 for 2D),
- batch size      = >= 2, capped at 5% of the dataset's voxels,
- normalization   = z-score with 0.5/99.5 percentile clipping from the
                    foreground intensity fingerprint.
"""

from __future__ import annotations

import json
from typing import Any, Sequence

import numpy as np

DEFAULT_MAX_FEATURES_3D = 320
DEFAULT_MAX_FEATURES_2D = 512
DEFAULT_BASE_FEATURES = 32


# ---------------------------------------------------------------------------
# Fingerprint
# ---------------------------------------------------------------------------

def extract_fingerprint(
    volumes: Sequence[np.ndarray],
    spacings: Sequence[Sequence[float]],
    segmentations: Sequence[np.ndarray] | None = None,
    foreground_label_threshold: int = 1,
) -> dict[str, Any]:
    """Dataset fingerprint (the nnU-Net ``dataset_fingerprint.json``
    equivalent, nnunet_client.py:521): per-case spatial shapes + spacings and
    foreground intensity statistics per channel.

    ``volumes`` are channels-last arrays ``[*spatial, C]``; ``segmentations``
    (optional) are integer maps ``[*spatial]`` used to restrict intensity
    stats to foreground voxels (labels >= ``foreground_label_threshold``).
    Without segmentations, nonzero-intensity voxels stand in for foreground.
    """
    if not volumes:
        raise ValueError("fingerprint needs at least one volume")
    n_channels = int(volumes[0].shape[-1])
    ndim = volumes[0].ndim - 1
    shapes = [tuple(int(s) for s in v.shape[:-1]) for v in volumes]
    spacings_out = [tuple(float(s) for s in sp) for sp in spacings]
    if any(len(sp) != ndim for sp in spacings_out):
        raise ValueError("spacing rank must match volume spatial rank")

    per_channel: dict[str, dict[str, float]] = {}
    for c in range(n_channels):
        samples = []
        for i, v in enumerate(volumes):
            chan = np.asarray(v[..., c], np.float64)
            if segmentations is not None:
                fg = np.asarray(segmentations[i]) >= foreground_label_threshold
            else:
                fg = chan != 0
            vals = chan[fg]
            if vals.size:
                samples.append(vals)
        allv = np.concatenate(samples) if samples else np.zeros((1,))
        per_channel[str(c)] = {
            "mean": float(allv.mean()),
            "std": float(allv.std() + 1e-8),
            "min": float(allv.min()),
            "max": float(allv.max()),
            "percentile_00_5": float(np.percentile(allv, 0.5)),
            "percentile_99_5": float(np.percentile(allv, 99.5)),
        }
    return {
        "shapes": [list(s) for s in shapes],
        "spacings": [list(s) for s in spacings_out],
        "num_channels": n_channels,
        "num_cases": len(volumes),
        "foreground_intensity_properties_per_channel": per_channel,
    }


# ---------------------------------------------------------------------------
# Planning
# ---------------------------------------------------------------------------

def _pooling_per_axis(
    patch: np.ndarray, max_stages: int, min_axis_extent: int = 4
) -> list[list[int]]:
    """Per-stage stride vectors: halve every axis that can still afford it.

    Stage 0 has stride 1 (no pooling before the first conv block), matching
    the plain-conv U-Net convention; subsequent stages carry per-axis stride
    2 while the running extent stays >= 2*min_axis_extent.
    """
    extents = patch.astype(np.float64).copy()
    strides = [[1] * len(patch)]
    for _ in range(max_stages - 1):
        stride = []
        for a in range(len(patch)):
            if extents[a] >= 2 * min_axis_extent:
                stride.append(2)
                extents[a] /= 2
            else:
                stride.append(1)
        if all(s == 1 for s in stride):
            break
        strides.append(stride)
    return strides


def _round_to_divisible(patch: np.ndarray, strides: list[list[int]]) -> np.ndarray:
    """Shrink each axis to the largest multiple of its total pooling factor."""
    factor = np.prod(np.asarray(strides), axis=0)
    rounded = (patch // factor) * factor
    return np.maximum(rounded, factor)


def generate_plans(
    fingerprint: dict[str, Any],
    dataset_name: str = "Dataset000",
    plans_name: str = "fl4health_tpu_plans",
    configuration: str | None = None,
    max_patch_voxels: int | None = None,
    max_stages: int = 6,
    base_features: int = DEFAULT_BASE_FEATURES,
    batch_size_cap_fraction: float = 0.05,
) -> dict[str, Any]:
    """Build a plans dict from a fingerprint (ExperimentPlanner equivalent).

    ``configuration`` defaults to "3d_fullres" for 3-D data and "2d" for 2-D.
    ``max_patch_voxels`` bounds patch memory (default: 128^3 for 3-D, 512^2
    for 2-D — the published nnU-Net defaults' order of magnitude).
    """
    shapes = np.asarray(fingerprint["shapes"], np.float64)
    spacings = np.asarray(fingerprint["spacings"], np.float64)
    ndim = shapes.shape[1]
    if configuration is None:
        configuration = "3d_fullres" if ndim == 3 else "2d"
    if max_patch_voxels is None:
        max_patch_voxels = 128**3 if ndim == 3 else 512**2

    target_spacing = np.median(spacings, axis=0)
    # Shapes resampled into the target spacing grid.
    resampled = shapes * spacings / target_spacing
    median_resampled = np.median(resampled, axis=0)

    patch = np.maximum(np.round(median_resampled).astype(np.int64), 4)
    # Shrink the largest axis until the voxel budget holds (keeps aspect
    # close to the median shape, the nnU-Net approach to memory budgeting).
    while np.prod(patch) > max_patch_voxels:
        patch[np.argmax(patch)] = int(patch[np.argmax(patch)] * 0.9)
    strides = _pooling_per_axis(patch, max_stages)
    patch = _round_to_divisible(patch, strides)
    n_stages = len(strides)

    max_features = DEFAULT_MAX_FEATURES_3D if ndim == 3 else DEFAULT_MAX_FEATURES_2D
    features = [min(base_features * (2**i), max_features) for i in range(n_stages)]
    kernel_sizes = [[3] * ndim for _ in range(n_stages)]

    # Batch cannot exceed `batch_size_cap_fraction` of the dataset's voxels
    # (nnunet_client.py:455 "a batch cannot contain more than 5% of the
    # voxels in the dataset").
    dataset_voxels = float(np.prod(np.median(resampled, axis=0))) * max(
        int(fingerprint.get("num_cases", 1)), 1
    )
    patch_voxels = float(np.prod(patch))
    batch_size = max(2, int(dataset_voxels * batch_size_cap_fraction / patch_voxels))
    batch_size = min(batch_size, 32)

    return {
        "plans_name": plans_name,
        "dataset_name": dataset_name,
        "original_median_shape_after_transp": [int(round(s)) for s in np.median(shapes, axis=0)],
        "original_median_spacing_after_transp": [float(s) for s in np.median(spacings, axis=0)],
        "foreground_intensity_properties_per_channel": fingerprint[
            "foreground_intensity_properties_per_channel"
        ],
        "configurations": {
            configuration: {
                "data_identifier": f"{plans_name}_{configuration}",
                "spacing": [float(s) for s in target_spacing],
                "patch_size": [int(p) for p in patch],
                "batch_size": int(batch_size),
                "median_image_size_in_voxels": [float(s) for s in median_resampled],
                "n_stages": n_stages,
                "features_per_stage": features,
                "strides": [list(map(int, s)) for s in strides],
                "kernel_sizes": kernel_sizes,
                "n_conv_per_stage": 2,
                "normalization_schemes": ["ZScoreClipped"]
                * int(fingerprint["num_channels"]),
            }
        },
    }


def localize_plans(
    plans: dict[str, Any],
    fingerprint: dict[str, Any],
    dataset_name: str,
    configuration: str | None = None,
) -> dict[str, Any]:
    """Client-side plans adaptation (``create_plans``, nnunet_client.py:388):
    keep the *global* architecture/patch/spacing decisions, swap in the LOCAL
    dataset's identity, median shape/spacing, and foreground intensity stats
    so normalization reflects the client's own distribution."""
    out = json.loads(json.dumps(plans))  # deep copy via round-trip
    out["source_plans_name"] = plans["plans_name"]
    out["plans_name"] = f"FL-{plans['plans_name']}-{dataset_name}"
    out["dataset_name"] = dataset_name
    shapes = np.asarray(fingerprint["shapes"], np.float64)
    spacings = np.asarray(fingerprint["spacings"], np.float64)
    out["original_median_shape_after_transp"] = [
        int(round(s)) for s in np.median(shapes, axis=0)
    ]
    out["original_median_spacing_after_transp"] = [
        float(s) for s in np.median(spacings, axis=0)
    ]
    out["foreground_intensity_properties_per_channel"] = fingerprint[
        "foreground_intensity_properties_per_channel"
    ]
    if configuration is None:
        configuration = default_configuration(out)
    cfg = out["configurations"][configuration]
    cfg["data_identifier"] = out["plans_name"]
    return out


def default_configuration(plans: dict[str, Any]) -> str:
    """Pick the configuration a plans dict describes, preferring 3d_fullres
    (the reference's fullres-first rule, nnunet_client.py:446)."""
    configs = plans["configurations"]
    if "3d_fullres" in configs:
        return "3d_fullres"
    return next(iter(configs))


# ---------------------------------------------------------------------------
# Wire format — JSON bytes, never pickle
# ---------------------------------------------------------------------------

def plans_to_bytes(plans: dict[str, Any]) -> bytes:
    return json.dumps(plans, sort_keys=True).encode("utf-8")


def plans_from_bytes(data: bytes) -> dict[str, Any]:
    return json.loads(data.decode("utf-8"))


# ---------------------------------------------------------------------------
# PolyLR (utils/nnunet_utils.py:491 PolyLRSchedulerWrapper)
# ---------------------------------------------------------------------------

def poly_lr_schedule(initial_lr: float, max_steps: int, exponent: float = 0.9):
    """lr(step) = initial * (1 - step/max_steps)^exponent — the nnU-Net
    default schedule, as an optax-compatible schedule function."""
    import jax.numpy as jnp

    def schedule(step):
        frac = jnp.clip(step / max_steps, 0.0, 1.0)
        return initial_lr * (1.0 - frac) ** exponent

    return schedule


def nnunet_optimizer(
    initial_lr: float = 1e-2,
    max_steps: int = 1000,
    momentum: float = 0.99,
    weight_decay: float = 3e-5,
    grad_clip_norm: float = 12.0,
):
    """The nnU-Net training recipe as one optax chain: global-norm clip 12
    (nnunet_client.py:214 train_step), SGD + Nesterov momentum 0.99, polyLR
    (nnunet_client.py:334,338)."""
    import optax

    return optax.chain(
        optax.clip_by_global_norm(grad_clip_norm),
        optax.add_decayed_weights(weight_decay),
        optax.sgd(
            learning_rate=poly_lr_schedule(initial_lr, max_steps),
            momentum=momentum,
            nesterov=True,
        ),
    )

"""On-device nnU-Net-style data augmentation — jittable, per-step, per-example.

Parity surface: the reference trains through nnunetv2's multiprocess augmenter
pipeline (/root/reference/fl4health/utils/nnunet_utils.py:307
``NnUNetDataLoaderWrapper`` wrapping the nnU-Net default transforms: spatial
mirroring/rotation, Gaussian noise, brightness, contrast, gamma). Those
augmenters are regularization — they change what the model converges to, not
just how fast batches arrive — so a TPU port must keep them.

TPU-native design: instead of CPU worker processes mutating numpy batches, the
transforms are pure jax ops applied *inside* the compiled training scan, keyed
per step and per example. That makes augmentation free of host round-trips,
reproducible from the PRNG stream, and fused by XLA into the forward pass.
The spatial family has two tiers: grid-exact transforms (axis mirrors +
90-degree rotations on isotropic axis pairs) and the interpolating family
below; everything intensity-side (noise/brightness/contrast/gamma) matches
the nnU-Net family directly.

Default probabilities follow nnunetv2's defaults: noise p=0.1 (variance-
uniform), brightness p=0.15, contrast p=0.15, gamma p=0.3 (retain_stats)
+ invert-image gamma p=0.1, mirror p=0.5 per axis, free-angle rotation
(±30°) p=0.2, random scaling (0.7–1.4) p=0.2. The interpolating transforms
(rotation/scaling, optional elastic) are resamples of the FIXED patch grid —
``jax.scipy.ndimage.map_coordinates`` with order-1 gathers for image
channels and order-0 (nearest) for labels — so shapes stay static and the
whole family compiles into the training scan. Out-of-bounds voxels use edge
replication (mode="nearest") for both image and label rather than
nnunetv2's constant-fill with a -1 ignore label: this keeps every label
valid and avoids threading new ignore-index semantics through the loss
stack (documented deviation). Low-resolution simulation (nearest-downsample by a random zoom, cubic
upsample back — batchgenerators' SimulateLowResolutionTransform with
order_down=0/order_up=3, p=0.25) keeps static shapes by drawing the zoom
from a small static set via ``lax.switch``; Gaussian blur is a separable
fixed-tap kernel. Remaining deviation, by design: elastic deformation
defaults OFF (matching nnunetv2, whose default pipeline sets
do_elastic=False) but is available via p_elastic.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp


def _bernoulli(key, p):
    return jax.random.uniform(key) < p


def _mirror_one(x, y, key, spatial_axes, p):
    """Flip each spatial axis independently w.p. ``p`` (x and y together).
    Per-example layout: x [*spatial, C], y [*spatial] — spatial axis indices
    coincide."""
    for i, ax in enumerate(spatial_axes):
        do = _bernoulli(jax.random.fold_in(key, i), p)
        x = jnp.where(do, jnp.flip(x, axis=ax), x)
        y = jnp.where(do, jnp.flip(y, axis=ax), y)
    return x, y


def _rot90_one(x, y, key, pairs, p):
    """One random 90-degree rotation (k in 1..3) on a random isotropic axis
    pair, w.p. ``p``. ``pairs`` lists spatial axis pairs (x-indexed) whose
    sizes are equal, so every branch preserves the static shape."""
    if not pairs:
        return x, y

    def rotated(k, xx, yy, ax):
        return (jnp.rot90(xx, k=k, axes=ax), jnp.rot90(yy, k=k, axes=ax))

    do = _bernoulli(jax.random.fold_in(key, 0), p)
    pair_idx = jax.random.randint(jax.random.fold_in(key, 1), (), 0, len(pairs))
    k = jax.random.randint(jax.random.fold_in(key, 2), (), 1, 4)
    branches_x, branches_y = [], []
    for ax in pairs:
        for kk in (1, 2, 3):
            bx, by = rotated(kk, x, y, ax)
            branches_x.append(bx)
            branches_y.append(by)
    sel = pair_idx * 3 + (k - 1)
    rx = jax.lax.switch(sel, [lambda b=b: b for b in branches_x])
    ry = jax.lax.switch(sel, [lambda b=b: b for b in branches_y])
    return jnp.where(do, rx, x), jnp.where(do, ry, y)


def _noise_one(x, key, p, variance_max):
    """Additive Gaussian noise, variance ~ U(0, variance_max) — nnU-Net's
    GaussianNoiseTransform draws the VARIANCE uniformly (sigma = sqrt(var)),
    not sigma itself."""
    do = _bernoulli(jax.random.fold_in(key, 0), p)
    var = jax.random.uniform(jax.random.fold_in(key, 1), (), minval=0.0,
                             maxval=variance_max)
    noise = jnp.sqrt(var) * jax.random.normal(
        jax.random.fold_in(key, 2), x.shape, x.dtype
    )
    return jnp.where(do, x + noise, x)


def _blur_one(x, key, p, sigma_lo=0.5, sigma_hi=1.0, radius=2):
    """Separable Gaussian blur, sigma ~ U(sigma_lo, sigma_hi) — nnU-Net's
    GaussianBlurTransform (p=0.2). Fixed 2*radius+1 tap kernel (radius 2
    covers 2 sigma at the range's top), edge padding."""
    do = _bernoulli(jax.random.fold_in(key, 0), p)
    sigma = jax.random.uniform(jax.random.fold_in(key, 1), (),
                               minval=sigma_lo, maxval=sigma_hi)
    offs = jnp.arange(-radius, radius + 1, dtype=jnp.float32)
    w = jnp.exp(-0.5 * jnp.square(offs / sigma))
    w = w / jnp.sum(w)
    out = x
    for ax in range(x.ndim - 1):  # spatial axes ([*spatial, C] layout)
        widths = [(0, 0)] * x.ndim
        widths[ax] = (radius, radius)
        xp = jnp.pad(out, widths, mode="edge")
        acc = jnp.zeros_like(out, dtype=jnp.float32)
        for i in range(2 * radius + 1):
            sl = [slice(None)] * x.ndim
            sl[ax] = slice(i, i + x.shape[ax])
            acc = acc + w[i] * xp[tuple(sl)].astype(jnp.float32)
        out = acc
    return jnp.where(do, out.astype(x.dtype), x)


def _brightness_one(x, key, p, lo, hi):
    do = _bernoulli(jax.random.fold_in(key, 0), p)
    mult = jax.random.uniform(jax.random.fold_in(key, 1), (), minval=lo,
                              maxval=hi)
    return jnp.where(do, x * mult, x)


def _contrast_one(x, key, p, lo, hi):
    """Scale around the per-channel mean, preserving range (nnU-Net's
    ContrastAugmentationTransform with preserve_range=True)."""
    do = _bernoulli(jax.random.fold_in(key, 0), p)
    factor = jax.random.uniform(jax.random.fold_in(key, 1), (), minval=lo,
                                maxval=hi)
    spatial = tuple(range(x.ndim - 1))
    mean = jnp.mean(x, axis=spatial, keepdims=True)
    mn = jnp.min(x, axis=spatial, keepdims=True)
    mx = jnp.max(x, axis=spatial, keepdims=True)
    scaled = jnp.clip(mean + (x - mean) * factor, mn, mx)
    return jnp.where(do, scaled, x)


def _gamma_one(x, key, p, lo, hi, invert):
    """Gamma on the patch rescaled to [0,1] per channel, mapped back, with
    the per-channel mean/std restored afterwards (nnU-Net's GammaTransform
    with retain_stats=True — without restoration, gamma shifts the z-scored
    statistics the normalization established). ``invert`` selects the
    invert_image=True variant (gamma applied to the negated image)."""
    do = _bernoulli(jax.random.fold_in(key, 0), p)
    gamma = jax.random.uniform(jax.random.fold_in(key, 1), (), minval=lo,
                               maxval=hi)
    spatial = tuple(range(x.ndim - 1))
    mean0 = jnp.mean(x, axis=spatial, keepdims=True)
    std0 = jnp.std(x, axis=spatial, keepdims=True)
    xin = -x if invert else x
    mn = jnp.min(xin, axis=spatial, keepdims=True)
    mx = jnp.max(xin, axis=spatial, keepdims=True)
    rng_ = jnp.maximum(mx - mn, 1e-7)
    unit = (xin - mn) / rng_
    out = jnp.power(jnp.maximum(unit, 1e-7), gamma) * rng_ + mn
    if invert:
        out = -out
    # retain_stats: restore the pre-transform per-channel mean/std
    mean1 = jnp.mean(out, axis=spatial, keepdims=True)
    std1 = jnp.std(out, axis=spatial, keepdims=True)
    out = (out - mean1) / jnp.maximum(std1, 1e-7) * std0 + mean0
    return jnp.where(do, out, x)


def _rotation_matrix(angles: jax.Array, nd: int) -> jax.Array:
    """[nd, nd] rotation from ``angles``: one angle for 2-D, three per-axis
    angles composed Rz @ Ry @ Rx for 3-D (the batchgenerators convention —
    each axis rotation drawn independently)."""
    c, s = jnp.cos(angles), jnp.sin(angles)
    if nd == 2:
        return jnp.array([[c[0], -s[0]], [s[0], c[0]]])
    rx = jnp.array([
        [1.0, 0.0, 0.0],
        [0.0, c[0], -s[0]],
        [0.0, s[0], c[0]],
    ])
    ry = jnp.array([
        [c[1], 0.0, s[1]],
        [0.0, 1.0, 0.0],
        [-s[1], 0.0, c[1]],
    ])
    rz = jnp.array([
        [c[2], -s[2], 0.0],
        [s[2], c[2], 0.0],
        [0.0, 0.0, 1.0],
    ])
    return rz @ ry @ rx


def _spatial_resample_one(
    x: jax.Array,
    y: jax.Array,
    key: jax.Array,
    p_rotation: float,
    p_scaling: float,
    rot_max_rad: float,
    scale_lo: float,
    scale_hi: float,
    p_elastic: float,
    elastic_alpha: float,
) -> tuple[jax.Array, jax.Array]:
    """Free-angle rotation + isotropic scaling (+ optional elastic) of one
    example via a single resampling gather on the fixed patch grid.

    x [*spatial, C] float, y [*spatial] int. Output voxel p samples input at
    ``center + s·R·(p − center) (+ elastic displacement)``: image channels
    bilinear (order=1), labels nearest (order=0) so no new label values can
    appear. When neither transform fires the coordinates are exact integers
    and both interpolators return the input bit-exactly; a final ``where``
    guards against float round-off anyway.
    """
    from jax.scipy.ndimage import map_coordinates

    spatial = y.shape
    nd = len(spatial)
    do_rot = _bernoulli(jax.random.fold_in(key, 0), p_rotation)
    do_scale = _bernoulli(jax.random.fold_in(key, 1), p_scaling)
    n_angles = 1 if nd == 2 else 3
    angles = jax.random.uniform(
        jax.random.fold_in(key, 2), (n_angles,),
        minval=-rot_max_rad, maxval=rot_max_rad,
    ) * do_rot
    scale = jnp.where(
        do_scale,
        jax.random.uniform(jax.random.fold_in(key, 3), (),
                           minval=scale_lo, maxval=scale_hi),
        1.0,
    )
    rot = _rotation_matrix(angles, nd)

    center = jnp.array([(s - 1) / 2.0 for s in spatial])
    grid = jnp.stack(
        jnp.meshgrid(*[jnp.arange(s, dtype=jnp.float32) for s in spatial],
                     indexing="ij")
    )  # [nd, *spatial]
    rel = grid - center.reshape((nd,) + (1,) * nd)
    mapped = scale * jnp.tensordot(rot, rel, axes=1) \
        + center.reshape((nd,) + (1,) * nd)

    do_elastic = _bernoulli(jax.random.fold_in(key, 4), p_elastic)
    if p_elastic > 0.0:
        # Coarse per-axis displacement noise upsampled to the patch — the
        # smooth random field of batchgenerators' elastic_deform, built from
        # a 4^nd grid instead of a gaussian-filtered dense field (cheaper,
        # same low-frequency character). Amplitude ~ U(0, elastic_alpha)
        # voxels.
        coarse = jax.random.normal(
            jax.random.fold_in(key, 5), (nd,) + (4,) * nd, jnp.float32
        )
        alpha = jax.random.uniform(
            jax.random.fold_in(key, 6), (), minval=0.0, maxval=elastic_alpha
        )
        disp = jax.image.resize(coarse, (nd, *spatial), method="linear")
        mapped = mapped + do_elastic * alpha * disp

    coords = [mapped[i] for i in range(nd)]
    x_out = jnp.stack(
        [
            map_coordinates(x[..., c], coords, order=1, mode="nearest")
            for c in range(x.shape[-1])
        ],
        axis=-1,
    ).astype(x.dtype)
    y_out = map_coordinates(y, coords, order=0, mode="nearest").astype(y.dtype)
    fired = do_rot | do_scale | (do_elastic if p_elastic > 0.0 else False)
    return (
        jnp.where(fired, x_out, x),
        jnp.where(fired, y_out, y),
    )


# Static zoom choices for low-res simulation: lax.switch needs static
# intermediate shapes, so the continuous U(0.5, 1) draw becomes a uniform
# choice over this set (covering batchgenerators' U(0.5, 1) range incl. the
# mild top end).
_LOWRES_ZOOMS = (0.5, 0.65, 0.8, 0.95)


def _lowres_one(x, key, p):
    """SimulateLowResolutionTransform: nearest-downsample by a random zoom,
    cubic-upsample back to the patch grid (order_down=0 / order_up=3).
    x-only (labels keep full resolution, as in batchgenerators)."""
    do = _bernoulli(jax.random.fold_in(key, 0), p)
    zi = jax.random.randint(jax.random.fold_in(key, 1), (), 0,
                            len(_LOWRES_ZOOMS))
    spatial = x.shape[:-1]

    def branch(z):
        small = tuple(max(int(round(s * z)), 1) for s in spatial)
        down = jax.image.resize(x, small + (x.shape[-1],), method="nearest")
        return jax.image.resize(down, x.shape, method="cubic").astype(x.dtype)

    out = jax.lax.switch(zi, [lambda z=z: branch(z) for z in _LOWRES_ZOOMS])
    return jnp.where(do, out, x)


def _isotropic_pairs(spatial_shape: Sequence[int]) -> tuple:
    """Spatial axis pairs (as x-array axes, i.e. offset by 0 for the leading
    per-example layout [*spatial, C]) with equal sizes."""
    pairs = []
    nd = len(spatial_shape)
    for i in range(nd):
        for j in range(i + 1, nd):
            if spatial_shape[i] == spatial_shape[j]:
                pairs.append((i, j))
    return tuple(pairs)


@functools.partial(
    jax.jit,
    static_argnames=("p_mirror", "p_rot90", "p_noise", "p_brightness",
                     "p_contrast", "p_gamma", "p_gamma_invert",
                     "p_rotation", "p_scaling", "rot_max_deg",
                     "scale_lo", "scale_hi", "p_elastic", "elastic_alpha",
                     "p_lowres", "p_blur"),
)
def augment_patch_batch(
    x: jax.Array,
    y: jax.Array,
    rng: jax.Array,
    p_mirror: float = 0.5,
    p_rot90: float = 0.5,
    p_noise: float = 0.1,
    p_brightness: float = 0.15,
    p_contrast: float = 0.15,
    p_gamma: float = 0.3,
    p_gamma_invert: float = 0.1,
    p_rotation: float = 0.2,
    p_scaling: float = 0.2,
    rot_max_deg: float = 30.0,
    scale_lo: float = 0.7,
    scale_hi: float = 1.4,
    p_elastic: float = 0.0,
    elastic_alpha: float = 8.0,
    p_lowres: float = 0.25,
    p_blur: float = 0.2,
) -> tuple[jax.Array, jax.Array]:
    """Augment one batch: x [B, *spatial, C] float, y [B, *spatial] int.

    Spatial transforms (free-angle rotation ±rot_max_deg at p_rotation,
    isotropic scaling scale_lo–scale_hi at p_scaling, optional elastic,
    mirror, rot90 on equal-size axis pairs) apply to x and y together;
    intensity transforms (noise, brightness, contrast, two gamma variants)
    to x only. Every decision is drawn per example from ``rng``. Matches
    nnunetv2's defaults: rotation ±30° p=0.2, scaling (0.7, 1.4) p=0.2
    (interpolating transforms lead the pipeline, as in nnunetv2's
    SpatialTransform), noise VARIANCE ~ U(0, 0.1) at p=0.1,
    brightness/contrast (0.75, 1.25) at p=0.15, low-res simulation at
    p=0.25, gamma (0.7, 1.5) with retain_stats at p=0.3 plus the separate
    invert-image gamma at p=0.1; elastic defaults off as in nnunetv2.
    """
    spatial = x.shape[1:-1]
    pairs = _isotropic_pairs(spatial)
    spatial_axes = tuple(range(len(spatial)))  # per-example x axes, pre-C
    interp_on = p_rotation > 0.0 or p_scaling > 0.0 or p_elastic > 0.0

    def one(xe, ye, key):
        keys = jax.random.split(key, 10)
        if interp_on:  # static gate: skip the gather entirely when disabled
            xe, ye = _spatial_resample_one(
                xe, ye, keys[7], p_rotation, p_scaling,
                rot_max_deg * jnp.pi / 180.0, scale_lo, scale_hi,
                p_elastic, elastic_alpha,
            )
        xe, ye = _mirror_one(
            xe, ye, keys[0], tuple(a for a in spatial_axes), p_mirror
        )
        xe, ye = _rot90_one(xe, ye, keys[1], pairs, p_rot90)
        xe = _noise_one(xe, keys[2], p_noise, 0.1)
        xe = _blur_one(xe, keys[9], p_blur)  # nnunetv2 order: noise -> blur
        xe = _brightness_one(xe, keys[3], p_brightness, 0.75, 1.25)
        xe = _contrast_one(xe, keys[4], p_contrast, 0.75, 1.25)
        if p_lowres > 0.0:  # static gate: three resize branches aren't free
            xe = _lowres_one(xe, keys[8], p_lowres)
        xe = _gamma_one(xe, keys[5], p_gamma_invert, 0.7, 1.5, invert=True)
        xe = _gamma_one(xe, keys[6], p_gamma, 0.7, 1.5, invert=False)
        return xe, ye

    keys = jax.random.split(rng, x.shape[0])
    return jax.vmap(one)(x, y, keys)

"""On-device nnU-Net-style data augmentation — jittable, per-step, per-example.

Parity surface: the reference trains through nnunetv2's multiprocess augmenter
pipeline (/root/reference/fl4health/utils/nnunet_utils.py:307
``NnUNetDataLoaderWrapper`` wrapping the nnU-Net default transforms: spatial
mirroring/rotation, Gaussian noise, brightness, contrast, gamma). Those
augmenters are regularization — they change what the model converges to, not
just how fast batches arrive — so a TPU port must keep them.

TPU-native design: instead of CPU worker processes mutating numpy batches, the
transforms are pure jax ops applied *inside* the compiled training scan, keyed
per step and per example. That makes augmentation free of host round-trips,
reproducible from the PRNG stream, and fused by XLA into the forward pass.
Arbitrary-angle rotation/elastic deformation (interpolating resamplers) are
replaced by their grid-exact counterparts (axis mirrors + 90-degree rotations
on isotropic axis pairs) — the standard lossless subset; everything intensity-
side (noise/brightness/contrast/gamma) matches the nnU-Net family directly.

Default probabilities follow nnunetv2's defaults: noise p=0.1 (variance-
uniform), brightness p=0.15, contrast p=0.15, gamma p=0.3 (retain_stats)
+ invert-image gamma p=0.1, mirror p=0.5 per axis. Known deviations from
the nnunetv2 pipeline, by design: free-angle rotation, elastic deformation,
random scaling/zoom, and low-resolution simulation are omitted (all require
interpolating resamplers — hostile to static-shape compiled code); mirrors
+ rot90 carry the spatial role.
"""

from __future__ import annotations

import functools
from typing import Sequence

import jax
import jax.numpy as jnp


def _bernoulli(key, p):
    return jax.random.uniform(key) < p


def _mirror_one(x, y, key, spatial_axes, p):
    """Flip each spatial axis independently w.p. ``p`` (x and y together).
    Per-example layout: x [*spatial, C], y [*spatial] — spatial axis indices
    coincide."""
    for i, ax in enumerate(spatial_axes):
        do = _bernoulli(jax.random.fold_in(key, i), p)
        x = jnp.where(do, jnp.flip(x, axis=ax), x)
        y = jnp.where(do, jnp.flip(y, axis=ax), y)
    return x, y


def _rot90_one(x, y, key, pairs, p):
    """One random 90-degree rotation (k in 1..3) on a random isotropic axis
    pair, w.p. ``p``. ``pairs`` lists spatial axis pairs (x-indexed) whose
    sizes are equal, so every branch preserves the static shape."""
    if not pairs:
        return x, y

    def rotated(k, xx, yy, ax):
        return (jnp.rot90(xx, k=k, axes=ax), jnp.rot90(yy, k=k, axes=ax))

    do = _bernoulli(jax.random.fold_in(key, 0), p)
    pair_idx = jax.random.randint(jax.random.fold_in(key, 1), (), 0, len(pairs))
    k = jax.random.randint(jax.random.fold_in(key, 2), (), 1, 4)
    branches_x, branches_y = [], []
    for ax in pairs:
        for kk in (1, 2, 3):
            bx, by = rotated(kk, x, y, ax)
            branches_x.append(bx)
            branches_y.append(by)
    sel = pair_idx * 3 + (k - 1)
    rx = jax.lax.switch(sel, [lambda b=b: b for b in branches_x])
    ry = jax.lax.switch(sel, [lambda b=b: b for b in branches_y])
    return jnp.where(do, rx, x), jnp.where(do, ry, y)


def _noise_one(x, key, p, variance_max):
    """Additive Gaussian noise, variance ~ U(0, variance_max) — nnU-Net's
    GaussianNoiseTransform draws the VARIANCE uniformly (sigma = sqrt(var)),
    not sigma itself."""
    do = _bernoulli(jax.random.fold_in(key, 0), p)
    var = jax.random.uniform(jax.random.fold_in(key, 1), (), minval=0.0,
                             maxval=variance_max)
    noise = jnp.sqrt(var) * jax.random.normal(
        jax.random.fold_in(key, 2), x.shape, x.dtype
    )
    return jnp.where(do, x + noise, x)


def _brightness_one(x, key, p, lo, hi):
    do = _bernoulli(jax.random.fold_in(key, 0), p)
    mult = jax.random.uniform(jax.random.fold_in(key, 1), (), minval=lo,
                              maxval=hi)
    return jnp.where(do, x * mult, x)


def _contrast_one(x, key, p, lo, hi):
    """Scale around the per-channel mean, preserving range (nnU-Net's
    ContrastAugmentationTransform with preserve_range=True)."""
    do = _bernoulli(jax.random.fold_in(key, 0), p)
    factor = jax.random.uniform(jax.random.fold_in(key, 1), (), minval=lo,
                                maxval=hi)
    spatial = tuple(range(x.ndim - 1))
    mean = jnp.mean(x, axis=spatial, keepdims=True)
    mn = jnp.min(x, axis=spatial, keepdims=True)
    mx = jnp.max(x, axis=spatial, keepdims=True)
    scaled = jnp.clip(mean + (x - mean) * factor, mn, mx)
    return jnp.where(do, scaled, x)


def _gamma_one(x, key, p, lo, hi, invert):
    """Gamma on the patch rescaled to [0,1] per channel, mapped back, with
    the per-channel mean/std restored afterwards (nnU-Net's GammaTransform
    with retain_stats=True — without restoration, gamma shifts the z-scored
    statistics the normalization established). ``invert`` selects the
    invert_image=True variant (gamma applied to the negated image)."""
    do = _bernoulli(jax.random.fold_in(key, 0), p)
    gamma = jax.random.uniform(jax.random.fold_in(key, 1), (), minval=lo,
                               maxval=hi)
    spatial = tuple(range(x.ndim - 1))
    mean0 = jnp.mean(x, axis=spatial, keepdims=True)
    std0 = jnp.std(x, axis=spatial, keepdims=True)
    xin = -x if invert else x
    mn = jnp.min(xin, axis=spatial, keepdims=True)
    mx = jnp.max(xin, axis=spatial, keepdims=True)
    rng_ = jnp.maximum(mx - mn, 1e-7)
    unit = (xin - mn) / rng_
    out = jnp.power(jnp.maximum(unit, 1e-7), gamma) * rng_ + mn
    if invert:
        out = -out
    # retain_stats: restore the pre-transform per-channel mean/std
    mean1 = jnp.mean(out, axis=spatial, keepdims=True)
    std1 = jnp.std(out, axis=spatial, keepdims=True)
    out = (out - mean1) / jnp.maximum(std1, 1e-7) * std0 + mean0
    return jnp.where(do, out, x)


def _isotropic_pairs(spatial_shape: Sequence[int]) -> tuple:
    """Spatial axis pairs (as x-array axes, i.e. offset by 0 for the leading
    per-example layout [*spatial, C]) with equal sizes."""
    pairs = []
    nd = len(spatial_shape)
    for i in range(nd):
        for j in range(i + 1, nd):
            if spatial_shape[i] == spatial_shape[j]:
                pairs.append((i, j))
    return tuple(pairs)


@functools.partial(
    jax.jit,
    static_argnames=("p_mirror", "p_rot90", "p_noise", "p_brightness",
                     "p_contrast", "p_gamma", "p_gamma_invert"),
)
def augment_patch_batch(
    x: jax.Array,
    y: jax.Array,
    rng: jax.Array,
    p_mirror: float = 0.5,
    p_rot90: float = 0.5,
    p_noise: float = 0.1,
    p_brightness: float = 0.15,
    p_contrast: float = 0.15,
    p_gamma: float = 0.3,
    p_gamma_invert: float = 0.1,
) -> tuple[jax.Array, jax.Array]:
    """Augment one batch: x [B, *spatial, C] float, y [B, *spatial] int.

    Spatial transforms (mirror, rot90 on equal-size axis pairs) apply to x
    and y together; intensity transforms (noise, brightness, contrast, two
    gamma variants) to x only. Every decision is drawn per example from
    ``rng``. Matches nnunetv2's default intensity family: noise VARIANCE ~
    U(0, 0.1) at p=0.1, brightness/contrast (0.75, 1.25) at p=0.15,
    gamma (0.7, 1.5) with retain_stats at p=0.3 plus the separate
    invert-image gamma at p=0.1.
    """
    spatial = x.shape[1:-1]
    pairs = _isotropic_pairs(spatial)
    spatial_axes = tuple(range(len(spatial)))  # per-example x axes, pre-C

    def one(xe, ye, key):
        keys = jax.random.split(key, 7)
        xe, ye = _mirror_one(
            xe, ye, keys[0], tuple(a for a in spatial_axes), p_mirror
        )
        xe, ye = _rot90_one(xe, ye, keys[1], pairs, p_rot90)
        xe = _noise_one(xe, keys[2], p_noise, 0.1)
        xe = _brightness_one(xe, keys[3], p_brightness, 0.75, 1.25)
        xe = _contrast_one(xe, keys[4], p_contrast, 0.75, 1.25)
        xe = _gamma_one(xe, keys[5], p_gamma_invert, 0.7, 1.5, invert=True)
        xe = _gamma_one(xe, keys[6], p_gamma, 0.7, 1.5, invert=False)
        return xe, ye

    keys = jax.random.split(rng, x.shape[0])
    return jax.vmap(one)(x, y, keys)

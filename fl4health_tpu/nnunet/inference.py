"""Sliding-window inference — nnU-Net's full-volume prediction path.

Parity surface: nnU-Net predicts whole volumes by tiling them with patches
at ``tile_step_size`` overlap and blending the patch logits under a Gaussian
importance map (nnunetv2's ``predict_sliding_window_return_logits``, used by
the reference through ``NnunetClient``'s trainer; the patch pipeline in
``nnunet/data.py`` covers training, this module covers prediction).

TPU-native design: window positions are static (volume and patch shapes are
concrete at trace time), so the tiling unrolls inside one jit — each window
is a batched model apply and a ``dynamic_update_slice`` accumulation onto
logit/weight canvases; no host round-trips per window. The Gaussian map
(sigma = patch/8, nnU-Net's constant) downweights window borders so
overlapping predictions blend smoothly instead of seaming.
"""

from __future__ import annotations

import itertools
from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
import numpy as np


def _window_starts(size: int, patch: int, step_fraction: float) -> list[int]:
    """nnU-Net-style start positions, evenly spaced: pick the window count
    from the target stride (patch * step_fraction), then distribute starts
    uniformly over [0, size - patch] so first/last windows touch the edges
    and interior overlap is balanced (matches nnunetv2's
    ``compute_steps_for_sliding_window`` placement rather than a fixed
    stride with the last window clamped flush)."""
    if size <= patch:
        return [0]
    target = max(patch * step_fraction, 1.0)
    n = int(np.ceil((size - patch) / target)) + 1
    span = size - patch
    if n == 1:
        return [0]
    actual = span / (n - 1)
    return sorted({int(round(actual * i)) for i in range(n)})


def gaussian_importance_map(patch_size: Sequence[int],
                            sigma_scale: float = 1.0 / 8.0) -> np.ndarray:
    """Separable Gaussian centered in the patch (nnunetv2's importance map):
    border predictions contribute less than center ones."""
    axes = []
    for p in patch_size:
        coords = np.arange(p, dtype=np.float64) - (p - 1) / 2.0
        sigma = max(p * sigma_scale, 1e-8)
        axes.append(np.exp(-0.5 * (coords / sigma) ** 2))
    out = np.ones((), np.float64)
    for a in axes:
        out = np.multiply.outer(out, a)
    out = out / out.max()
    # nnU-Net clamps zeros so fully-covered-by-one-window voxels still divide
    out[out == 0] = np.min(out[out > 0])
    return out.astype(np.float32)


def sliding_window_predict(
    apply_fn: Callable[..., Any],
    params,
    model_state,
    volume: jax.Array,
    patch_size: Sequence[int],
    step_fraction: float = 0.5,
    gaussian: bool = True,
    rng: jax.Array | None = None,
) -> jax.Array:
    """Full-volume logits [*spatial, n_classes] from patch-wise application.

    apply_fn: the ModelDef.apply ((params, model_state, x[B,*patch,C], ...)
    -> ((preds, feats), state)) — the engine's forward contract; volume:
    [*spatial, C]. Spatial dims smaller than the patch are zero-padded and
    cropped back.
    """
    patch_size = tuple(int(p) for p in patch_size)
    spatial = volume.shape[:-1]
    assert len(spatial) == len(patch_size), (
        f"volume spatial rank {len(spatial)} != patch rank {len(patch_size)}"
    )
    # pad up to patch size where the volume is smaller
    pad = [(0, max(p - s, 0)) for s, p in zip(spatial, patch_size)]
    padded = jnp.pad(volume, pad + [(0, 0)])
    pspatial = padded.shape[:-1]

    weight = (
        jnp.asarray(gaussian_importance_map(patch_size))
        if gaussian else jnp.ones(patch_size, jnp.float32)
    )

    starts = [
        _window_starts(s, p, step_fraction) for s, p in zip(pspatial, patch_size)
    ]
    if rng is None:
        rng = jax.random.PRNGKey(0)

    # One compiled program per (apply_fn, geometry): the unrolled multi-window
    # graph is expensive to trace, and a per-call closure would defeat the jit
    # cache — a 50-volume test set must compile once, not 50 times.
    cache_key = (apply_fn, pspatial, patch_size, step_fraction, bool(gaussian))
    cached = _COMPILED_PREDICTORS.get(cache_key)
    if cached is not None:
        out = cached(params, model_state, padded, rng)
        crop = tuple(slice(0, s) for s in spatial)
        return out[crop]

    def predict_all(params, model_state, padded, rng):
        logits = None
        norm = jnp.zeros(pspatial + (1,), jnp.float32)
        for corner in itertools.product(*starts):
            patch = jax.lax.dynamic_slice(
                padded, corner + (0,), patch_size + (padded.shape[-1],)
            )
            (preds, _), _ = apply_fn(
                params, model_state, patch[None], train=False, rng=rng
            )
            contrib = preds["prediction"][0].astype(jnp.float32) * weight[..., None]
            if logits is None:  # canvas shape known after the first forward
                logits = jnp.zeros(pspatial + (contrib.shape[-1],), jnp.float32)
            logits = jax.lax.dynamic_update_slice(
                logits,
                jax.lax.dynamic_slice(logits, corner + (0,),
                                      contrib.shape) + contrib,
                corner + (0,),
            )
            norm = jax.lax.dynamic_update_slice(
                norm,
                jax.lax.dynamic_slice(norm, corner + (0,),
                                      patch_size + (1,)) + weight[..., None],
                corner + (0,),
            )
        return logits / jnp.maximum(norm, 1e-8)

    compiled = jax.jit(predict_all)
    if len(_COMPILED_PREDICTORS) >= _CACHE_LIMIT:
        # bounded FIFO: heterogeneous volume shapes (a fresh program per
        # padded geometry) must not grow process memory without limit
        _COMPILED_PREDICTORS.pop(next(iter(_COMPILED_PREDICTORS)))
    _COMPILED_PREDICTORS[cache_key] = compiled
    out = compiled(params, model_state, padded, rng)
    # crop padding back off
    crop = tuple(slice(0, s) for s in spatial)
    return out[crop]


_CACHE_LIMIT = 32
_COMPILED_PREDICTORS: dict = {}

"""nnU-Net data pipeline — normalization + foreground-oversampled patching.

Parity surface (/root/reference/fl4health/clients/nnunet_client.py:259-321
``get_data_loaders`` wrapping nnunetv2's patch-sampling loaders via
``NnUNetDataLoaderWrapper`` /root/reference/fl4health/utils/nnunet_utils.py:307;
:487 ``maybe_preprocess``).

TPU-native design: preprocessing (clip + z-score from the plans' fingerprint
stats) and patch extraction are host-side numpy, producing a [N, *patch, C]
patch tensor that feeds the engine's single-gather batch construction.
Random crops oversample foreground with the nnU-Net 1/3 forced-foreground
rule. The reference's multiprocess augmenter pipeline plays two roles: it
hides eager-CPU transform latency (moot for a device-resident tensor) and it
*regularizes* — spatial/intensity augmentation changes what the model
converges to. The second role is kept on-device: ``nnunet/augment.py``
applies the transform family inside the compiled training scan, keyed per
step, and ``resample_patches``/per-round ``seed`` here supports refreshing
the patch bank between rounds so the crop distribution is not frozen at
setup time.
"""

from __future__ import annotations

from typing import Any, Sequence

import numpy as np


def normalize_volume(
    volume: np.ndarray, intensity_props: dict[str, dict[str, float]]
) -> np.ndarray:
    """Per-channel clipped z-score (the "ZScoreClipped" scheme the planner
    records): clip to the foreground 0.5/99.5 percentiles, subtract the
    foreground mean, divide by the foreground std."""
    out = np.empty_like(volume, dtype=np.float32)
    for c in range(volume.shape[-1]):
        props = intensity_props[str(c)]
        chan = np.asarray(volume[..., c], np.float32)
        chan = np.clip(chan, props["percentile_00_5"], props["percentile_99_5"])
        out[..., c] = (chan - props["mean"]) / max(props["std"], 1e-8)
    return out


def _random_patch_corner(
    rng: np.random.Generator,
    shape: Sequence[int],
    patch: Sequence[int],
    fg_coords: np.ndarray | None,
    force_foreground: bool,
) -> tuple[int, ...]:
    """Crop corner; when forcing foreground, center the patch on a random
    foreground voxel (clamped to bounds) — nnU-Net's oversampling rule.
    ``fg_coords`` is the case's precomputed [N_fg, ndim] foreground index
    table (computed once per case, not per patch)."""
    max_corner = [max(s - p, 0) for s, p in zip(shape, patch)]
    if force_foreground and fg_coords is not None and len(fg_coords):
        center = fg_coords[rng.integers(len(fg_coords))]
        return tuple(
            int(np.clip(c - p // 2, 0, m))
            for c, p, m in zip(center, patch, max_corner)
        )
    return tuple(int(rng.integers(m + 1)) for m in max_corner)


def extract_patch_dataset(
    volumes: Sequence[np.ndarray],
    segmentations: Sequence[np.ndarray],
    plans: dict[str, Any],
    n_patches: int,
    seed: int = 0,
    configuration: str | None = None,
    oversample_foreground: float = 1.0 / 3.0,
) -> tuple[np.ndarray, np.ndarray]:
    """-> (x [N, *patch, C] float32 normalized, y [N, *patch] int32).

    Volumes are channels-last; shorter-than-patch axes are zero-padded (the
    nnU-Net pad-to-patch behavior). Every ~third patch is forced to contain
    foreground.
    """
    if configuration is None:
        from fl4health_tpu.nnunet.plans import default_configuration

        configuration = default_configuration(plans)
    cfg = plans["configurations"][configuration]
    patch = [int(p) for p in cfg["patch_size"]]
    props = plans["foreground_intensity_properties_per_channel"]
    rng = np.random.default_rng(seed)

    normed = [normalize_volume(v, props) for v in volumes]
    # Pad any volume smaller than the patch in some axis.
    padded_v, padded_s = [], []
    for v, s in zip(normed, segmentations):
        pads = [(0, max(p - sh, 0)) for p, sh in zip(patch, v.shape[:-1])]
        padded_v.append(np.pad(v, pads + [(0, 0)]))
        padded_s.append(np.pad(np.asarray(s), pads))

    # Foreground coordinate tables, once per case (not per patch).
    fg_tables = [np.argwhere(s >= 1) for s in padded_s]

    n_channels = padded_v[0].shape[-1]
    xs = np.empty((n_patches, *patch, n_channels), np.float32)
    ys = np.empty((n_patches, *patch), np.int32)
    for i in range(n_patches):
        case = int(rng.integers(len(padded_v)))
        force_fg = (i % max(int(round(1.0 / oversample_foreground)), 1)) == 0
        corner = _random_patch_corner(
            rng, padded_v[case].shape[:-1], patch, fg_tables[case], force_fg
        )
        sl = tuple(slice(c, c + p) for c, p in zip(corner, patch))
        xs[i] = padded_v[case][sl]
        ys[i] = padded_s[case][sl]
    return xs, ys


def make_patch_resampler(
    volumes_per_client: Sequence[Sequence[np.ndarray]],
    segmentations_per_client: Sequence[Sequence[np.ndarray]],
    plans: dict[str, Any],
    n_patches: int,
    base_seed: int = 0,
    every: int = 1,
    **extract_kwargs: Any,
) -> Any:
    """-> ``train_data_provider`` for ``FederatedSimulation``: fresh patch
    banks per round (the sampling half of nnU-Net's per-iteration random
    crops — the reference's loaders draw new crops every batch; here the bank
    refreshes every ``every`` rounds and the compiled scan shuffles within
    it). Each client's stream is seeded by (base_seed, client, round) so runs
    are reproducible and clients decorrelated."""

    def provider(round_idx: int):
        if (round_idx - 1) % every != 0 or round_idx == 1:
            # round 1 keeps the construction-time bank (seeded identically),
            # so resampling only changes data from round `1+every` on.
            return None
        xs, ys = [], []
        for ci, (v, s) in enumerate(
            zip(volumes_per_client, segmentations_per_client)
        ):
            x, y = extract_patch_dataset(
                v, s, plans, n_patches,
                seed=base_seed + 100_003 * ci + round_idx,
                **extract_kwargs,
            )
            xs.append(x)
            ys.append(y)
        return xs, ys

    return provider

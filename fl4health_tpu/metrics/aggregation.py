"""Server-side metric aggregation over client results.

Parity: /root/reference/fl4health/metrics/metric_aggregation.py:6-155 —
sample-weighted or uniform averaging of per-client metric dicts, for both fit
and evaluate phases, with normalization.

TPU shape: metric values arrive client-stacked ([clients] per key); weighting
reuses the same effective-weights kernel as parameter aggregation.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import jax
import jax.numpy as jnp

from fl4health_tpu.core.aggregate import effective_weights


def aggregate_metrics(
    client_metrics: Mapping[str, jax.Array],
    sample_counts: jax.Array,
    mask: jax.Array | None = None,
    weighted: bool = True,
) -> dict[str, jax.Array]:
    """Aggregate stacked metric values [clients] -> scalar per key."""
    w = effective_weights(sample_counts, mask, weighted)
    return {
        k: jnp.sum(jnp.asarray(v, jnp.float32) * w) for k, v in client_metrics.items()
    }


def aggregate_metrics_list(
    per_client: Sequence[Mapping[str, jax.Array]],
    sample_counts: Sequence[float],
    weighted: bool = True,
) -> dict[str, float]:
    """Host-list convenience: list of per-client dicts -> aggregated floats.

    Mirrors metric_aggregation.metric_aggregation + normalize_metrics.
    """
    if not per_client:
        return {}
    keys = per_client[0].keys()
    stacked = {
        k: jnp.asarray([float(m[k]) for m in per_client], jnp.float32) for k in keys
    }
    counts = jnp.asarray(list(sample_counts), jnp.float32)
    out = aggregate_metrics(stacked, counts, weighted=weighted)
    return {k: float(v) for k, v in out.items()}


def prefix_test_metrics(metrics: Mapping[str, float]) -> tuple[dict, dict]:
    """Split a metrics dict into (val, test) by the reference's 'test -' prefix
    convention (servers/base_server.py:545 _unpack_metrics)."""
    val = {k: v for k, v in metrics.items() if not k.startswith("test -")}
    test = {k: v for k, v in metrics.items() if k.startswith("test -")}
    return val, test

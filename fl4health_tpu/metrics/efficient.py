"""Streaming count-based classification metrics.

Reference: the memory-efficient tp/fp/fn/tn accumulation design of
/root/reference/fl4health/metrics/efficient_metrics_base.py:28-120 (with soft
continuous counts) and efficient_metrics.py (Binary/MultiClassDice). That
design is already the right shape for JAX: fixed-size count vectors updated
per batch — here they live on device inside lax.scan.

Conventions:
- Binary metrics accept probabilities/logits of shape [B] or [B,1] (threshold
  0.5 post-sigmoid if values outside [0,1] are detected) or hard {0,1} labels.
- Multiclass metrics accept logits/probs [B, C] and integer targets [B] (or
  one-hot [B, C]).
- ``mask`` is [B] example validity; padded rows contribute nothing.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from fl4health_tpu.metrics.base import Metric


def _as_probs(preds: jax.Array) -> jax.Array:
    """Map logits to probabilities when needed (idempotent on probs)."""
    outside = jnp.logical_or(jnp.min(preds) < 0.0, jnp.max(preds) > 1.0)
    return jnp.where(outside, jax.nn.sigmoid(preds), preds)


def _binary_counts(preds, targets, mask, threshold=0.5, soft=False):
    p = _as_probs(preds.reshape(preds.shape[0], -1)[:, 0].astype(jnp.float32))
    t = targets.reshape(targets.shape[0], -1)[:, 0].astype(jnp.float32)
    m = mask.astype(jnp.float32)
    if not soft:
        p = (p >= threshold).astype(jnp.float32)
    tp = jnp.sum(p * t * m)
    fp = jnp.sum(p * (1 - t) * m)
    fn = jnp.sum((1 - p) * t * m)
    tn = jnp.sum((1 - p) * (1 - t) * m)
    return jnp.stack([tp, fp, fn, tn])


def _multiclass_counts(preds, targets, mask, n_classes):
    """Per-class [C, 4] (tp, fp, fn, tn) from [B,C] scores + [B] int targets."""
    pred_cls = jnp.argmax(preds, axis=-1)
    if targets.ndim == preds.ndim:  # one-hot targets
        targets = jnp.argmax(targets, axis=-1)
    m = mask.astype(jnp.float32)
    pred_1h = jax.nn.one_hot(pred_cls, n_classes)
    targ_1h = jax.nn.one_hot(targets, n_classes)
    tp = jnp.sum(pred_1h * targ_1h * m[:, None], axis=0)
    fp = jnp.sum(pred_1h * (1 - targ_1h) * m[:, None], axis=0)
    fn = jnp.sum((1 - pred_1h) * targ_1h * m[:, None], axis=0)
    tn = jnp.sum((1 - pred_1h) * (1 - targ_1h) * m[:, None], axis=0)
    return jnp.stack([tp, fp, fn, tn], axis=-1)


# ---------------------------------------------------------------------------
# Metric constructors
# ---------------------------------------------------------------------------

def accuracy(name: str = "accuracy") -> Metric:
    """Top-1 accuracy for [B,C] logits or binary [B] scores (metrics.py:155)."""

    def init():
        return jnp.zeros((2,), jnp.float32)  # correct, total

    def update(state, preds, targets, mask):
        m = mask.astype(jnp.float32)
        if preds.ndim >= 2 and preds.shape[-1] > 1:
            pred_cls = jnp.argmax(preds, axis=-1)
            t = jnp.argmax(targets, axis=-1) if targets.ndim == preds.ndim else targets
        else:
            pred_cls = (_as_probs(preds.reshape(preds.shape[0])) >= 0.5).astype(jnp.int32)
            t = targets.reshape(targets.shape[0])
        correct = jnp.sum((pred_cls == t).astype(jnp.float32) * m)
        return state + jnp.stack([correct, jnp.sum(m)])

    def compute(state):
        return state[0] / jnp.maximum(state[1], 1.0)

    return Metric(name, init, update, compute)


def balanced_accuracy(n_classes: int, name: str = "balanced_accuracy") -> Metric:
    """Mean per-class recall (metrics.py:178)."""

    def init():
        return jnp.zeros((n_classes, 4), jnp.float32)

    def update(state, preds, targets, mask):
        return state + _multiclass_counts(preds, targets, mask, n_classes)

    def compute(state):
        tp, fn = state[:, 0], state[:, 2]
        support = tp + fn
        recall = tp / jnp.maximum(support, 1.0)
        present = (support > 0).astype(jnp.float32)
        return jnp.sum(recall * present) / jnp.maximum(jnp.sum(present), 1.0)

    return Metric(name, init, update, compute)


def f1(n_classes: int, average: str = "weighted", name: str = "f1") -> Metric:
    """F1 with weighted/macro/micro averaging (metrics.py:219 uses sklearn
    weighted average by default)."""

    def init():
        return jnp.zeros((n_classes, 4), jnp.float32)

    def update(state, preds, targets, mask):
        return state + _multiclass_counts(preds, targets, mask, n_classes)

    def compute(state):
        tp, fp, fn = state[:, 0], state[:, 1], state[:, 2]
        if average == "micro":
            return 2 * jnp.sum(tp) / jnp.maximum(2 * jnp.sum(tp) + jnp.sum(fp) + jnp.sum(fn), 1.0)
        per_class = 2 * tp / jnp.maximum(2 * tp + fp + fn, 1.0)
        support = tp + fn
        if average == "weighted":
            return jnp.sum(per_class * support) / jnp.maximum(jnp.sum(support), 1.0)
        present = (support > 0).astype(jnp.float32)
        return jnp.sum(per_class * present) / jnp.maximum(jnp.sum(present), 1.0)

    return Metric(name, init, update, compute)


def binary_classification_metric(
    stat: str, threshold: float = 0.5, name: str | None = None
) -> Metric:
    """Binary precision/recall/specificity/npv/f1/accuracy from streamed counts
    (efficient_metrics_base.py:429 BinaryClassificationMetric)."""

    def init():
        return jnp.zeros((4,), jnp.float32)

    def update(state, preds, targets, mask):
        return state + _binary_counts(preds, targets, mask, threshold)

    def compute(state):
        tp, fp, fn, tn = state[0], state[1], state[2], state[3]
        eps = 1.0
        if stat == "precision":
            return tp / jnp.maximum(tp + fp, eps)
        if stat == "recall":
            return tp / jnp.maximum(tp + fn, eps)
        if stat == "specificity":
            return tn / jnp.maximum(tn + fp, eps)
        if stat == "npv":
            return tn / jnp.maximum(tn + fn, eps)
        if stat == "f1":
            return 2 * tp / jnp.maximum(2 * tp + fp + fn, eps)
        return (tp + tn) / jnp.maximum(tp + fp + fn + tn, eps)  # accuracy

    return Metric(name or f"binary_{stat}", init, update, compute)


def binary_soft_dice(
    epsilon: float = 1e-7, spatial_dims: tuple[int, ...] | None = None,
    name: str = "dice",
) -> Metric:
    """Soft Dice coefficient with probability intersections
    (metrics.py:116 BinarySoftDiceCoefficient / efficient_metrics.py:163).

    Accumulates (2*intersection, denominator) so the final coefficient is the
    dataset-level dice; per-image dice averaging is the TransformsMetric route.
    """

    def init():
        return jnp.zeros((2,), jnp.float32)

    def update(state, preds, targets, mask):
        p = _as_probs(preds.astype(jnp.float32))
        t = targets.astype(jnp.float32)
        m = mask.astype(jnp.float32).reshape((-1,) + (1,) * (p.ndim - 1))
        inter = jnp.sum(p * t * m)
        denom = jnp.sum(p * m) + jnp.sum(t * m)
        return state + jnp.stack([2.0 * inter, denom])

    def compute(state):
        return (state[0] + epsilon) / (state[1] + epsilon)

    return Metric(name, init, update, compute)


def multiclass_dice(n_classes: int, name: str = "multiclass_dice") -> Metric:
    """Mean per-class hard Dice from streamed counts (efficient_metrics.py:15)."""

    def init():
        return jnp.zeros((n_classes, 4), jnp.float32)

    def update(state, preds, targets, mask):
        return state + _multiclass_counts(preds, targets, mask, n_classes)

    def compute(state):
        tp, fp, fn = state[:, 0], state[:, 1], state[:, 2]
        dice = 2 * tp / jnp.maximum(2 * tp + fp + fn, 1.0)
        present = (tp + fn > 0).astype(jnp.float32)
        return jnp.sum(dice * present) / jnp.maximum(jnp.sum(present), 1.0)

    return Metric(name, init, update, compute)


def binned_auc(n_thresholds: int = 200, name: str = "roc_auc") -> Metric:
    """Streaming ROC-AUC via fixed threshold bins.

    The reference RocAuc (metrics.py:199) stores every pred and calls sklearn —
    O(dataset) host memory. The streaming form keeps [T,4] counts at T fixed
    thresholds and trapezoid-integrates ROC, standard practice on accelerators
    (Keras AUC); error is O(1/T).
    """

    thresholds = jnp.linspace(0.0, 1.0, n_thresholds)

    def init():
        return jnp.zeros((n_thresholds, 4), jnp.float32)

    def update(state, preds, targets, mask):
        p = _as_probs(preds.reshape(preds.shape[0], -1)[:, 0].astype(jnp.float32))
        t = targets.reshape(targets.shape[0], -1)[:, 0].astype(jnp.float32)
        m = mask.astype(jnp.float32)
        pred_pos = (p[None, :] >= thresholds[:, None]).astype(jnp.float32)  # [T,B]
        tp = jnp.sum(pred_pos * t[None] * m[None], axis=1)
        fp = jnp.sum(pred_pos * (1 - t[None]) * m[None], axis=1)
        fn = jnp.sum((1 - pred_pos) * t[None] * m[None], axis=1)
        tn = jnp.sum((1 - pred_pos) * (1 - t[None]) * m[None], axis=1)
        return state + jnp.stack([tp, fp, fn, tn], axis=-1)

    def compute(state):
        tp, fp, fn, tn = state[:, 0], state[:, 1], state[:, 2], state[:, 3]
        tpr = tp / jnp.maximum(tp + fn, 1.0)
        fpr = fp / jnp.maximum(fp + tn, 1.0)
        # thresholds ascend -> fpr/tpr descend; integrate |dx| * mean(y)
        return jnp.sum(
            (fpr[:-1] - fpr[1:]) * 0.5 * (tpr[:-1] + tpr[1:])
        )

    return Metric(name, init, update, compute)


def segmentation_dice(
    n_classes: int, ignore_label: int | None = None, name: str = "seg_dice"
) -> Metric:
    """Hard per-class Dice over dense segmentation maps, streamed as counts.

    preds are logits [B, *spatial, C]; targets are integer maps [B, *spatial];
    mask is per-example [B]. Background (class 0) is excluded from the mean,
    matching the reference's dice conventions for nnU-Net workloads
    (metrics/efficient_metrics.py MultiClassDice with do_bg=False semantics).
    Voxels carrying ``ignore_label`` are excluded entirely (the nnU-Net
    ignore-label contract, nnunet_client.py:703).
    """

    def init():
        return jnp.zeros((n_classes, 3), jnp.float32)  # tp, fp, fn per class

    def update(state, preds, targets, mask):
        pred_lbl = jnp.argmax(preds, axis=-1)
        t = targets.astype(jnp.int32)
        m = jnp.broadcast_to(
            mask.reshape((-1,) + (1,) * (t.ndim - 1)), t.shape
        ).astype(jnp.float32)
        if ignore_label is not None:
            m = m * (t != ignore_label).astype(jnp.float32)
        pred_oh = jax.nn.one_hot(pred_lbl, n_classes, dtype=jnp.float32)
        true_oh = jax.nn.one_hot(t, n_classes, dtype=jnp.float32)
        axes = tuple(range(t.ndim))
        tp = jnp.sum(pred_oh * true_oh * m[..., None], axis=axes)
        fp = jnp.sum(pred_oh * (1 - true_oh) * m[..., None], axis=axes)
        fn = jnp.sum((1 - pred_oh) * true_oh * m[..., None], axis=axes)
        return state + jnp.stack([tp, fp, fn], axis=-1)

    def compute(state):
        tp, fp, fn = state[:, 0], state[:, 1], state[:, 2]
        dice = 2 * tp / jnp.maximum(2 * tp + fp + fn, 1.0)
        present = (tp + fn > 0).astype(jnp.float32)
        if n_classes > 1:
            dice, present = dice[1:], present[1:]
        return jnp.sum(dice * present) / jnp.maximum(jnp.sum(present), 1.0)

    return Metric(name, init, update, compute)

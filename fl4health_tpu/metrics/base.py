"""Metric abstraction — pure (init, update, compute) triples over state pytrees.

Reference surface (/root/reference/fl4health/metrics/base_metrics.py:17): a
``Metric`` ABC with update/compute/clear accumulating python-side state, and a
``MetricManager`` (metric_managers.py:11) fanning updates over per-prediction-key
metric collections.

TPU-native design: metric state is a pytree threaded through ``lax.scan`` of
the training/eval loop, so metrics accumulate on-device inside jit with zero
host sync; ``compute`` runs once at the end. ``clear`` is just ``init()``.
Every update takes an example-validity ``mask`` so ragged batches (padded
cohort data) never contaminate counts — the reference's empty-batch skip guard
(clients/basic_client.py:660-662) generalized per example.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Mapping

import jax
import jax.numpy as jnp

from fl4health_tpu.core.types import PyTree


@dataclasses.dataclass(frozen=True)
class Metric:
    """A named metric as pure functions.

    init:    () -> state
    update:  (state, preds, targets, mask) -> state      [jit/scan-safe]
    compute: (state) -> scalar
    """

    name: str
    init: Callable[[], PyTree]
    update: Callable[[PyTree, jax.Array, jax.Array, jax.Array], PyTree]
    compute: Callable[[PyTree], jax.Array]


@dataclasses.dataclass(frozen=True)
class MetricManager:
    """Fixed collection of metrics updated together (metric_managers.py:11).

    State is a dict name->metric-state; usable directly as a scan carry.
    """

    metrics: tuple[Metric, ...]
    prefix: str = ""

    def init(self) -> dict:
        return {m.name: m.init() for m in self.metrics}

    def update(
        self,
        state: dict,
        preds: jax.Array,
        targets: jax.Array,
        mask: jax.Array | None = None,
    ) -> dict:
        if mask is None:
            mask = jnp.ones((preds.shape[0],), jnp.float32)
        return {
            m.name: m.update(state[m.name], preds, targets, mask) for m in self.metrics
        }

    def compute(self, state: dict) -> dict:
        key = (self.prefix + " - ") if self.prefix else ""
        return {f"{key}{m.name}": m.compute(state[m.name]) for m in self.metrics}


def ema_metric(inner: Metric, smoothing_factor: float = 0.1, name: str | None = None) -> Metric:
    """Exponential-moving-average wrapper (compound_metrics.py:17).

    State carries (inner_state, ema_value, initialized). The EMA folds in the
    inner metric's instantaneous value at each update, then the inner state is
    reset — matching the reference's per-call EMA semantics.
    """

    def init():
        return {
            "inner": inner.init(),
            "ema": jnp.zeros((), jnp.float32),
            "started": jnp.zeros((), jnp.bool_),
        }

    def update(state, preds, targets, mask):
        fresh = inner.update(inner.init(), preds, targets, mask)
        val = inner.compute(fresh).astype(jnp.float32)
        new_ema = jnp.where(
            state["started"],
            smoothing_factor * val + (1.0 - smoothing_factor) * state["ema"],
            val,
        )
        return {"inner": state["inner"], "ema": new_ema, "started": jnp.ones((), jnp.bool_)}

    def compute(state):
        return state["ema"]

    return Metric(name=name or f"ema_{inner.name}", init=init, update=update, compute=compute)


def transforms_metric(
    inner: Metric,
    pred_transforms: tuple[Callable[[jax.Array], jax.Array], ...] = (),
    target_transforms: tuple[Callable[[jax.Array], jax.Array], ...] = (),
    name: str | None = None,
) -> Metric:
    """Apply transforms to preds/targets before the inner metric
    (compound_metrics.py:128)."""

    def update(state, preds, targets, mask):
        for t in pred_transforms:
            preds = t(preds)
        for t in target_transforms:
            targets = t(targets)
        return inner.update(state, preds, targets, mask)

    return Metric(
        name=name or inner.name, init=inner.init, update=update, compute=inner.compute
    )

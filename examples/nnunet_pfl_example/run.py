"""Personalized federated nnU-Net: plans negotiation + Ditto via make_it_personal (reference: examples/nnunet_pfl_example — nnU-Net with Ditto/MR-MTL personalization).

Run:  python examples/nnunet_pfl_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/nnunet_pfl_example/run.py
"""

import os
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

import numpy as np
from fl4health_tpu.clients.nnunet import NnunetClientLogic, make_nnunet_properties_provider
from fl4health_tpu.clients.personalized import (
    PersonalizedMode,
    exchange_global_subtree,
    make_it_personal,
)
from fl4health_tpu.exchange.exchanger import FixedLayerExchanger
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.metrics.efficient import segmentation_dice
from fl4health_tpu.models.unet import deep_supervision_strides, unet_from_plans
from fl4health_tpu.nnunet import extract_patch_dataset, nnunet_optimizer
from fl4health_tpu.server.nnunet import NnunetServer
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg


def synth_client(seed, n, size):
    rng = np.random.default_rng(seed)
    vols, segs = [], []
    for _ in range(n):
        coords = np.stack(np.meshgrid(*[np.arange(size)] * 3, indexing="ij"), -1).astype(float)
        c = np.asarray([rng.uniform(size * .3, size * .7) for _ in range(3)])
        r = size * rng.uniform(.2, .3)
        seg = (np.sqrt(((coords - c) ** 2).sum(-1)) < r).astype(np.int32)
        vols.append((rng.normal(0, .3, (size,) * 3)[..., None] + seg[..., None]).astype(np.float32))
        segs.append(seg)
    return vols, segs


size, nvol = cfg["volume_size"], cfg["n_volumes"]
if os.environ.get("FL4HEALTH_EXAMPLE_TINY"):
    # twin 3D U-Nets dominate smoke-suite compile time; shrink the volumes
    size, nvol = 8, 2
    cfg["local_steps"] = min(int(cfg["local_steps"]), 2)
client_data = [synth_client(10 * (i + 1), nvol, size) for i in range(cfg["n_clients"])]
providers = [
    make_nnunet_properties_provider(v, [(1.0, 1.0, 1.0)] * len(v), s)
    for v, s in client_data
]


def sim_builder(plans, n_in, n_heads):
    net = unet_from_plans(plans, n_in, n_heads)
    base = NnunetClientLogic(engine.from_flax(net),
                             ds_strides=deep_supervision_strides(plans))
    # The pfl twist: an exchanged global U-Net + a private personal U-Net with
    # an l2 drift constraint — nnU-Net personalized exactly like any other
    # client logic.
    logic = make_it_personal(base, PersonalizedMode.DITTO, lam=cfg["lam"])
    datasets = []
    for i, (v, s) in enumerate(client_data):
        x, y = extract_patch_dataset(v, s, plans, n_patches=10, seed=i)
        datasets.append(ClientDataset(x[:8], y[:8], x[8:], y[8:]))
    return FederatedSimulation(
        logic=logic,
        tx=nnunet_optimizer(5e-3, cfg["n_server_rounds"] * cfg["local_steps"]),
        strategy=FedAvg(),
        datasets=datasets,
        batch_size=2,
        metrics=MetricManager((segmentation_dice(n_heads),)),
        local_steps=cfg["local_steps"],
        seed=0,
        exchanger=FixedLayerExchanger(exchange_global_subtree),
        extra_loss_keys=logic.extra_loss_keys,
    )


server = NnunetServer(config=dict(cfg), property_providers=providers,
                      sim_builder=sim_builder)
lib.run_and_report(server, cfg)

"""APFL adaptive personal/global mixing (reference: examples/apfl_example).

Run:  python examples/apfl_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/apfl_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

from fl4health_tpu.clients.apfl import ApflClientLogic, apfl_model_def
from fl4health_tpu.exchange.exchanger import FixedLayerExchanger
from fl4health_tpu.models import bases
from fl4health_tpu.models.cnn import MnistNet
from fl4health_tpu.server.simulation import FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg

module = bases.ApflModule(local_model=MnistNet(hidden=32), global_model=MnistNet(hidden=32))
sim = FederatedSimulation(
    logic=ApflClientLogic(apfl_model_def(module), engine.masked_cross_entropy,
                          alpha=cfg["alpha"]),
    tx=optax.sgd(cfg["learning_rate"]),
    strategy=FedAvg(),
    datasets=lib.mnist_client_datasets(cfg),
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_epochs=cfg["local_epochs"],
    seed=42,
    exchanger=FixedLayerExchanger(bases.ApflModule.exchange_global_model),
    extra_loss_keys=("global_ce", "personal_ce"),
)
lib.run_and_report(sim, cfg)

"""Federated nnU-Net-class 3D segmentation with plans negotiation (reference: examples/nnunet_example).

Run:  python examples/nnunet_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/nnunet_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

import numpy as np
from fl4health_tpu.clients.nnunet import NnunetClientLogic, make_nnunet_properties_provider
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.metrics.efficient import segmentation_dice
from fl4health_tpu.models.unet import deep_supervision_strides, unet_from_plans
from fl4health_tpu.nnunet import extract_patch_dataset, nnunet_optimizer
from fl4health_tpu.server.nnunet import NnunetServer
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg

def synth_client(seed, n, size):
    rng = np.random.default_rng(seed)
    vols, segs = [], []
    for _ in range(n):
        coords = np.stack(np.meshgrid(*[np.arange(size)] * 3, indexing="ij"), -1).astype(float)
        c = np.asarray([rng.uniform(size * .3, size * .7) for _ in range(3)])
        r = size * rng.uniform(.2, .3)
        seg = (np.sqrt(((coords - c) ** 2).sum(-1)) < r).astype(np.int32)
        vols.append((rng.normal(0, .3, (size,) * 3)[..., None] + seg[..., None]).astype(np.float32))
        segs.append(seg)
    return vols, segs

size, nvol = cfg["volume_size"], cfg["n_volumes"]
client_data = [synth_client(10 * (i + 1), nvol, size) for i in range(cfg["n_clients"])]
providers = [
    make_nnunet_properties_provider(v, [(1.0, 1.0, 1.0)] * len(v), s)
    for v, s in client_data
]

def sim_builder(plans, n_in, n_heads):
    net = unet_from_plans(plans, n_in, n_heads)
    logic = NnunetClientLogic(engine.from_flax(net),
                              ds_strides=deep_supervision_strides(plans))
    datasets = []
    for i, (v, s) in enumerate(client_data):
        x, y = extract_patch_dataset(v, s, plans, n_patches=10, seed=i)
        datasets.append(ClientDataset(x[:8], y[:8], x[8:], y[8:]))
    return FederatedSimulation(
        logic=logic,
        tx=nnunet_optimizer(5e-3, cfg["n_server_rounds"] * cfg["local_steps"]),
        strategy=FedAvg(),
        datasets=datasets,
        batch_size=2,
        metrics=MetricManager((segmentation_dice(n_heads),)),
        local_steps=cfg["local_steps"],
        seed=0,
        extra_loss_keys=("dice", "ce"),
    )

server = NnunetServer(config=dict(cfg), property_providers=providers,
                      sim_builder=sim_builder)
lib.run_and_report(server, cfg)

# Full-volume prediction with the trained global model: sliding-window
# tiling + Gaussian blending (nnunetv2's predict_sliding_window role).
import json

import jax
import jax.numpy as jnp

from fl4health_tpu.nnunet import normalize_volume, sliding_window_predict
from fl4health_tpu.nnunet.plans import default_configuration

sim = server.sim
vol, seg = client_data[0][0][0], client_data[0][1][0]
config = server.plans["configurations"][default_configuration(server.plans)]
props = server.plans["foreground_intensity_properties_per_channel"]
model_state = jax.tree_util.tree_map(lambda x: x[0], sim.client_states.model_state)
logits = sliding_window_predict(
    sim.logic.model.apply, sim.global_params,
    model_state,
    jnp.asarray(normalize_volume(vol, props)),
    patch_size=config["patch_size"],
)
pred = jnp.argmax(logits, -1)
inter = float(jnp.sum((pred == 1) & (jnp.asarray(seg) == 1)))
denom = float(jnp.sum(pred == 1) + jnp.sum(jnp.asarray(seg) == 1))
print(json.dumps({"sliding_window_dice": round(2 * inter / max(denom, 1), 4)}))

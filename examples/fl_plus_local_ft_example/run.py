"""Personal-FL: federated training, then per-client local fine-tuning
(reference: examples/fl_plus_local_ft_example — train a global model with
FedAvg, then each client adapts it on its own data with no further
exchange).

Run:  python examples/fl_plus_local_ft_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/fl_plus_local_ft_example/run.py
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402
from fl4health_tpu.clients.ditto import KeepLocalExchanger  # noqa: E402
from fl4health_tpu.server.simulation import FederatedSimulation  # noqa: E402
from fl4health_tpu.strategies.fedavg import FedAvg  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)
datasets = lib.mnist_client_datasets(cfg)
model = lib.mnist_model(cfg)

# Phase 1: federated training.
sim = FederatedSimulation(
    logic=engine.ClientLogic(model, engine.masked_cross_entropy),
    tx=optax.sgd(cfg["learning_rate"]),
    strategy=FedAvg(),
    datasets=datasets,
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_epochs=cfg["local_epochs"],
    seed=42,
)
fl_history = lib.run_and_report(sim, cfg)

# Phase 2: local fine-tuning — every client keeps training from the final
# global model with NOTHING exchanged (KeepLocalExchanger pulls are no-ops;
# the aggregate is never consumed again).
ft = FederatedSimulation(
    logic=engine.ClientLogic(model, engine.masked_cross_entropy),
    tx=optax.sgd(cfg["learning_rate"] / 2),
    strategy=FedAvg(),
    datasets=datasets,
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_epochs=cfg["local_epochs"],
    seed=43,
    exchanger=KeepLocalExchanger(),
)
# warm-start every client from the federated global model
import jax  # noqa: E402

global_params = sim.global_params
ft.client_states = ft.client_states.replace(
    params=jax.tree_util.tree_map(
        lambda g, c: jax.numpy.broadcast_to(g[None], c.shape).astype(c.dtype),
        global_params, ft.client_states.params,
    )
)
ft_history = ft.fit(int(cfg.get("ft_rounds", 2)))
print(json.dumps({
    "personal_ft": True,
    "post_fl_accuracy": round(fl_history[-1].eval_metrics["accuracy"], 5),
    "post_ft_accuracy": round(ft_history[-1].eval_metrics["accuracy"], 5),
}))

"""SCAFFOLD with control-variate warm start (reference: examples/scaffold_example).

Run:  python examples/scaffold_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/scaffold_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

from fl4health_tpu.clients.scaffold import ScaffoldClientLogic
from fl4health_tpu.server.servers import ScaffoldServer
from fl4health_tpu.server.simulation import FederatedSimulation
from fl4health_tpu.strategies.scaffold import Scaffold

sim = FederatedSimulation(
    logic=ScaffoldClientLogic(lib.mnist_model(cfg), engine.masked_cross_entropy,
                              learning_rate=cfg["learning_rate"]),
    tx=optax.sgd(cfg["learning_rate"]),
    strategy=Scaffold(learning_rate=1.0),
    datasets=lib.mnist_client_datasets(cfg),
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_epochs=cfg["local_epochs"],
    seed=42,
)
server = ScaffoldServer(sim, warm_start=cfg.get("warm_start", False))
lib.run_and_report(server, cfg)

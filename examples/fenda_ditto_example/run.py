"""FENDA + Ditto: twin FENDA models with drift-constrained personal global extractor (reference: examples/fenda_ditto_example).

Run:  python examples/fenda_ditto_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/fenda_ditto_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

from fl4health_tpu.clients.fenda import FendaDittoClientLogic
from fl4health_tpu.exchange.exchanger import FixedLayerExchanger
from fl4health_tpu.models import bases
from fl4health_tpu.server.simulation import FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg


def fenda():
    return bases.FendaModel(
        first_feature_extractor=bases.DenseFeatures((32,)),
        second_feature_extractor=bases.DenseFeatures((32,)),
        head_module=bases.HeadModule(head=bases.DenseHead(10)),
    )


model = bases.TwinModel(global_model=fenda(), personal_model=fenda())
sim = FederatedSimulation(
    logic=FendaDittoClientLogic(engine.from_flax(model),
                                engine.masked_cross_entropy, lam=cfg["lam"]),
    tx=optax.sgd(cfg["learning_rate"]),
    strategy=FedAvg(),
    datasets=lib.mnist_client_datasets(cfg),
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_epochs=cfg["local_epochs"],
    seed=42,
    exchanger=FixedLayerExchanger(bases.TwinModel.exchange_global_model),
    extra_loss_keys=("global_ce", "personal_ce", "penalty"),
)
lib.run_and_report(sim, cfg)

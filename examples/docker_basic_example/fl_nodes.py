"""Shared node logic for the dockerized basic example (reference:
examples/docker_basic_example — the basic FedAvg example packaged as one
server + N client containers).

Both deployment shapes use exactly this code:
- ``run.py`` hosts the silos as in-process threads (CI-testable);
- ``client.py`` / ``server.py`` run them as real processes/containers over
  the same TCP wire (transport/loopback.py + codec frames).
"""

from __future__ import annotations

import sys
from concurrent.futures import ThreadPoolExecutor
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

import jax
import jax.numpy as jnp
import numpy as np
import optax

from fl4health_tpu.clients import engine
from fl4health_tpu.datasets.synthetic import synthetic_classification
from fl4health_tpu.metrics import efficient
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models.cnn import Mlp
from fl4health_tpu.transport import LoopbackServer, call, decode, encode

N_CLASSES = 3
DIM = 6


def build_logic():
    module = Mlp(features=(16,), n_outputs=N_CLASSES)
    return engine.ClientLogic(engine.from_flax(module), engine.masked_cross_entropy)


def make_silo_handler(seed: int, batch_size: int, local_steps: int,
                      learning_rate: float):
    """One hospital's request handler: pull global params, train locally,
    return update + sample count + metrics."""
    logic = build_logic()
    tx = optax.sgd(learning_rate)
    x, y = synthetic_classification(
        jax.random.PRNGKey(seed), 64, (DIM,), N_CLASSES, class_sep=2.0
    )
    state = engine.create_train_state(logic, tx, jax.random.PRNGKey(seed), x[:1])
    train = jax.jit(
        engine.make_local_train(logic, tx, MetricManager((efficient.accuracy(),)))
    )
    n = 48  # train split; x[n:] is the held-out eval slice

    @jax.jit
    def holdout_accuracy(params, model_state):
        (preds, _), _ = logic.model.apply(
            params, model_state, x[n:], train=False, rng=jax.random.PRNGKey(0)
        )
        return jnp.mean(
            (jnp.argmax(preds["prediction"], axis=-1) == y[n:]).astype(jnp.float32)
        )

    def handler(frame: bytes) -> bytes:
        nonlocal state
        global_params = decode(frame, like=state.params)
        state = state.replace(params=global_params)
        batches = engine.epoch_batches(
            state.rng, x[:n], y[:n], batch_size, n_steps=local_steps
        )
        state, losses, _, _ = train(state, None, batches)
        return encode({
            "params": state.params,
            "n": jnp.asarray(float(n)),
            "loss": losses["backward"],
            "accuracy": holdout_accuracy(state.params, state.model_state),
        })

    return handler


def serve_silo(seed: int, batch_size: int, local_steps: int,
               learning_rate: float, host: str = "0.0.0.0", port: int = 0):
    handler = make_silo_handler(seed, batch_size, local_steps, learning_rate)
    return LoopbackServer(handler, host=host, port=port)


def coordinate_round(addrs: list[tuple[str, int]], global_params):
    """One FedAvg round over the wire: broadcast → local fit → weighted merge.
    Silo RPCs fan out concurrently (the containers train in parallel; round
    latency is the slowest silo, not the sum) — hence the thread pool here
    instead of transport.broadcast_round's sequential loop; the merge IS the
    shared helper."""
    from fl4health_tpu.transport import weighted_merge

    frame = encode(global_params)
    like = {"params": global_params, "n": jnp.asarray(0.0),
            "loss": jnp.asarray(0.0), "accuracy": jnp.asarray(0.0)}
    with ThreadPoolExecutor(max_workers=len(addrs)) as pool:
        results = list(pool.map(
            lambda addr: decode(call(addr[0], addr[1], frame, timeout=120.0),
                                like=like),
            addrs,
        ))
    merged, weights = weighted_merge(results)
    stats = {
        "fit_loss": float(np.average([float(r["loss"]) for r in results],
                                     weights=weights)),
        "accuracy": float(np.average([float(r["accuracy"]) for r in results],
                                     weights=weights)),
    }
    return merged, stats


def init_global_params(seed: int = 0):
    logic = build_logic()
    x = np.zeros((1, DIM), np.float32)
    params, _ = logic.model.init(jax.random.PRNGKey(seed), x)
    return params

"""Dockerized basic example, exercised in-process (reference: examples/docker_basic_example).

The SAME node code (fl_nodes.py) that the Dockerfile/compose deployment runs
as containers is hosted here as threads over real TCP sockets, so the wire
path is identical — only the process packaging differs.

Run:  python examples/docker_basic_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/docker_basic_example/run.py
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent))

import _lib as lib  # noqa: E402
import fl_nodes  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

silos = [
    fl_nodes.serve_silo(
        seed=10 + i,
        batch_size=cfg["batch_size"],
        local_steps=cfg["local_steps"],
        learning_rate=cfg["learning_rate"],
        host="127.0.0.1",
    )
    for i in range(cfg["n_clients"])
]
try:
    addrs = [(s.host, s.port) for s in silos]
    params = fl_nodes.init_global_params()
    last = None
    for rnd in range(1, cfg["n_server_rounds"] + 1):
        params, stats = fl_nodes.coordinate_round(addrs, params)
        last = stats
        print(json.dumps({"round": rnd,
                          "fit_loss": round(stats["fit_loss"], 5),
                          "eval_accuracy": round(stats["accuracy"], 5)}))
    print(json.dumps({"final": True, "rounds": cfg["n_server_rounds"],
                      "eval_accuracy": round(last["accuracy"], 5)}))
finally:
    for s in silos:
        s.close()

"""Server container entrypoint: coordinate FedAvg rounds over client silos.

Env: FL_CLIENTS — comma-separated host:port list (default
"client1:8081,client2:8081"); FL_ROUNDS (default 5).
"""

import json
import os
import socket
import time

import fl_nodes

addrs = []
for spec in os.environ.get("FL_CLIENTS", "client1:8081,client2:8081").split(","):
    host, port = spec.rsplit(":", 1)
    addrs.append((host, int(port)))

# Wait for silo containers to come up.
deadline = time.time() + 120
for host, port in addrs:
    while True:
        try:
            socket.create_connection((host, port), timeout=2).close()
            break
        except OSError:
            if time.time() > deadline:
                raise TimeoutError(f"silo {host}:{port} never came up")
            time.sleep(1)

params = fl_nodes.init_global_params()
for rnd in range(1, int(os.environ.get("FL_ROUNDS", 5)) + 1):
    params, stats = fl_nodes.coordinate_round(addrs, params)
    print(json.dumps({"round": rnd, **stats}), flush=True)
print(json.dumps({"final": True}), flush=True)

"""Client container entrypoint: serve one silo on a fixed port.

Env: FL_PORT (default 8081), FL_SEED (default 1), FL_BATCH_SIZE, FL_LOCAL_STEPS,
FL_LEARNING_RATE.
"""

import os
import time

import fl_nodes

server = fl_nodes.serve_silo(
    seed=int(os.environ.get("FL_SEED", 1)),
    batch_size=int(os.environ.get("FL_BATCH_SIZE", 8)),
    local_steps=int(os.environ.get("FL_LOCAL_STEPS", 5)),
    learning_rate=float(os.environ.get("FL_LEARNING_RATE", 0.1)),
    host="0.0.0.0",
    port=int(os.environ.get("FL_PORT", 8081)),
)
print(f"silo ready on :{server.port}", flush=True)
while True:
    time.sleep(3600)

"""Tabular feature alignment: two-poll schema negotiation then federated training (reference: examples/feature_alignment_example).

Run:  python examples/feature_alignment_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/feature_alignment_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

import numpy as np
import pandas as pd
from fl4health_tpu.feature_alignment.orchestration import (
    TabularDataClient, TabularFeatureAlignmentServer,
)
from fl4health_tpu.models.cnn import Mlp
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg

def frame(n, seed, drop=False):
    r = np.random.default_rng(seed)
    age = r.uniform(20, 90, n); bp = r.uniform(90, 180, n)
    sex = r.choice(["F", "M"], n)
    score = (age / 90 + (bp - 90) / 90 + (sex == "M") * 0.3) / 2.3
    y = (score + r.normal(0, 0.15, n) > 0.55).astype(int).astype(str)
    d = {"pid": np.arange(n), "age": age, "bp": bp, "sex": sex, "outcome": y}
    if drop:
        del d["bp"]
    return pd.DataFrame(d)

clients = [TabularDataClient(frame(60, s, drop=(s == 2)), "pid", ["outcome"])
           for s in (1, 2, 3)]

def builder(in_dim, out_dim, aligned_clients):
    datasets = []
    for c in aligned_clients:
        x, y = c.aligned_arrays()
        y = y.astype(np.int32)
        datasets.append(ClientDataset(x[:48], y[:48], x[48:], y[48:]))
    return FederatedSimulation(
        logic=engine.ClientLogic(
            engine.from_flax(Mlp(features=(16,), n_outputs=out_dim)),
            engine.masked_cross_entropy,
        ),
        tx=optax.adam(5e-3),
        strategy=FedAvg(),
        datasets=datasets,
        batch_size=cfg["batch_size"],
        metrics=lib.accuracy_metrics(),
        local_steps=5,
        seed=0,
    )

server = TabularFeatureAlignmentServer({}, clients, builder)
lib.run_and_report(server, cfg)

"""Constrained FENDA: parallel local/global extractors with cosine + contrastive constraints (reference: examples/fenda_example).

Run:  python examples/fenda_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/fenda_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

from fl4health_tpu.clients.fenda import ConstrainedFendaClientLogic
from fl4health_tpu.exchange.exchanger import FixedLayerExchanger
from fl4health_tpu.models import bases
from fl4health_tpu.server.simulation import FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg

model = bases.FendaModel(
    first_feature_extractor=bases.DenseFeatures((32,)),
    second_feature_extractor=bases.DenseFeatures((32,)),
    head_module=bases.HeadModule(head=bases.DenseHead(10)),
)
sim = FederatedSimulation(
    logic=ConstrainedFendaClientLogic(
        engine.from_flax(model), engine.masked_cross_entropy,
        cos_sim_loss_weight=cfg["cos_sim_weight"],
        contrastive_loss_weight=cfg["contrastive_weight"],
    ),
    tx=optax.sgd(cfg["learning_rate"]),
    strategy=FedAvg(),
    datasets=lib.mnist_client_datasets(cfg),
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_epochs=cfg["local_epochs"],
    seed=42,
    exchanger=FixedLayerExchanger(bases.ParallelSplitModel.exchange_global_extractor),
)
lib.run_and_report(sim, cfg)

"""Shared plumbing for the examples corpus.

Role of the reference's per-example boilerplate
(/root/reference/examples/*/server.py + client.py + config.yaml, SURVEY
Appendix A): each example here is ONE ``run.py`` (the cohort is a single
SPMD program — there is no server/client process split to script) plus the
same-shaped ``config.yaml``. This module carries the shared pieces: config
loading, dataset construction (real MNIST from disk when present, else the
deterministic synthetic corpus — explicitly, never silently), model
builders, and the run/report loop.
"""

from __future__ import annotations

import json
import os
import sys
from pathlib import Path

import jax
import numpy as np

REPO = Path(__file__).resolve().parent.parent
if str(REPO) not in sys.path:
    sys.path.insert(0, str(REPO))

from fl4health_tpu.utils.bootstrap import honor_cpu_platform_request  # noqa: E402

honor_cpu_platform_request()

from fl4health_tpu.clients import engine  # noqa: E402
from fl4health_tpu.datasets.partitioners import DirichletLabelBasedAllocation  # noqa: E402
from fl4health_tpu.datasets.synthetic import synthetic_classification  # noqa: E402
from fl4health_tpu.datasets.vision import federated_client_datasets  # noqa: E402
from fl4health_tpu.metrics import efficient  # noqa: E402
from fl4health_tpu.metrics.base import MetricManager  # noqa: E402
from fl4health_tpu.models.cnn import MnistNet, Mlp  # noqa: E402
from fl4health_tpu.utils.config import load_config  # noqa: E402

MNIST_DATA_DIR = Path(os.environ.get("FL4HEALTH_MNIST_DIR", "/root/data/mnist"))


def example_config(example_dir: str | Path) -> dict:
    """Load the example's config.yaml with env overrides for smoke tests
    (FL4HEALTH_EXAMPLE_ROUNDS / _CLIENTS shrink any example)."""
    cfg = load_config(str(Path(example_dir) / "config.yaml"))
    if os.environ.get("FL4HEALTH_EXAMPLE_ROUNDS"):
        cfg["n_server_rounds"] = int(os.environ["FL4HEALTH_EXAMPLE_ROUNDS"])
    if os.environ.get("FL4HEALTH_EXAMPLE_CLIENTS"):
        cfg["n_clients"] = int(os.environ["FL4HEALTH_EXAMPLE_CLIENTS"])
    return cfg


def mnist_client_datasets(cfg: dict, image_hw: int = 14):
    """Dirichlet-partitioned MNIST-shaped client datasets. Real MNIST is used
    when present on disk; otherwise the seeded synthetic corpus (stated on
    stdout so runs are never silently synthetic)."""
    n_clients = int(cfg.get("n_clients", 4))
    if os.environ.get("FL4HEALTH_EXAMPLE_TINY"):
        # smoke-test mode: quarter-size synthetic data, fastest compile
        x, y = synthetic_classification(
            jax.random.PRNGKey(0), 240, (8, 8, 1), 10, class_sep=1.5
        )
        x, y = np.asarray(x), np.asarray(y)
        print("# data: tiny synthetic corpus (FL4HEALTH_EXAMPLE_TINY)")
        # near-uniform allocation: 240 samples over 10 labels can't honor
        # min_label_examples under a skewed draw at 4+ partitions
        partitioner = DirichletLabelBasedAllocation(
            number_of_partitions=n_clients, unique_labels=list(range(10)),
            beta=5.0, min_label_examples=1, hash_key=42,
        )
        return federated_client_datasets(
            x, y, n_clients=n_clients, partitioner=partitioner, hash_key=7
        )
    try:
        from fl4health_tpu.datasets.vision import load_mnist_arrays

        # load_mnist_arrays already returns [N,28,28,1] float32 normalized
        x, y = load_mnist_arrays(MNIST_DATA_DIR, train=True)
        x = np.asarray(x, np.float32)
        y = np.asarray(y, np.int64)
        idx = np.random.default_rng(0).permutation(len(x))[:2048]
        x, y = x[idx], y[idx]
        print(f"# data: real MNIST from {MNIST_DATA_DIR}")
    except (FileNotFoundError, OSError):
        x, y = synthetic_classification(
            jax.random.PRNGKey(0), 960, (image_hw, image_hw, 1), 10, class_sep=1.2
        )
        x, y = np.asarray(x), np.asarray(y)
        print("# data: synthetic MNIST-shaped corpus (no real MNIST on disk)")
    partitioner = DirichletLabelBasedAllocation(
        number_of_partitions=n_clients, unique_labels=list(range(10)),
        beta=float(cfg.get("dirichlet_beta", 0.8)), min_label_examples=1,
        hash_key=42,
    )
    return federated_client_datasets(
        x, y, n_clients=n_clients, partitioner=partitioner, hash_key=7
    )


def mnist_model(cfg: dict):
    return engine.from_flax(MnistNet(hidden=int(cfg.get("hidden", 32))))


def mlp_model(cfg: dict, n_outputs: int = 10):
    return engine.from_flax(
        Mlp(features=(int(cfg.get("hidden", 32)),), n_outputs=n_outputs)
    )


def accuracy_metrics() -> MetricManager:
    return MetricManager((efficient.accuracy(),))


def run_and_report(sim_or_server, cfg: dict, **fit_kwargs):
    """fit + per-round JSON lines on stdout (the JsonReporter role the
    reference smoke tests scrape, reporting/base.py is the in-library path)."""
    n_rounds = int(cfg.get("n_server_rounds", 3))
    history = sim_or_server.fit(n_rounds, **fit_kwargs)
    if isinstance(history, tuple):  # DP servers return (history, epsilon)
        history, epsilon = history
        print(json.dumps({"epsilon": round(float(epsilon), 4)}))

    def headline_metric(rec) -> tuple[str, float]:
        # accuracy when present; otherwise the config's own lead metric
        # (e.g. seg_dice for the nnU-Net example); metric-less SSL configs
        # report their eval loss
        metrics = rec.eval_metrics
        if "accuracy" in metrics:
            return "accuracy", metrics["accuracy"]
        if metrics:
            key = sorted(metrics)[0]
            return key, metrics[key]
        return "loss", rec.eval_losses.get("checkpoint", float("nan"))

    for rec in history:
        name, value = headline_metric(rec)
        print(
            json.dumps(
                {
                    "round": rec.round,
                    "fit_loss": round(rec.fit_losses.get("backward", float("nan")), 5),
                    "eval_loss": round(rec.eval_losses.get("checkpoint", float("nan")), 5),
                    f"eval_{name}": round(value, 5),
                }
            )
        )
    name, value = headline_metric(history[-1])
    print(
        json.dumps(
            {"final": True, "rounds": len(history), f"eval_{name}": round(value, 5)}
        )
    )
    return history

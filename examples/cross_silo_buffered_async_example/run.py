"""Buffered-async cross-silo rounds with COMPRESSED wire frames.

Run:  python examples/cross_silo_buffered_async_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 python examples/cross_silo_buffered_async_example/run.py

The two wire features PR 6 and PR 9 added, composed over the REAL
coordinator path:

- every silo ships its update as a COMPRESSED frame
  (``encode_compressed``: global top-k + int8 quantization, CRC-checked
  framing) and the coordinator decodes it with ``decode_compressed``
  through ``SiloUpdateBuffer``'s pluggable decoder — the same
  retry/metrics machinery dense frames ride;
- the coordinator does NOT barrier on the slowest silo: a
  ``SiloUpdateBuffer`` collects replies as they arrive and the server
  aggregates as soon as ``buffer_size`` updates are in (FedBuff-style),
  staleness-discounting updates that trained from an older server
  version (``1/sqrt(1+staleness)``, the same rule the in-process async
  mode uses). One silo is made a straggler with ``chaos_handler``'s
  deterministic delay, so slow updates genuinely arrive stale.
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from fl4health_tpu.clients import engine  # noqa: E402
from fl4health_tpu.compression.config import CompressionConfig  # noqa: E402
from fl4health_tpu.datasets.synthetic import synthetic_classification  # noqa: E402
from fl4health_tpu.models.cnn import Mlp  # noqa: E402
from fl4health_tpu.resilience.faults import (  # noqa: E402
    TransportFaultPolicy,
    chaos_handler,
)
from fl4health_tpu.server.async_schedule import staleness_discount  # noqa: E402
from fl4health_tpu.transport import (  # noqa: E402
    LoopbackServer,
    SiloUpdateBuffer,
    decode,
    encode,
)
from fl4health_tpu.transport.codec import (  # noqa: E402
    decode_compressed,
    encode_compressed,
)

cfg = lib.example_config(Path(__file__).parent)
N_SILOS = 4
K = int(cfg.get("buffer_size", 2))
COMP = CompressionConfig(topk_fraction=0.25, quant_bits=8)

module = Mlp(features=(16,), n_outputs=3)
model = engine.from_flax(module)
criterion = engine.masked_cross_entropy
logic = engine.ClientLogic(model, criterion)
tx = optax.sgd(cfg["learning_rate"])
init_params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 6)))[0]


def make_silo(seed: int, slow: bool):
    """One remote hospital: private data, local training, COMPRESSED
    update frames. ``slow`` silos straggle behind a deterministic
    chaos delay — their updates arrive stale at the buffer."""
    x, y = synthetic_classification(
        jax.random.PRNGKey(seed), 48, (6,), 3, class_sep=2.0
    )
    state = engine.create_train_state(logic, tx, jax.random.PRNGKey(seed), x[:1])
    train = jax.jit(engine.make_local_train(logic, tx, lib.accuracy_metrics()))
    n = 40

    def handler(frame: bytes) -> bytes:
        nonlocal state
        global_params = decode(frame, like=state.params)
        state = state.replace(params=global_params)
        batches = engine.epoch_batches(
            state.rng, x[:n], y[:n], cfg["batch_size"],
            n_steps=cfg["local_steps"],
        )
        state, _losses, _metrics, _ = train(state, None, batches)
        delta = jax.tree_util.tree_map(
            lambda t, g: np.asarray(t - g, np.float32),
            state.params, global_params,
        )
        return encode_compressed(delta, COMP)

    if slow:
        handler = chaos_handler(
            handler,
            TransportFaultPolicy(delay_s=0.2, delay_probability=1.0),
            seed=0, silo_idx=seed,
        )
    return LoopbackServer(handler), n


silos = [make_silo(s, slow=(s == N_SILOS - 1)) for s in range(N_SILOS)]
addrs = [(srv.host, srv.port) for srv, _ in silos]
counts = {f"{h}:{p}": float(n) for (h, p), (_, n) in zip(addrs, silos)}

# coordinator-held validation set (public split) to score the global model
val_x, val_y = synthetic_classification(
    jax.random.PRNGKey(99), 64, (6,), 3, class_sep=2.0
)


def float_loss(params):
    (preds, _features), _state = model.apply(params, None, val_x, train=False)
    logits = preds["prediction"]
    one_hot = jax.nn.one_hot(val_y, 3)
    return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * one_hot, axis=-1))


buffer = SiloUpdateBuffer(
    reply_template=init_params,
    decoder=lambda raw: decode_compressed(raw, like=init_params),
)
global_params = init_params
version = 0
silo_version = {a: 0 for a in addrs}
try:
    buffer.dispatch(addrs, global_params, version)
    dense_bytes = len(encode(init_params))
    for event in range(1, int(cfg["n_server_rounds"]) + 1):
        arrivals = buffer.take(K, timeout=60.0)
        stal = [float(version - a.version) for a in arrivals]
        disc = staleness_discount(np.asarray(stal))
        w = np.asarray(
            [counts[a.result.silo] for a in arrivals]
        ) * np.asarray(disc)
        w = w / w.sum()
        merged_delta = jax.tree_util.tree_map(
            lambda *leaves: sum(wi * leaf for wi, leaf in zip(w, leaves)),
            *[a.reply for a in arrivals],
        )
        global_params = jax.tree_util.tree_map(
            lambda g, d: g + d, global_params, merged_delta
        )
        version += 1
        # consumed silos pull the fresh version and train again
        consumed = [
            next(a for a in addrs if f"{a[0]}:{a[1]}" == r.result.silo)
            for r in arrivals
        ]
        buffer.dispatch(consumed, global_params, version)
        print(json.dumps({
            "event": event,
            "arrived": [a.result.silo.split(":")[-1] for a in arrivals],
            "staleness": stal,
            "val_loss": round(float(float_loss(global_params)), 5),
        }))
finally:
    buffer.close()
    for srv, _ in silos:
        srv.close()

comp_bytes = len(encode_compressed(
    jax.tree_util.tree_map(lambda a: np.asarray(a, np.float32), init_params),
    COMP,
))
print(json.dumps({
    "final": True,
    "events": int(cfg["n_server_rounds"]),
    "buffer_size": K,
    "wire_bytes_dense": dense_bytes,
    "wire_bytes_compressed": comp_bytes,
    "wire_ratio": round(dense_bytes / comp_bytes, 2),
}))

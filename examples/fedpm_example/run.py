"""FedPM probabilistic-mask training with Beta-posterior aggregation (reference: examples/fedpm_example).

Run:  python examples/fedpm_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/fedpm_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

from fl4health_tpu.clients.fedpm import FedPmClientLogic
from fl4health_tpu.models.masked import MaskedMlp
from fl4health_tpu.server.simulation import FederatedSimulation
from fl4health_tpu.strategies.fedpm import FedPm

model = MaskedMlp(features=(64,), n_outputs=10)
sim = FederatedSimulation(
    logic=FedPmClientLogic(engine.from_flax(model), engine.masked_cross_entropy),
    tx=optax.sgd(cfg["learning_rate"]),
    strategy=FedPm(),
    datasets=lib.mnist_client_datasets(cfg),
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_epochs=cfg["local_epochs"],
    seed=42,
)
lib.run_and_report(sim, cfg)

"""Cross-silo federated round over the host RPC wire (reference deploy mode:
one process per hospital over Flower gRPC, research/fedprox_cluster/
run_fl_cluster.sh; here: TCP loopback silos + the transport codec).

Run:  python examples/cross_silo_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 python examples/cross_silo_example/run.py

Each "silo" is a LoopbackServer owning private data; the coordinator ships
global params as a wire frame (native C++ framing + CRC when available),
each silo trains locally and returns its update + sample count; the
coordinator FedAvg-merges on the host. No silo's raw data ever crosses.
"""

import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from fl4health_tpu.clients import engine  # noqa: E402
from fl4health_tpu.datasets.synthetic import synthetic_classification  # noqa: E402
from fl4health_tpu.models.cnn import Mlp  # noqa: E402
from fl4health_tpu.transport import (  # noqa: E402
    LoopbackServer,
    broadcast_round,
    decode,
    encode,
    weighted_merge,
)

cfg = lib.example_config(Path(__file__).parent)

module = Mlp(features=(16,), n_outputs=3)
model = engine.from_flax(module)
criterion = engine.masked_cross_entropy
logic = engine.ClientLogic(model, criterion)
tx = optax.sgd(cfg["learning_rate"])


def make_silo(seed: int):
    """One remote hospital: private data + a local training handler."""
    x, y = synthetic_classification(jax.random.PRNGKey(seed), 48, (6,), 3, class_sep=2.0)
    state = engine.create_train_state(logic, tx, jax.random.PRNGKey(seed), x[:1])
    train = jax.jit(engine.make_local_train(logic, tx, lib.accuracy_metrics()))
    n = 40

    def handler(frame: bytes) -> bytes:
        nonlocal state
        global_params = decode(frame, like=state.params)
        state = state.replace(params=global_params)
        batches = engine.epoch_batches(
            state.rng, x[:n], y[:n], cfg["batch_size"], n_steps=cfg["local_steps"]
        )
        state, losses, metrics, _ = train(state, None, batches)
        return encode(
            {
                "params": state.params,
                "n": jnp.asarray(float(n)),
                "loss": losses["backward"],
            }
        )

    return LoopbackServer(handler), n


silos = [make_silo(s) for s in (1, 2, 3)]
init_params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 6)))[0]
reply_template = {
    "params": init_params, "n": jnp.zeros(()), "loss": jnp.zeros(()),
}

global_params = init_params
try:
    for rnd in range(1, int(cfg["n_server_rounds"]) + 1):
        replies = broadcast_round(
            [(srv.host, srv.port) for srv, _ in silos],
            global_params, reply_template,
        )
        global_params, _ = weighted_merge(replies)
        mean_loss = float(np.mean([float(r["loss"]) for r in replies]))
        print(json.dumps({"round": rnd, "fit_loss": round(mean_loss, 5)}))
finally:
    for srv, _ in silos:
        srv.close()
print(json.dumps({"final": True, "rounds": int(cfg["n_server_rounds"])}))

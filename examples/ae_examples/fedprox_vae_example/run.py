"""FedProx + federated VAE training (reference:
examples/ae_examples/fedprox_vae_example — VAE clients under the adaptive
proximal constraint).

Run:  python examples/ae_examples/fedprox_vae_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/ae_examples/fedprox_vae_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

import jax
import jax.numpy as jnp
from flax import linen as nn
from fl4health_tpu.models.autoencoders import VariationalAe, make_vae_loss
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.clients.fedprox import FedProxClientLogic
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedprox import FedAvgWithAdaptiveConstraint

latent = cfg["latent_dim"]
base = lib.mnist_client_datasets(cfg)
flat_dim = int(jnp.prod(jnp.asarray(base[0].x_train.shape[1:])))
datasets = [
    ClientDataset(
        x_train=jnp.asarray(d.x_train).reshape(len(d.x_train), -1),
        y_train=jnp.asarray(d.x_train).reshape(len(d.x_train), -1),
        x_val=jnp.asarray(d.x_val).reshape(len(d.x_val), -1),
        y_val=jnp.asarray(d.x_val).reshape(len(d.x_val), -1),
    )
    for d in base
]

class Enc(nn.Module):
    @nn.compact
    def __call__(self, x, train=True):
        h = nn.relu(nn.Dense(32)(x))
        return nn.Dense(latent)(h), nn.Dense(latent)(h)

class Dec(nn.Module):
    @nn.compact
    def __call__(self, z, train=True):
        return nn.Dense(flat_dim)(nn.relu(nn.Dense(32)(z)))

def mse(preds, targets, mask):
    per = jnp.mean((preds - targets) ** 2, axis=-1)
    return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)

sim = FederatedSimulation(
    logic=FedProxClientLogic(
        engine.from_flax(VariationalAe(encoder=Enc(), decoder=Dec())),
        make_vae_loss(latent, mse),
    ),
    tx=optax.adam(cfg["learning_rate"]),
    strategy=FedAvgWithAdaptiveConstraint(
        initial_drift_penalty_weight=cfg["initial_mu"]
    ),
    datasets=datasets,
    batch_size=cfg["batch_size"],
    metrics=MetricManager(()),
    local_epochs=cfg["local_epochs"],
    seed=11,
    extra_loss_keys=("vanilla", "penalty"),
)
lib.run_and_report(sim, cfg)

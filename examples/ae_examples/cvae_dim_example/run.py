"""CVAE dimensionality-reduction preprocessing for downstream FL
(reference: examples/ae_examples/cvae_dim_example — each client encodes its
data through a trained CVAE encoder with a FIXED per-client condition via
CvaeFixedConditionProcessor, then trains a classifier federally on the
latents).

Two stages in one script (the reference ships the trained CVAE as a
checkpoint; here stage 1 trains it in-process so the flow is end-to-end):
  1. federated CVAE training via AutoEncoderDatasetConverter with a FIXED
     condition per client (client one-hot — the converter's fixed-array
     path, utils/dataset_converter.py:169);
  2. CvaeFixedConditionProcessor (preprocessing/autoencoders.py) encodes
     every client's images to latent mu's; FedAvg MLP classifies latents.

Run:  python examples/ae_examples/cvae_dim_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/ae_examples/cvae_dim_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from _cvae_lib import CondDec, CondEnc, mse  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models.autoencoders import ConditionalVae, make_vae_loss
from fl4health_tpu.models.cnn import Mlp
from fl4health_tpu.preprocessing.autoencoders import (
    AutoEncoderDatasetConverter,
    CvaeFixedConditionProcessor,
)
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg

latent = cfg["latent_dim"]
base = lib.mnist_client_datasets(cfg)
n_clients = len(base)
flat_dim = int(jnp.prod(jnp.asarray(base[0].x_train.shape[1:])))

# One converter per client: the FIXED condition is the client's one-hot id
# (the reference conditions its CVAE on client membership for dim-reduction).
converters = [
    AutoEncoderDatasetConverter(condition=jax.nn.one_hot(i, n_clients))
    for i in range(n_clients)
]
cvae_datasets = []
for conv, d in zip(converters, base):
    x_tr, t_tr = conv.convert_dataset(jnp.asarray(d.x_train),
                                      jnp.asarray(d.y_train))
    x_va, t_va = conv.convert_dataset(jnp.asarray(d.x_val),
                                      jnp.asarray(d.y_val))
    cvae_datasets.append(ClientDataset(x_train=x_tr, y_train=t_tr,
                                       x_val=x_va, y_val=t_va))

cvae = ConditionalVae(
    encoder=CondEnc(latent), decoder=CondDec(flat_dim),
    unpack_input_condition=converters[0].get_unpacking_function(),
)
stage1 = FederatedSimulation(
    logic=engine.ClientLogic(engine.from_flax(cvae), make_vae_loss(latent, mse)),
    tx=optax.adam(cfg["learning_rate"]),
    strategy=FedAvg(),
    datasets=cvae_datasets,
    batch_size=cfg["batch_size"],
    metrics=MetricManager(()),
    local_epochs=cfg["local_epochs"],
    seed=17,
)
stage1.fit(int(cfg["n_server_rounds"]))
cvae_params = jax.device_get(stage1.strategy.global_params(stage1.server_state))
print('{"stage": "cvae_trained"}')


# Stage 2: encode every client's data with its fixed condition, then
# federated classification on the latents.
def encode_fn(x, cond):
    packed = jnp.concatenate([x, cond], axis=1)
    (_, feats), _ = engine.from_flax(cvae).apply(
        cvae_params, {}, packed, train=False,
        rng=jax.random.PRNGKey(0),
    )
    return feats["mu"], feats["logvar"]


latent_datasets = []
for i, d in enumerate(base):
    proc = CvaeFixedConditionProcessor(
        encode_fn, jax.nn.one_hot(i, n_clients), return_mu_only=True
    )
    latent_datasets.append(ClientDataset(
        x_train=proc(jnp.asarray(d.x_train).reshape(len(d.x_train), -1)),
        y_train=jnp.asarray(d.y_train),
        x_val=proc(jnp.asarray(d.x_val).reshape(len(d.x_val), -1)),
        y_val=jnp.asarray(d.y_val),
    ))

stage2 = FederatedSimulation(
    logic=engine.ClientLogic(
        engine.from_flax(Mlp(features=(32,), n_outputs=10)),
        engine.masked_cross_entropy,
    ),
    tx=optax.adam(cfg["learning_rate"]),
    strategy=FedAvg(),
    datasets=latent_datasets,
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_epochs=cfg["local_epochs"],
    seed=19,
)
lib.run_and_report(stage2, cfg)

"""CVAE dimensionality-reduction preprocessing for downstream FL
(reference: examples/ae_examples/cvae_dim_example — each client encodes its
data through a trained CVAE encoder with a FIXED per-client condition via
CvaeFixedConditionProcessor, then trains a classifier federally on the
latents).

Two stages in one script (the reference ships the trained CVAE as a
checkpoint; here stage 1 trains it in-process so the flow is end-to-end):
  1. federated CVAE training, condition = client one-hot;
  2. CvaeFixedConditionProcessor(preprocessing/autoencoders.py) encodes
     every client's images to latent mu's; FedAvg MLP classifies latents.

Run:  python examples/ae_examples/cvae_dim_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/ae_examples/cvae_dim_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

import jax
import jax.numpy as jnp
from flax import linen as nn

from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models.autoencoders import ConditionalVae, make_vae_loss
from fl4health_tpu.models.cnn import Mlp
from fl4health_tpu.preprocessing.autoencoders import CvaeFixedConditionProcessor
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg

latent = cfg["latent_dim"]
base = lib.mnist_client_datasets(cfg)
n_clients = len(base)
flat_dim = int(jnp.prod(jnp.asarray(base[0].x_train.shape[1:])))


def pack(x, client_idx):
    flat = jnp.asarray(x).reshape(len(x), -1)
    cond = jnp.broadcast_to(
        jax.nn.one_hot(client_idx, n_clients)[None, :], (len(flat), n_clients)
    )
    return jnp.concatenate([flat, cond], axis=1)


cvae_datasets = [
    ClientDataset(
        x_train=pack(d.x_train, i),
        y_train=jnp.asarray(d.x_train).reshape(len(d.x_train), -1),
        x_val=pack(d.x_val, i),
        y_val=jnp.asarray(d.x_val).reshape(len(d.x_val), -1),
    )
    for i, d in enumerate(base)
]


def unpack_input_condition(packed):
    return packed[:, :flat_dim], packed[:, flat_dim:]


class CondEnc(nn.Module):
    @nn.compact
    def __call__(self, x, condition, train=True):
        h = nn.relu(nn.Dense(32)(jnp.concatenate([x, condition], axis=1)))
        return nn.Dense(latent)(h), nn.Dense(latent)(h)


class CondDec(nn.Module):
    @nn.compact
    def __call__(self, z, condition, train=True):
        h = nn.relu(nn.Dense(32)(jnp.concatenate([z, condition], axis=1)))
        return nn.Dense(flat_dim)(h)


def mse(preds, targets, mask):
    per = jnp.mean((preds - targets) ** 2, axis=-1)
    return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)


cvae = ConditionalVae(encoder=CondEnc(), decoder=CondDec(),
                      unpack_input_condition=unpack_input_condition)
stage1 = FederatedSimulation(
    logic=engine.ClientLogic(engine.from_flax(cvae), make_vae_loss(latent, mse)),
    tx=optax.adam(cfg["learning_rate"]),
    strategy=FedAvg(),
    datasets=cvae_datasets,
    batch_size=cfg["batch_size"],
    metrics=MetricManager(()),
    local_epochs=cfg["local_epochs"],
    seed=17,
)
stage1.fit(int(cfg["n_server_rounds"]))
cvae_params = jax.device_get(stage1.strategy.global_params(stage1.server_state))
print('{"stage": "cvae_trained"}')


# Stage 2: encode every client's data with its fixed condition, then
# federated classification on the latents.
def encode_fn(x, cond):
    packed = jnp.concatenate([x, cond], axis=1)
    (_, feats), _ = engine.from_flax(cvae).apply(
        cvae_params, {}, packed, train=False,
        rng=jax.random.PRNGKey(0),
    )
    return feats["mu"], feats["logvar"]


latent_datasets = []
for i, d in enumerate(base):
    proc = CvaeFixedConditionProcessor(
        encode_fn, jax.nn.one_hot(i, n_clients), return_mu_only=True
    )
    latent_datasets.append(ClientDataset(
        x_train=proc(jnp.asarray(d.x_train).reshape(len(d.x_train), -1)),
        y_train=jnp.asarray(d.y_train),
        x_val=proc(jnp.asarray(d.x_val).reshape(len(d.x_val), -1)),
        y_val=jnp.asarray(d.y_val),
    ))

stage2 = FederatedSimulation(
    logic=engine.ClientLogic(
        engine.from_flax(Mlp(features=(32,), n_outputs=10)),
        engine.masked_cross_entropy,
    ),
    tx=optax.adam(cfg["learning_rate"]),
    strategy=FedAvg(),
    datasets=latent_datasets,
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_epochs=cfg["local_epochs"],
    seed=19,
)
lib.run_and_report(stage2, cfg)

"""Federated Conditional-VAE training (reference:
examples/ae_examples/cvae_examples/mlp_cvae_example — CVAE conditioned on a
per-sample one-hot, trained federally).

The condition (here the digit label, one-hot) is PACKED into the model
input and split back out by ``ConditionalVae.unpack_input_condition`` —
the reference's AutoEncoderDatasetConverter condition-packing contract
(utils/dataset_converter.py:68).

Run:  python examples/ae_examples/cvae_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/ae_examples/cvae_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

import jax.numpy as jnp
from flax import linen as nn

from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models.autoencoders import ConditionalVae, make_vae_loss
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg

latent = cfg["latent_dim"]
N_CLASSES = 10
base = lib.mnist_client_datasets(cfg)
flat_dim = int(jnp.prod(jnp.asarray(base[0].x_train.shape[1:])))


def pack(x, y):
    """[flat image | one-hot condition] — the converter's packed layout."""
    flat = jnp.asarray(x).reshape(len(x), -1)
    cond = jax.nn.one_hot(jnp.asarray(y), N_CLASSES)
    return jnp.concatenate([flat, cond], axis=1)


import jax  # noqa: E402

datasets = [
    ClientDataset(
        x_train=pack(d.x_train, d.y_train),
        y_train=jnp.asarray(d.x_train).reshape(len(d.x_train), -1),
        x_val=pack(d.x_val, d.y_val),
        y_val=jnp.asarray(d.x_val).reshape(len(d.x_val), -1),
    )
    for d in base
]


def unpack_input_condition(packed):
    return packed[:, :flat_dim], packed[:, flat_dim:]


class CondEnc(nn.Module):
    @nn.compact
    def __call__(self, x, condition, train=True):
        h = nn.relu(nn.Dense(32)(jnp.concatenate([x, condition], axis=1)))
        return nn.Dense(latent)(h), nn.Dense(latent)(h)


class CondDec(nn.Module):
    @nn.compact
    def __call__(self, z, condition, train=True):
        h = nn.relu(nn.Dense(32)(jnp.concatenate([z, condition], axis=1)))
        return nn.Dense(flat_dim)(h)


def mse(preds, targets, mask):
    per = jnp.mean((preds - targets) ** 2, axis=-1)
    return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)


sim = FederatedSimulation(
    logic=engine.ClientLogic(
        engine.from_flax(ConditionalVae(
            encoder=CondEnc(), decoder=CondDec(),
            unpack_input_condition=unpack_input_condition,
        )),
        make_vae_loss(latent, mse),
    ),
    tx=optax.adam(cfg["learning_rate"]),
    strategy=FedAvg(),
    datasets=datasets,
    batch_size=cfg["batch_size"],
    metrics=MetricManager(()),
    local_epochs=cfg["local_epochs"],
    seed=13,
)
lib.run_and_report(sim, cfg)

"""Federated Conditional-VAE training (reference:
examples/ae_examples/cvae_examples/mlp_cvae_example — CVAE conditioned on a
per-sample one-hot label, trained federally).

The condition is packed into the model input by
``AutoEncoderDatasetConverter`` and split back out by the converter's own
unpacking function wired into ``ConditionalVae.unpack_input_condition`` —
the reference's converter contract (utils/dataset_converter.py:68). A
custom converter pins the one-hot width to 10 so non-IID clients missing
some digits still agree on the condition size.

Run:  python examples/ae_examples/cvae_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/ae_examples/cvae_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))
sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from _cvae_lib import CondDec, CondEnc, mse  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models.autoencoders import ConditionalVae, make_vae_loss
from fl4health_tpu.preprocessing.autoencoders import AutoEncoderDatasetConverter
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg

latent = cfg["latent_dim"]
N_CLASSES = 10
base = lib.mnist_client_datasets(cfg)
flat_dim = int(jnp.prod(jnp.asarray(base[0].x_train.shape[1:])))

converter = AutoEncoderDatasetConverter(
    custom_converter=lambda x, y: (
        jnp.concatenate(
            [x.reshape(x.shape[0], -1), jax.nn.one_hot(y, N_CLASSES)], axis=1
        ),
        x,
    ),
    condition_vector_size=N_CLASSES,
)

datasets = []
for d in base:
    x_tr, t_tr = converter.convert_dataset(jnp.asarray(d.x_train),
                                           jnp.asarray(d.y_train))
    x_va, t_va = converter.convert_dataset(jnp.asarray(d.x_val),
                                           jnp.asarray(d.y_val))
    datasets.append(ClientDataset(x_train=x_tr, y_train=t_tr,
                                  x_val=x_va, y_val=t_va))

sim = FederatedSimulation(
    logic=engine.ClientLogic(
        engine.from_flax(ConditionalVae(
            encoder=CondEnc(latent), decoder=CondDec(flat_dim),
            unpack_input_condition=converter.get_unpacking_function(),
        )),
        make_vae_loss(latent, mse),
    ),
    tx=optax.adam(cfg["learning_rate"]),
    strategy=FedAvg(),
    datasets=datasets,
    batch_size=cfg["batch_size"],
    metrics=MetricManager(()),
    local_epochs=cfg["local_epochs"],
    seed=13,
)
lib.run_and_report(sim, cfg)

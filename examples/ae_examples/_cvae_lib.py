"""Shared CVAE pieces for the ae_examples flows (role of the reference's
shared example models; both CVAE examples wire these through
AutoEncoderDatasetConverter's packing contract)."""

import jax.numpy as jnp
from flax import linen as nn


class CondEnc(nn.Module):
    latent: int

    @nn.compact
    def __call__(self, x, condition, train=True):
        x = x.reshape(x.shape[0], -1)
        h = nn.relu(nn.Dense(32)(jnp.concatenate([x, condition], axis=1)))
        return nn.Dense(self.latent)(h), nn.Dense(self.latent)(h)


class CondDec(nn.Module):
    out_dim: int

    @nn.compact
    def __call__(self, z, condition, train=True):
        h = nn.relu(nn.Dense(32)(jnp.concatenate([z, condition], axis=1)))
        return nn.Dense(self.out_dim)(h)


def mse(preds, targets, mask):
    # make_vae_loss reshapes recon to the target's (image) shape; compare
    # flat either way
    preds = preds.reshape(preds.shape[0], -1)
    targets = targets.reshape(targets.shape[0], -1)
    per = jnp.mean((preds - targets) ** 2, axis=-1)
    return jnp.sum(per * mask) / jnp.maximum(jnp.sum(mask), 1.0)

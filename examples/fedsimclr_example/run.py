"""FedSimCLR SSL pretraining with NT-Xent (reference: examples/fedsimclr_example).

Run:  python examples/fedsimclr_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/fedsimclr_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

import jax
import numpy as np
from fl4health_tpu.clients.fedsimclr import FedSimClrClientLogic
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models import bases
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg

# SSL pretraining pairs: y carries the augmented view of x.
base = lib.mnist_client_datasets(cfg)
datasets = []
for i, d in enumerate(base):
    rng = np.random.default_rng(i)
    aug = lambda a: a + 0.05 * rng.normal(size=np.asarray(a).shape).astype(np.float32)  # noqa: E731
    datasets.append(ClientDataset(
        x_train=d.x_train, y_train=aug(d.x_train),
        x_val=d.x_val, y_val=aug(d.x_val),
    ))
model = bases.FedSimClrModel(
    encoder=bases.DenseFeatures((64,)), projection_head=bases.DenseHead(32),
    pretrain=True,
)
sim = FederatedSimulation(
    logic=FedSimClrClientLogic(engine.from_flax(model), temperature=0.5),
    tx=optax.adam(cfg["learning_rate"]),
    strategy=FedAvg(),
    datasets=datasets,
    batch_size=cfg["batch_size"],
    metrics=MetricManager(()),
    local_epochs=cfg["local_epochs"],
    seed=7,
)
lib.run_and_report(sim, cfg)

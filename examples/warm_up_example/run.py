"""Pre-FL warm-up weight injection (reference: examples/warm_up_example).

Run:  python examples/warm_up_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/warm_up_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

import jax
from fl4health_tpu.preprocessing.warm_up import WarmedUpModule
from fl4health_tpu.server.simulation import FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg

# Phase 1: local (non-federated) warm-up on client 0's data.
datasets = lib.mnist_client_datasets(cfg)
model = lib.mnist_model(cfg)
warm_sim = FederatedSimulation(
    logic=engine.ClientLogic(model, engine.masked_cross_entropy),
    tx=optax.sgd(cfg["learning_rate"]),
    strategy=FedAvg(),
    datasets=datasets[:1],
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_epochs=cfg["local_epochs"],
    seed=1,
)
warm_sim.fit(1)
pretrained = jax.device_get(warm_sim.global_params)

# Phase 2: federated run warm-started from the pretrained weights
# (warmed_up_module.py injection semantics).
sim = FederatedSimulation(
    logic=engine.ClientLogic(model, engine.masked_cross_entropy),
    tx=optax.sgd(cfg["learning_rate"]),
    strategy=FedAvg(),
    datasets=datasets,
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_epochs=cfg["local_epochs"],
    seed=42,
)
warm = WarmedUpModule(pretrained)
warmed = warm.load_from_pretrained(jax.device_get(sim.global_params))
sim.server_state = sim.server_state.replace(params=warmed)
lib.run_and_report(sim, cfg)

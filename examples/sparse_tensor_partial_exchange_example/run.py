"""Sparse top-score parameter exchange, COO on the wire (reference: examples/sparse_tensor_partial_exchange_example).

Run:  python examples/sparse_tensor_partial_exchange_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/sparse_tensor_partial_exchange_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

from fl4health_tpu.exchange.exchanger import SparseExchanger
from fl4health_tpu.server.simulation import FederatedSimulation
from fl4health_tpu.strategies.dynamic_layer import FedAvgSparse

sim = FederatedSimulation(
    logic=engine.ClientLogic(lib.mnist_model(cfg), engine.masked_cross_entropy),
    tx=optax.sgd(cfg["learning_rate"]),
    strategy=FedAvgSparse(),
    datasets=lib.mnist_client_datasets(cfg),
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_epochs=cfg["local_epochs"],
    seed=42,
    exchanger=SparseExchanger(sparsity_level=cfg["sparsity_level"]),
)
lib.run_and_report(sim, cfg)

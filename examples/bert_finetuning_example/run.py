"""BERT-class transformer fine-tuning with LoRA-only exchange + FedOpt (reference: examples/bert_finetuning_example + examples/fedllm_example).

Run:  python examples/bert_finetuning_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/bert_finetuning_example/run.py

Pretrained start: set ``pretrained_checkpoint`` in config.yaml (or the
FL4HEALTH_PRETRAINED_CHECKPOINT env var) to a .npz/.pt checkpoint; weights
are injected via the warm-up name surgery before federation begins — the
reference's "fine-tune an actually-pretrained model" role. The broadcast
covers the FULL tree, so frozen LoRA base kernels receive the pretrained
values even though they never cross the wire afterwards.
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

import jax
from fl4health_tpu.datasets.synthetic import synthetic_text_classification
from fl4health_tpu.models.transformer import TransformerClassifier
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedopt import FedOpt
from fl4health_tpu.utils.peft import lora_exchanger, lora_trainable_mask, masked_optimizer

model_module = TransformerClassifier(
    vocab_size=cfg["vocab_size"], n_classes=cfg["n_classes"], d_model=32,
    n_heads=2, n_layers=2, d_ff=64, max_len=cfg["seq_len"],
    lora_rank=cfg["lora_rank"],
)
model = engine.from_flax(model_module)
datasets = []
for i in range(cfg["n_clients"]):
    x, y = synthetic_text_classification(
        jax.random.PRNGKey(10 + i), 48, cfg["vocab_size"], cfg["seq_len"],
        cfg["n_classes"], class_sep=3.0,
    )
    datasets.append(ClientDataset(x[:32], y[:32], x[32:], y[32:]))
init_params = model.init(jax.random.PRNGKey(0), datasets[0].x_train[:1])[0]
sim = FederatedSimulation(
    logic=engine.ClientLogic(model, engine.masked_cross_entropy),
    tx=masked_optimizer(optax.adam(cfg["learning_rate"]),
                        lora_trainable_mask(init_params)),
    strategy=FedOpt(optax.adam(cfg["server_learning_rate"])),
    datasets=datasets,
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_steps=cfg["local_steps"],
    seed=3,
    exchanger=lora_exchanger(),
)
import os  # noqa: E402

ckpt = os.environ.get("FL4HEALTH_PRETRAINED_CHECKPOINT") or cfg.get(
    "pretrained_checkpoint"
)
if ckpt:
    from fl4health_tpu.preprocessing.checkpoint_io import warm_up_from_file

    warmed = warm_up_from_file(
        jax.device_get(sim.global_params), ckpt,
        torch_linear_convention=str(ckpt).endswith((".pt", ".bin", ".pth")),
    )
    sim.set_global_params(warmed)
lib.run_and_report(sim, cfg)

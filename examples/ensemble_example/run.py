"""Federated ensembles trained simultaneously (reference: examples/ensemble_example).

Run:  python examples/ensemble_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/ensemble_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

from fl4health_tpu.clients.ensemble import EnsembleClientLogic
from fl4health_tpu.models import bases
from fl4health_tpu.models.cnn import Mlp
from fl4health_tpu.server.simulation import FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg

members = (Mlp(features=(32,), n_outputs=10), Mlp(features=(24,), n_outputs=10))
model = bases.EnsembleModel(members=members)
sim = FederatedSimulation(
    logic=EnsembleClientLogic(engine.from_flax(model), engine.masked_cross_entropy,
                              n_members=len(members)),
    tx=optax.sgd(cfg["learning_rate"]),
    strategy=FedAvg(),
    datasets=lib.mnist_client_datasets(cfg),
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_epochs=cfg["local_epochs"],
    seed=42,
)
lib.run_and_report(sim, cfg)

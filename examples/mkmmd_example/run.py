"""MR-MTL with MK-MMD feature alignment (reference: examples/mr_mtl_mkmmd_example family).

Run:  python examples/mkmmd_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/mkmmd_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

from fl4health_tpu.clients.mmd import MrMtlMkMmdClientLogic
from fl4health_tpu.clients.ditto import KeepLocalExchanger
from fl4health_tpu.server.simulation import FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg

sim = FederatedSimulation(
    logic=MrMtlMkMmdClientLogic(
        lib.mnist_model(cfg), engine.masked_cross_entropy,
        lam=cfg["lam"], mkmmd_loss_weight=cfg["mkmmd_weight"],
    ),
    tx=optax.sgd(cfg["learning_rate"]),
    strategy=FedAvg(),
    datasets=lib.mnist_client_datasets(cfg),
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_steps=cfg["local_steps"],
    seed=42,
    exchanger=KeepLocalExchanger(),
)
lib.run_and_report(sim, cfg)

"""Federated LLM-style LoRA fine-tuning with ZeRO-sharded optimizer state (reference: examples/fedllm_example — LoRA + DeepSpeed ZeRO configs).

The reference delegates memory scaling to DeepSpeed ZeRO JSON configs; here
the equivalent is first-class: ``zero_sharded_optimizer`` shards Adam moments
over a ``model`` mesh axis (ZeRO-1, parallel/zero.py), and only LoRA adapter
parameters cross the wire (utils/peft.py).

Run:  python examples/fedllm_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/fedllm_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

import os

if os.environ.get("FL4HEALTH_EXAMPLE_TINY"):
    # smoke-suite budget: shrink the model, keep every code path (LoRA
    # exchange, masked Adam, ZeRO-1 demo)
    cfg.update(d_model=32, n_heads=2, n_layers=1, d_ff=64, vocab_size=64,
               seq_len=16, local_steps=2)

import jax
from fl4health_tpu.datasets.synthetic import synthetic_text_classification
from fl4health_tpu.models.transformer import TransformerClassifier
from fl4health_tpu.parallel.mesh import Mesh, mesh_utils
from fl4health_tpu.parallel.zero import zero_sharded_optimizer
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg
from fl4health_tpu.utils.peft import lora_exchanger, lora_trainable_mask, masked_optimizer

model_module = TransformerClassifier(
    vocab_size=cfg["vocab_size"], n_classes=cfg["n_classes"],
    d_model=cfg["d_model"], n_heads=cfg["n_heads"], n_layers=cfg["n_layers"],
    d_ff=cfg["d_ff"], max_len=cfg["seq_len"], lora_rank=cfg["lora_rank"],
)
model = engine.from_flax(model_module)
datasets = []
for i in range(cfg["n_clients"]):
    x, y = synthetic_text_classification(
        jax.random.PRNGKey(20 + i), 48, cfg["vocab_size"], cfg["seq_len"],
        cfg["n_classes"], class_sep=3.0,
    )
    datasets.append(ClientDataset(x[:32], y[:32], x[32:], y[32:]))
init_params = model.init(jax.random.PRNGKey(0), datasets[0].x_train[:1])[0]

# Base optimizer: Adam over the LoRA-trainable subset only. (Like the
# reference, ZeRO operates WITHIN a client, not across the federation —
# see the within-client demo after the federated rounds below.)
tx = masked_optimizer(optax.adam(cfg["learning_rate"]),
                      lora_trainable_mask(init_params))

sim = FederatedSimulation(
    logic=engine.ClientLogic(model, engine.masked_cross_entropy),
    tx=tx,
    strategy=FedAvg(),
    datasets=datasets,
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_steps=cfg["local_steps"],
    seed=11,
    exchanger=lora_exchanger(),
)
lib.run_and_report(sim, cfg)

# --- Within-client ZeRO-1 demo (the DeepSpeed-zero2/3-JSON role) ----------
# One client's local fine-tuning with Adam moments sharded over a 'model'
# mesh axis: per-device optimizer state drops to 1/n while the update stays
# numerically the plain Adam update.
n_model_shards = int(cfg.get("zero_shards", 1))
if n_model_shards > 1 and len(jax.devices()) >= n_model_shards:
    import jax.numpy as jnp
    from fl4health_tpu.clients.engine import Batch

    zero_mesh = Mesh(
        mesh_utils.create_device_mesh((n_model_shards,),
                                      devices=jax.devices()[:n_model_shards]),
        ("model",),
    )
    zero_tx = zero_sharded_optimizer(
        optax.adam(cfg["learning_rate"]), zero_mesh, init_params,
        axis_name="model",
    )
    logic = engine.ClientLogic(model, engine.masked_cross_entropy)
    x, y = datasets[0].x_train, datasets[0].y_train
    state = engine.create_train_state(logic, zero_tx, jax.random.PRNGKey(0), x[:1])
    step = jax.jit(engine.make_train_step(logic, zero_tx))
    for i in range(2):
        xb, yb = x[i * 8:(i + 1) * 8], y[i * 8:(i + 1) * 8]
        batch = Batch(x=xb, y=yb,
                      example_mask=jnp.ones((len(xb),), jnp.float32),
                      step_mask=jnp.ones((), jnp.float32))
        state, out = step(state, None, batch)
    total = sum(
        v.size * v.dtype.itemsize
        for v in jax.tree_util.tree_leaves(state.opt_state)
        if getattr(v, "ndim", 0) >= 1
    )
    print(f"# zero-1: {n_model_shards}-way sharded Adam, "
          f"{zero_tx.state_bytes_per_device(state.opt_state)}/{total} "
          f"opt-state bytes per device, step loss="
          f"{float(out.losses['backward']):.4f}")

    # --- ZeRO-2: gradient reduction as psum_scatter ----------------------
    # Per-device UNREDUCED grads (here: per-microbatch) reduce directly into
    # 1/n shards — the summed gradient vector never materializes anywhere
    # (the DeepSpeed zero2 config's memory split).
    from fl4health_tpu.parallel.zero import zero2_sharded_optimizer

    z2_tx = zero2_sharded_optimizer(
        optax.adam(cfg["learning_rate"]), zero_mesh, init_params,
        axis_name="model",
    )
    z2_state = z2_tx.init(init_params)

    def micro_grads(p, xb, yb):
        def loss(p_):
            (preds, _), _ = model.apply(p_, {}, xb, train=False)
            return engine.masked_cross_entropy(
                preds["prediction"], yb, jnp.ones((len(xb),), jnp.float32)
            )
        return jax.grad(loss)(p)

    locals_ = [
        micro_grads(init_params, x[i * 4:(i + 1) * 4], y[i * 4:(i + 1) * 4])
        for i in range(n_model_shards)
    ]
    stacked = jax.tree_util.tree_map(lambda *g: jnp.stack(g), *locals_)
    updates, z2_state = z2_tx.update(stacked, z2_state, init_params)
    print(f"# zero-2: grads psum_scattered over {n_model_shards} devices, "
          f"{z2_tx.grad_bytes_per_device()} summed-grad bytes per device, "
          f"update norm="
          f"{float(jnp.linalg.norm(jax.flatten_util.ravel_pytree(updates)[0])):.4f}")

    # --- ZeRO-2 through the federated engine -----------------------------
    # Same FederatedSimulation API as everywhere else: pass the ZeRO-2
    # optimizer as ``tx`` and the engine splits every batch into n_shards
    # microbatches whose unreduced grads reduce via psum_scatter
    # (clients/engine.py _microbatched_value_and_grads; parity with the
    # unsharded round pinned by tests/parallel/test_tp_zero.py::
    # TestZero2EngineIntegration). Full-parameter exchange here: the
    # pytree-masked LoRA optimizer operates on the param TREE while the
    # ZeRO wrapper works on the flat shard vector, so the two don't compose
    # yet — this sim trains the full model.
    if cfg["batch_size"] % n_model_shards == 0:
        z2_sim = FederatedSimulation(
            logic=engine.ClientLogic(model, engine.masked_cross_entropy),
            tx=z2_tx,
            strategy=FedAvg(),
            datasets=datasets,
            batch_size=cfg["batch_size"],
            metrics=lib.accuracy_metrics(),
            local_steps=cfg["local_steps"],
            seed=11,
        )
        z2_hist = z2_sim.fit(2)
        print(f"# zero-2 federated sim: 2 rounds through the engine "
              f"microbatch path, final eval acc="
              f"{float(z2_hist[-1].eval_metrics['accuracy']):.4f}")
    else:
        print(f"# zero-2 federated sim skipped: batch_size "
              f"{cfg['batch_size']} not divisible by {n_model_shards} shards")
else:
    print("# zero-1/2 demo skipped (single device or zero_shards=1)")

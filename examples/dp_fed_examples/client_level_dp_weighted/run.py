"""Weighted client-level DP-FedAvgM with adaptive clipping (reference:
examples/dp_fed_examples/client_level_dp_weighted).

The reference variant trains a logistic-regression breast-cancer classifier
(31 tabular features) across hospitals of very different sizes, so client
updates are weighted by capped sample counts (McMahan et al. 1710.06963)
rather than uniformly averaged, and the clipping bound adapts server-side
(arXiv 1905.03871). This mirrors that: a 31-feature synthetic binary task,
deliberately uneven client shards, ``weighted_aggregation=True`` plus
adaptive clipping on the strategy.

Run:  python examples/dp_fed_examples/client_level_dp_weighted/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/dp_fed_examples/client_level_dp_weighted/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))
import jax  # noqa: E402
import numpy as np  # noqa: E402
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402
from fl4health_tpu.clients.clipping import ClippingClientLogic  # noqa: E402
from fl4health_tpu.datasets.synthetic import synthetic_classification  # noqa: E402
from fl4health_tpu.datasets.vision import split_data_and_targets  # noqa: E402
from fl4health_tpu.models.cnn import LogisticRegression  # noqa: E402
from fl4health_tpu.server.servers import ClientLevelDpFedAvgServer  # noqa: E402
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation  # noqa: E402
from fl4health_tpu.strategies.client_dp_fedavgm import ClientLevelDPFedAvgM  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)
n_clients = int(cfg["n_clients"])

# Uneven "hospitals": a 64..256 linspace profile NORMALIZED to the
# 1024-sample pool, so the capped-count weighting is exercised (equal shards
# would collapse it to the unweighted mean) and every client gets a
# non-empty shard at any FL4HEALTH_EXAMPLE_CLIENTS. (The previous raw
# linspace summed past 1024 at >=7 clients, silently truncating trailing
# clients to empty shards.)
x, y = synthetic_classification(
    jax.random.PRNGKey(0), 1024, (31,), 2, class_sep=1.5
)
x, y = np.asarray(x), np.asarray(y)
profile = np.linspace(64, 256, n_clients)
sizes = np.floor(profile * 1024 / profile.sum()).astype(int)
sizes[: 1024 - sizes.sum()] += 1  # distribute the flooring remainder
assert sizes.sum() == 1024 and (sizes > 0).all()
offsets = np.concatenate([[0], np.cumsum(sizes)])
datasets = []
for i in range(n_clients):
    px, py = x[offsets[i]:offsets[i + 1]], y[offsets[i]:offsets[i + 1]]
    xt, yt, xv, yv = split_data_and_targets(px, py, 0.2, 7 + i)
    datasets.append(ClientDataset(x_train=xt, y_train=yt, x_val=xv, y_val=yv))

sim = FederatedSimulation(
    logic=ClippingClientLogic(
        engine.from_flax(LogisticRegression(n_outputs=2)),
        engine.masked_cross_entropy,
        adaptive_clipping=True,
    ),
    tx=optax.sgd(cfg["learning_rate"]),
    strategy=ClientLevelDPFedAvgM(
        noise_multiplier=cfg["noise_multiplier"],
        initial_clipping_bound=cfg["clipping_bound"],
        adaptive_clipping=True,
        bit_noise_multiplier=cfg["bit_noise_multiplier"],
        clipping_quantile=cfg["clipping_quantile"],
        weighted_aggregation=True,
    ),
    datasets=datasets,
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_epochs=cfg["local_epochs"],
    seed=42,
)
server = ClientLevelDpFedAvgServer(sim, noise_multiplier=cfg["noise_multiplier"])
lib.run_and_report(server, cfg)

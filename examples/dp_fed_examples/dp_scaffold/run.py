"""DP-SCAFFOLD: control variates + instance-level DP-SGD with accounting (reference: examples/dp_scaffold_example).

Run:  python examples/dp_fed_examples/dp_scaffold/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/dp_fed_examples/dp_scaffold/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

from fl4health_tpu.clients.instance_level_dp import DpScaffoldClientLogic
from fl4health_tpu.server.servers import DpScaffoldServer
from fl4health_tpu.server.simulation import FederatedSimulation
from fl4health_tpu.strategies.scaffold import Scaffold

sim = FederatedSimulation(
    logic=DpScaffoldClientLogic(
        lib.mlp_model(cfg), engine.masked_cross_entropy,
        learning_rate=cfg["learning_rate"],
        clipping_bound=cfg["clipping_bound"],
        noise_multiplier=cfg["noise_multiplier"],
    ),
    tx=optax.sgd(cfg["learning_rate"]),
    strategy=Scaffold(learning_rate=1.0),
    datasets=lib.mnist_client_datasets(cfg),
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_epochs=cfg["local_epochs"],
    seed=42,
)
server = DpScaffoldServer(
    sim, noise_multiplier=cfg["noise_multiplier"], batch_size=cfg["batch_size"],
    warm_start=cfg.get("warm_start", False),
)
lib.run_and_report(server, cfg)

"""Client-level DP-FedAvgM with clipped updates + noisy aggregation (reference: examples/dp_fed_examples/client_level_dp).

Run:  python examples/dp_fed_examples/client_level_dp/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/dp_fed_examples/client_level_dp/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

from fl4health_tpu.clients.clipping import ClippingClientLogic
from fl4health_tpu.server.servers import ClientLevelDpFedAvgServer
from fl4health_tpu.server.simulation import FederatedSimulation
from fl4health_tpu.strategies.client_dp_fedavgm import ClientLevelDPFedAvgM

sim = FederatedSimulation(
    logic=ClippingClientLogic(lib.mlp_model(cfg), engine.masked_cross_entropy),
    tx=optax.sgd(cfg["learning_rate"]),
    strategy=ClientLevelDPFedAvgM(
        noise_multiplier=cfg["noise_multiplier"],
        initial_clipping_bound=cfg["clipping_bound"],
    ),
    datasets=lib.mnist_client_datasets(cfg),
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_epochs=cfg["local_epochs"],
    seed=42,
)
server = ClientLevelDpFedAvgServer(sim, noise_multiplier=cfg["noise_multiplier"])
lib.run_and_report(server, cfg)

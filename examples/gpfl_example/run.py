"""GPFL class-embedding personalization (reference: examples/gpfl_example).

Run:  python examples/gpfl_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/gpfl_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

from fl4health_tpu.clients.gpfl import GpflClientLogic, gpfl_model_def
from fl4health_tpu.exchange.exchanger import FixedLayerExchanger
from fl4health_tpu.models import bases
from fl4health_tpu.server.simulation import FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg

module = bases.GpflModel(
    base_module=bases.DenseFeatures((32,)), n_classes=10, feature_dim=16,
)
sim = FederatedSimulation(
    logic=GpflClientLogic(gpfl_model_def(module), engine.masked_cross_entropy,
                          n_classes=10, lam=cfg["lam"], mu=cfg["mu"]),
    tx=optax.sgd(cfg["learning_rate"]),
    strategy=FedAvg(),
    datasets=lib.mnist_client_datasets(cfg),
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_epochs=cfg["local_epochs"],
    seed=42,
    exchanger=FixedLayerExchanger(bases.GpflModel.exchange_shared),
)
lib.run_and_report(sim, cfg)

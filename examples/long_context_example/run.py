"""Long-context federated fine-tuning through the Pallas flash-attention
kernel (beyond-reference: the reference has NO long-context machinery —
SURVEY §5 — and delegates scale to DeepSpeed configs; here long context is
first-class: kernels/flash_attention.py carries the T^2 score memory in
VMEM, and on a multi-device seq mesh parallel/ring_attention.py's
ring_flash_attention extends the same kernel across chips).

This example trains a document-classifier cohort at seq_len 256 (tiny mode
shrinks it) with attention_fn=flash_attention inside the compiled
federated round — remat on, bf16-ready. On CPU the kernel runs in Pallas
interpret mode (slow but exact); on TPU it compiles via Mosaic.

Run:  python examples/long_context_example/run.py
Tiny: FL4HEALTH_EXAMPLE_TINY=1 FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/long_context_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

import functools
import os

if os.environ.get("FL4HEALTH_EXAMPLE_TINY"):
    # smoke-suite budget: interpret-mode flash at seq 256 is too slow on
    # one CPU core; keep the code path, shrink the shapes
    cfg.update(seq_len=32, vocab_size=64, d_model=16, n_heads=2, n_layers=1,
               d_ff=32, block=16, local_steps=2)

import jax
from fl4health_tpu.datasets.synthetic import synthetic_text_classification
from fl4health_tpu.kernels.flash_attention import flash_attention
from fl4health_tpu.models.transformer import TransformerClassifier
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg

module = TransformerClassifier(
    vocab_size=cfg["vocab_size"], n_classes=cfg["n_classes"],
    d_model=cfg["d_model"], n_heads=cfg["n_heads"], n_layers=cfg["n_layers"],
    d_ff=cfg["d_ff"], max_len=cfg["seq_len"], remat=True,
    attention_fn=functools.partial(
        flash_attention, block_q=cfg["block"], block_k=cfg["block"]
    ),
)
datasets = []
for i in range(cfg["n_clients"]):
    x, y = synthetic_text_classification(
        jax.random.PRNGKey(30 + i), 24, cfg["vocab_size"], cfg["seq_len"],
        cfg["n_classes"], class_sep=3.0,
    )
    datasets.append(ClientDataset(x[:16], y[:16], x[16:], y[16:]))

sim = FederatedSimulation(
    logic=engine.ClientLogic(engine.from_flax(module),
                             engine.masked_cross_entropy),
    tx=optax.adam(cfg["learning_rate"]),
    strategy=FedAvg(),
    datasets=datasets,
    batch_size=cfg["batch_size"],
    metrics=lib.accuracy_metrics(),
    local_steps=cfg["local_steps"],
    seed=23,
)
lib.run_and_report(sim, cfg)

"""Federated PCA: local SVD subspaces merged by stacked-SVD (reference: examples/fedpca_examples).

Run:  python examples/fedpca_example/run.py
Tiny: FL4HEALTH_EXAMPLE_ROUNDS=1 FL4HEALTH_EXAMPLE_CLIENTS=2 python examples/fedpca_example/run.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))
import optax  # noqa: E402

import _lib as lib  # noqa: E402
from fl4health_tpu.clients import engine  # noqa: E402

cfg = lib.example_config(Path(__file__).parent)

import jax
import jax.numpy as jnp
import json
import numpy as np
from fl4health_tpu.models.autoencoders import PcaModule
from fl4health_tpu.strategies.base import FitResults
from fl4health_tpu.strategies.fedpca import FedPCA, PcaPacket

datasets = lib.mnist_client_datasets(cfg)
k = cfg["n_components"]
pca = PcaModule(low_rank=True, rank_estimation=k)
components, svs, counts = [], [], []
for d in datasets:
    state = pca.fit(jnp.asarray(np.asarray(d.x_train).reshape(len(d.x_train), -1)))
    components.append(state.components[:, :k])
    svs.append(state.singular_values[:k])
    counts.append(d.n_train)

strategy = FedPCA(n_components=k)
server_state = strategy.init(
    {"components": components[0], "singular_values": svs[0]}
)
results = FitResults(
    packets=PcaPacket(components=jnp.stack(components),
                      singular_values=jnp.stack(svs)),
    sample_counts=jnp.asarray(counts, jnp.float32),
    train_losses={}, train_metrics={},
    mask=jnp.ones((len(datasets),)),
)
merged = strategy.aggregate(server_state, results, 1)
# merged principal subspace explains the pooled data
pooled = np.concatenate([np.asarray(d.x_val).reshape(len(d.x_val), -1) for d in datasets])
pooled = pooled - pooled.mean(axis=0)
proj = pooled @ np.asarray(merged.components)
ratio = float((proj ** 2).sum() / (pooled ** 2).sum())
print(json.dumps({"merged_components": list(np.asarray(merged.components).shape),
                  "explained_variance_ratio": round(ratio, 4)}))
assert ratio > 0.1

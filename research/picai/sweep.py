"""Federated nnU-Net prostate-segmentation harness (reference:
research/picai/ — nnU-Net under FedAvg on the PI-CAI bpMRI volumes, plus a
central/single-node baseline; monai/nnunet_scripts drive the real data).

The real PI-CAI corpus cannot exist on this box (zero egress); the harness
keeps the experiment SHAPE — plans negotiation from client fingerprints,
deep-supervised U-Net from the plans, on-device augmentation, polyLR SGD,
dice selection over an lr sweep, and a "central" (single-client) baseline
arm mirroring research/picai/central. Drop real volumes in via
FL4HEALTH_PICAI_DIR (per-client .npz files with `volume` [D,H,W,C] and
`segmentation` [D,H,W] arrays) and the same sweep runs on them.

Run:  python research/picai/sweep.py
Tiny: FL4HEALTH_SWEEP_TINY=1 python research/picai/sweep.py
"""

import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent
sys.path.insert(0, str(REPO))

import jax

from fl4health_tpu.utils.bootstrap import honor_cpu_platform_request

honor_cpu_platform_request()
import numpy as np

from fl4health_tpu.clients import engine
from fl4health_tpu.clients.nnunet import (
    NnunetClientLogic,
    make_nnunet_properties_provider,
)
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.metrics.efficient import segmentation_dice
from fl4health_tpu.models.unet import deep_supervision_strides, unet_from_plans
from fl4health_tpu.nnunet import extract_patch_dataset, nnunet_optimizer
from fl4health_tpu.server.nnunet import NnunetServer
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg
from fl4health_tpu.utils.hp_search import hp_grid, sweep

TINY = bool(os.environ.get("FL4HEALTH_SWEEP_TINY"))
N_CLIENTS = 2 if TINY else 3
ROUNDS = 2 if TINY else 8
SIZE = 10 if TINY else 24
N_VOLUMES = 2 if TINY else 6
N_PATCHES = 8 if TINY else 40
LOCAL_STEPS = 2 if TINY else 4


def _synth_prostate(seed: int, n: int, size: int):
    """Ellipsoid-lesion phantoms: background noise + a bright lesion —
    enough anisotropy/label sparsity to exercise the nnU-Net paths."""
    rng = np.random.default_rng(seed)
    vols, segs = [], []
    for _ in range(n):
        coords = np.stack(
            np.meshgrid(*[np.arange(size)] * 3, indexing="ij"), -1
        ).astype(float)
        c = np.asarray([rng.uniform(size * 0.3, size * 0.7) for _ in range(3)])
        radii = np.asarray([size * rng.uniform(0.12, 0.3) for _ in range(3)])
        seg = (np.sum(((coords - c) / radii) ** 2, -1) < 1.0).astype(np.int32)
        vols.append(
            (rng.normal(0, 0.35, (size,) * 3)[..., None]
             + 1.2 * seg[..., None]).astype(np.float32)
        )
        segs.append(seg)
    return vols, segs


def _load_clients():
    data_dir = os.environ.get("FL4HEALTH_PICAI_DIR")
    if data_dir and Path(data_dir).exists():
        clients = []
        for cdir in sorted(Path(data_dir).iterdir()):
            if not cdir.is_dir():
                continue
            vols, segs = [], []
            for f in sorted(cdir.glob("*.npz")):
                with np.load(f) as z:
                    vols.append(np.asarray(z["volume"], np.float32))
                    segs.append(np.asarray(z["segmentation"], np.int32))
            if vols:
                clients.append((vols, segs))
        if clients:
            print(f"# data: real volumes from {data_dir} "
                  f"({len(clients)} clients)")
            return clients
    print("# data: synthetic prostate phantoms")
    return [_synth_prostate(7 * (i + 1), N_VOLUMES, SIZE)
            for i in range(N_CLIENTS)]


CLIENT_DATA = _load_clients()


def build(seed: int, lr: float, central: bool) -> "NnunetServer":
    data = ([(sum((v for v, _ in CLIENT_DATA), []),
              sum((s for _, s in CLIENT_DATA), []))]
            if central else CLIENT_DATA)
    providers = [
        make_nnunet_properties_provider(
            v, [(1.0, 1.0, 1.0)] * len(v), s, max_patch_voxels=SIZE ** 3
        )
        for v, s in data
    ]

    def sim_builder(plans, n_in, n_heads):
        cfg_ = plans["configurations"]["3d_fullres"]
        cfg_["features_per_stage"] = [
            max(f // 4, 8) for f in cfg_["features_per_stage"]
        ]
        net = unet_from_plans(plans, n_in, n_heads)
        logic = NnunetClientLogic(
            engine.from_flax(net), ds_strides=deep_supervision_strides(plans)
        )
        datasets = []
        for i, (v, s) in enumerate(data):
            x, y = extract_patch_dataset(v, s, plans, n_patches=N_PATCHES,
                                         seed=seed * 101 + i)
            cut = int(N_PATCHES * 0.75)
            datasets.append(
                ClientDataset(x[:cut], y[:cut], x[cut:], y[cut:])
            )
        return FederatedSimulation(
            logic=logic,
            tx=nnunet_optimizer(lr, ROUNDS * LOCAL_STEPS),
            strategy=FedAvg(),
            datasets=datasets,
            batch_size=2,
            metrics=MetricManager((segmentation_dice(n_heads),)),
            local_steps=LOCAL_STEPS,
            seed=seed,
            extra_loss_keys=("dice", "ce"),
        )

    return NnunetServer(
        config={"n_server_rounds": ROUNDS},
        property_providers=providers,
        sim_builder=sim_builder,
    )


grid = hp_grid(
    lr=[5e-3] if TINY else [1e-3, 5e-3, 1e-2],
    central=[False] if TINY else [False, True],
)

results = sweep(
    build, grid, n_rounds=ROUNDS, n_seeds=1,
    score=lambda history: float(history[-1].eval_metrics["seg_dice"]),
    minimize=False,
)
for r in results:
    print(json.dumps({"params": r.params,
                      "mean_dice": round(r.mean_score, 4)}))
best = results[0]
print(json.dumps({"best": best.params, "dice": round(best.mean_score, 4)}))

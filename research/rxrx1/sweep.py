"""rxrx1 personalization experiments (reference: research/rxrx1/ — fedavg /
ditto / mr_mtl (+ MMD-regularized variants) on the RxRx1 fluorescence
microscopy corpus partitioned by experiment site, selected by
find_best_hp).

Real data rides `datasets.medical.load_rxrx1_data` when
FL4HEALTH_RXRX1_DIR points at the reference's on-disk layout
(metadata.csv + images/*.npy); without it (zero-egress box) the corpus is
synthetic microscopy-shaped images with per-site covariate shift — the same
experiment shape at toy scale. The MMD arm exercises DittoMkMmdClientLogic,
the reference's ditto_mkmmd variant.

Run:  python research/rxrx1/sweep.py
Tiny: FL4HEALTH_SWEEP_TINY=1 python research/rxrx1/sweep.py
"""

import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent
sys.path.insert(0, str(REPO))

import jax

from fl4health_tpu.utils.bootstrap import honor_cpu_platform_request

honor_cpu_platform_request()
import numpy as np
import optax

from fl4health_tpu.clients import engine
from fl4health_tpu.clients.personalized import (
    KeepLocalExchanger,
    PersonalizedMode,
    exchange_global_subtree,
    make_it_personal,
)
from fl4health_tpu.exchange.exchanger import FixedLayerExchanger
from fl4health_tpu.metrics import efficient
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models.cnn import MnistNet
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg
from fl4health_tpu.utils.hp_search import hp_grid, sweep

TINY = bool(os.environ.get("FL4HEALTH_SWEEP_TINY"))
N_SITES = 2 if TINY else 4
ROUNDS = 2 if TINY else 8
PER_SITE = 24 if TINY else 160
HW = 12 if TINY else 28
CLASSES = 4 if TINY else 10


def _synth_site(site: int):
    """Microscopy-shaped synthetic: class = blob count pattern, site =
    global intensity/illumination shift (the covariate shift rxrx1's
    site partition exists to study)."""
    rng = np.random.default_rng(31 + site)
    x = np.zeros((PER_SITE, HW, HW, 1), np.float32)
    y = rng.integers(0, CLASSES, PER_SITE).astype(np.int32)
    coords = np.stack(np.meshgrid(np.arange(HW), np.arange(HW),
                                  indexing="ij"), -1)
    for i in range(PER_SITE):
        img = rng.normal(0.1 * site, 0.15, (HW, HW))
        for _ in range(int(y[i]) + 1):
            c = rng.uniform(2, HW - 2, 2)
            r = rng.uniform(1.0, 2.0)
            img += np.exp(-np.sum((coords - c) ** 2, -1) / (2 * r * r))
        x[i, ..., 0] = img * (1.0 + 0.2 * site)
    return x, y


def _load_sites():
    """-> (sites, n_classes). The label space comes from the DATA: real
    rxrx1 has ~1108 siRNA classes (load_rxrx1_data's info), and the model
    head must be sized from it, not from the synthetic default."""
    data_dir = os.environ.get("FL4HEALTH_RXRX1_DIR")
    if data_dir and Path(data_dir).exists():
        from fl4health_tpu.datasets.medical import load_rxrx1_data

        sites, n_classes = [], None
        for s in range(1, N_SITES + 1):
            try:
                x, y, info = load_rxrx1_data(data_dir, client_site=s,
                                             train=True)
                sites.append((x, y))
                n_classes = int(info["n_classes"])
            except FileNotFoundError:
                break
        if sites:
            print(f"# data: real rxrx1 from {data_dir} ({len(sites)} sites, "
                  f"{n_classes} classes)")
            return sites, n_classes
    print("# data: synthetic microscopy-shaped corpus with site shift")
    return [_synth_site(s) for s in range(N_SITES)], CLASSES


def client_datasets() -> tuple[list[ClientDataset], int]:
    sites, n_classes = _load_sites()
    out = []
    for x, y in sites:
        cut = int(len(x) * 0.75)
        out.append(ClientDataset(x[:cut], y[:cut], x[cut:], y[cut:]))
    return out, n_classes


DATASETS, N_CLASSES_DATA = client_datasets()


def build(seed: int, algo: str, lr: float, lam: float) -> FederatedSimulation:
    model = engine.from_flax(MnistNet(n_classes=N_CLASSES_DATA, hidden=32))
    extra_keys = ()
    if algo == "ditto_mkmmd":
        from fl4health_tpu.clients.mmd import DittoMkMmdClientLogic
        from fl4health_tpu.models import bases

        def _net():
            return MnistNet(n_classes=N_CLASSES_DATA, hidden=32)

        twin = bases.TwinModel(global_model=_net(), personal_model=_net())
        logic = DittoMkMmdClientLogic(
            engine.from_flax(twin), engine.masked_cross_entropy,
            feature_model=engine.from_flax(_net()),
            lam=lam, mkmmd_loss_weight=0.1,
            beta_global_update_interval=2 if TINY else 20,
        )
        exchanger = FixedLayerExchanger(bases.TwinModel.exchange_global_model)
        extra_keys = tuple(logic.extra_loss_keys)
    elif algo == "ditto":
        base = engine.ClientLogic(model, engine.masked_cross_entropy)
        logic = make_it_personal(base, PersonalizedMode.DITTO, lam=lam)
        exchanger = FixedLayerExchanger(exchange_global_subtree)
        extra_keys = tuple(logic.extra_loss_keys)
    elif algo == "mr_mtl":
        base = engine.ClientLogic(model, engine.masked_cross_entropy)
        logic = make_it_personal(base, PersonalizedMode.MR_MTL, lam=lam)
        exchanger = KeepLocalExchanger()
        extra_keys = tuple(logic.extra_loss_keys)
    else:
        logic, exchanger = engine.ClientLogic(
            model, engine.masked_cross_entropy
        ), None
    return FederatedSimulation(
        logic=logic,
        tx=optax.sgd(lr),
        strategy=FedAvg(),
        datasets=DATASETS,
        batch_size=8,
        metrics=MetricManager((efficient.accuracy(),)),
        local_steps=2 if TINY else 4,
        seed=seed,
        exchanger=exchanger,
        extra_loss_keys=extra_keys,
    )


grid = hp_grid(
    algo=["fedavg", "ditto", "mr_mtl"] if TINY
    else ["fedavg", "ditto", "mr_mtl", "ditto_mkmmd"],
    lr=[0.05] if TINY else [0.01, 0.05],
    lam=[0.1] if TINY else [0.01, 0.1, 1.0],
)
grid = [hp for hp in grid if hp["algo"] != "fedavg" or hp["lam"] == grid[0]["lam"]]

results = sweep(
    build, grid, n_rounds=ROUNDS, n_seeds=1 if TINY else 3,
    score=lambda history: float(history[-1].eval_metrics["accuracy"]),
    minimize=False,
)
for r in results:
    print(json.dumps({"params": r.params,
                      "mean_accuracy": round(r.mean_score, 4)}))
best = results[0]
print(json.dumps({"best": best.params, "accuracy": round(best.mean_score, 4)}))

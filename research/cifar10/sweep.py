"""HP sweep: FedAvg vs FedProx on CIFAR-shaped non-IID clients (reference:
research/cifar10/ + research/*/find_best_hp.py selection semantics).

Run:  python research/cifar10/sweep.py
Tiny: FL4HEALTH_SWEEP_TINY=1 python research/cifar10/sweep.py
"""

import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent
sys.path.insert(0, str(REPO))

import jax

from fl4health_tpu.utils.bootstrap import honor_cpu_platform_request

honor_cpu_platform_request()
import numpy as np
import optax

from fl4health_tpu.clients import engine
from fl4health_tpu.clients.fedprox import FedProxClientLogic
from fl4health_tpu.datasets.partitioners import DirichletLabelBasedAllocation
from fl4health_tpu.datasets.synthetic import synthetic_classification
from fl4health_tpu.datasets.vision import federated_client_datasets
from fl4health_tpu.metrics import efficient
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models.cnn import CifarNet
from fl4health_tpu.server.simulation import FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg
from fl4health_tpu.strategies.fedprox import FedAvgWithAdaptiveConstraint
from fl4health_tpu.utils.hp_search import hp_grid, sweep

TINY = bool(os.environ.get("FL4HEALTH_SWEEP_TINY"))
N_CLIENTS = 2 if TINY else 8
ROUNDS = 2 if TINY else 10
HW = 8 if TINY else 32


def client_datasets():
    try:
        from fl4health_tpu.datasets.vision import load_cifar10_arrays

        x, y = load_cifar10_arrays(
            Path(os.environ.get("FL4HEALTH_CIFAR_DIR", "/root/data/cifar10")),
            train=True,
        )
        idx = np.random.default_rng(0).permutation(len(x))[: 4096 if not TINY else 256]
        x, y = np.asarray(x, np.float32)[idx], np.asarray(y, np.int64)[idx]
        print("# data: real CIFAR-10")
    except (FileNotFoundError, OSError):
        x, y = synthetic_classification(
            jax.random.PRNGKey(0), 256 if TINY else 2048, (HW, HW, 3), 10,
            class_sep=1.5,
        )
        x, y = np.asarray(x), np.asarray(y)
        print("# data: synthetic CIFAR-shaped corpus")
    part = DirichletLabelBasedAllocation(
        number_of_partitions=N_CLIENTS, unique_labels=list(range(10)),
        beta=0.5, min_label_examples=1, hash_key=13,
    )
    return federated_client_datasets(x, y, n_clients=N_CLIENTS,
                                     partitioner=part, hash_key=5)


DATASETS = client_datasets()


def build(seed: int, algo: str, lr: float, mu: float) -> FederatedSimulation:
    model = engine.from_flax(CifarNet())
    if algo == "fedavg":
        logic = engine.ClientLogic(model, engine.masked_cross_entropy)
        strategy = FedAvg()
    else:
        logic = FedProxClientLogic(model, engine.masked_cross_entropy)
        strategy = FedAvgWithAdaptiveConstraint(
            initial_drift_penalty_weight=mu, adapt_loss_weight=False
        )
    return FederatedSimulation(
        logic=logic,
        tx=optax.sgd(lr),
        strategy=strategy,
        datasets=DATASETS,
        batch_size=16,
        metrics=MetricManager((efficient.accuracy(),)),
        local_epochs=1,
        seed=seed,
    )


grid = hp_grid(
    algo=["fedavg", "fedprox"],
    lr=[0.05] if TINY else [0.01, 0.05, 0.1],
    mu=[0.1] if TINY else [0.01, 0.1, 1.0],
)
# mu is inert for fedavg — drop duplicate configs
grid = [hp for hp in grid if hp["algo"] != "fedavg" or hp["mu"] == grid[0]["mu"]]

results = sweep(
    build, grid, n_rounds=ROUNDS, n_seeds=1 if TINY else 3,
    score=lambda history: float(history[-1].eval_metrics["accuracy"]),
    minimize=False,
)
for r in results:
    print(json.dumps({"params": r.params,
                      "mean_accuracy": round(r.mean_score, 4)}))
best = results[0]
print(json.dumps({"best": best.params, "accuracy": round(best.mean_score, 4)}))

"""FedProx cluster experiment, local-silo edition (reference:
research/fedprox_cluster/run_fl_cluster.sh — one slurm job per (mu, run):
a gRPC server + N client processes per job, logs scraped by
find_best_hp.py).

The TPU-native equivalent keeps the deployment shape: for every mu in the
grid, N LoopbackServer silos (one process-isolated handler each, talking
the transport codec's wire frames over TCP — the C++ framing when built)
run FedProx rounds against a coordinator, and every run drops a
JsonReporter-style dump under ``<sweep_dir>/mu_<mu>/Run<k>/``. Selection is
``find_best_hp_dir`` over the dump tree — the reference's file-based
find_best_hp flow, byte-for-byte in spirit.

Run:  python research/fedprox_cluster/run_local_cluster.py
Tiny: FL4HEALTH_SWEEP_TINY=1 python research/fedprox_cluster/run_local_cluster.py
Output tree: FL4HEALTH_CLUSTER_DIR (default: ./cluster_runs under this dir).
"""

import json
import os
import sys
import types
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent
sys.path.insert(0, str(REPO))

from fl4health_tpu.utils.bootstrap import honor_cpu_platform_request

honor_cpu_platform_request()
import jax
import jax.numpy as jnp
import numpy as np
import optax

from fl4health_tpu.clients import engine
from fl4health_tpu.clients.fedprox import FedProxClientLogic
from fl4health_tpu.datasets.synthetic import fedprox_synthetic
from fl4health_tpu.metrics import efficient
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models.cnn import Mlp
from fl4health_tpu.transport import (
    LoopbackServer,
    broadcast_round,
    decode,
    encode,
    weighted_merge,
)
from fl4health_tpu.utils.hp_search import find_best_hp_dir

TINY = bool(os.environ.get("FL4HEALTH_SWEEP_TINY"))
N_SILOS = 2 if TINY else 5
ROUNDS = 2 if TINY else 8
RUNS = 1 if TINY else 3
PER_SILO = 24 if TINY else 120
DIM, CLASSES = (8, 3) if TINY else (30, 6)
MUS = [0.1] if TINY else [0.01, 0.1, 1.0]
LOCAL_STEPS = 2 if TINY else 4


def make_silo(seed: int, mu: float, shard):
    """One 'hospital' process boundary: private shard + FedProx local
    training behind a TCP handler speaking wire frames."""
    x, y = np.asarray(shard[0]), np.asarray(shard[1])
    logic = FedProxClientLogic(
        engine.from_flax(Mlp(features=(16,), n_outputs=CLASSES)),
        engine.masked_cross_entropy,
    )
    tx = optax.sgd(0.05)
    state = engine.create_train_state(
        logic, tx, jax.random.PRNGKey(seed), jnp.asarray(x[:1])
    )
    train = jax.jit(
        engine.make_local_train(
            logic, tx, MetricManager((efficient.accuracy(),)),
            loss_keys=("backward", *logic.extra_loss_keys),
        )
    )

    def handler(frame: bytes) -> bytes:
        nonlocal state
        global_params = decode(frame, like=state.params)
        state = state.replace(params=global_params)
        # mu rides the payload in the reference protocol (the server packs
        # it); this cluster job pins it per-silo from the hp grid.
        ctx = logic.init_round_context(
            state, types.SimpleNamespace(
                drift_penalty_weight=jnp.asarray(mu, jnp.float32)
            )
        )
        batches = engine.epoch_batches(
            state.rng, jnp.asarray(x), jnp.asarray(y), 8,
            n_steps=LOCAL_STEPS,
        )
        new_state, losses, metrics, _ = train(state, ctx, batches)
        state = new_state
        return encode({
            "params": state.params,
            "n": jnp.asarray(float(len(x))),
            "loss": losses["backward"],
            "accuracy": metrics["accuracy"],
        })

    return LoopbackServer(handler), state.params


def run_job(mu: float, run_idx: int, out_dir: Path) -> None:
    """One (mu, run) cluster job: silos up, FedProx rounds over the wire,
    JsonReporter-style dump down."""
    shards = fedprox_synthetic(
        jax.random.PRNGKey(run_idx), N_SILOS, PER_SILO,
        alpha=0.5, beta=0.5, dim=DIM, n_classes=CLASSES,
    )
    silos = [make_silo(100 * run_idx + i, mu, s)
             for i, s in enumerate(shards)]
    init_params = silos[0][1]
    template = {"params": init_params, "n": jnp.zeros(()),
                "loss": jnp.zeros(()), "accuracy": jnp.zeros(())}
    global_params = init_params
    dump: dict = {"host_type": "server", "mu": mu, "rounds": {}}
    try:
        for rnd in range(1, ROUNDS + 1):
            replies = broadcast_round(
                [(srv.host, srv.port) for srv, _ in silos],
                global_params, template,
            )
            global_params, _ = weighted_merge(replies)
            dump["rounds"][str(rnd)] = {
                "fit_loss": float(np.mean([float(r["loss"]) for r in replies])),
                "accuracy": float(np.mean([float(r["accuracy"]) for r in replies])),
            }
    finally:
        for srv, _ in silos:
            srv.close()
    run_dir = out_dir / f"mu_{mu}" / f"Run{run_idx + 1}"
    run_dir.mkdir(parents=True, exist_ok=True)
    (run_dir / "server_metrics.json").write_text(json.dumps(dump, indent=2))


def main() -> None:
    root = Path(os.environ.get(
        "FL4HEALTH_CLUSTER_DIR", Path(__file__).parent / "cluster_runs"
    ))
    # Each invocation gets a fresh sweep subtree: find_best_hp_dir scans
    # every hp folder under the dir it's given, so stale mu_* trees from a
    # previous (possibly differently-configured) invocation must not enter
    # this run's selection.
    import tempfile

    root.mkdir(parents=True, exist_ok=True)
    out_dir = Path(tempfile.mkdtemp(prefix="sweep_", dir=root))
    print(json.dumps({"sweep_dir": str(out_dir)}))
    for mu in MUS:
        for run_idx in range(RUNS):
            run_job(mu, run_idx, out_dir)
            print(json.dumps({"job": f"mu_{mu}", "run": run_idx + 1,
                              "status": "done"}))
    # find_best_hp_dir resolves the dotted metric inside the LAST round's
    # record of each dump — the reference's log-scrape selection.
    best_dir, best_score = find_best_hp_dir(
        out_dir, metric="accuracy", minimize=False,
    )
    print(json.dumps({
        "best": best_dir.name if best_dir else None,
        "mean_final_accuracy":
            round(best_score, 4) if best_score is not None else None,
    }))


if __name__ == "__main__":
    main()

"""Personalization on the FedProx synthetic design (reference:
research/synthetic_data/ — fedavg vs ditto vs mr_mtl on the alpha/beta
heterogeneous synthetic corpus from the FedProx paper, hp-swept with
find_best_hp selection).

The reference preprocesses the corpus to disk (preprocess.py) and runs each
algorithm as its own slurm job; here the generator is
``datasets.synthetic.fedprox_synthetic`` (same W_k/v_k construction) and the
three algorithms share one sweep. alpha/beta control client heterogeneity —
the experiment's point is that personalized methods win as alpha/beta grow.

Run:  python research/synthetic_data/sweep.py
Tiny: FL4HEALTH_SWEEP_TINY=1 python research/synthetic_data/sweep.py
Knobs: FL4HEALTH_SYNTH_ALPHA / FL4HEALTH_SYNTH_BETA (default 0.5/0.5).
"""

import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent
sys.path.insert(0, str(REPO))

import jax

from fl4health_tpu.utils.bootstrap import honor_cpu_platform_request

honor_cpu_platform_request()
import numpy as np
import optax

from fl4health_tpu.clients import engine
from fl4health_tpu.clients.personalized import (
    KeepLocalExchanger,
    PersonalizedMode,
    exchange_global_subtree,
    make_it_personal,
)
from fl4health_tpu.datasets.synthetic import fedprox_synthetic
from fl4health_tpu.exchange.exchanger import FixedLayerExchanger
from fl4health_tpu.metrics import efficient
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models.cnn import Mlp
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg
from fl4health_tpu.utils.hp_search import hp_grid, sweep

TINY = bool(os.environ.get("FL4HEALTH_SWEEP_TINY"))
N_CLIENTS = 2 if TINY else 8
ROUNDS = 2 if TINY else 10
PER_CLIENT = 24 if TINY else 200
DIM, CLASSES = (12, 4) if TINY else (60, 10)
ALPHA = float(os.environ.get("FL4HEALTH_SYNTH_ALPHA", 0.5))
BETA = float(os.environ.get("FL4HEALTH_SYNTH_BETA", 0.5))


def client_datasets() -> list[ClientDataset]:
    shards = fedprox_synthetic(
        jax.random.PRNGKey(0), N_CLIENTS, PER_CLIENT,
        alpha=ALPHA, beta=BETA, dim=DIM, n_classes=CLASSES,
    )
    out = []
    for x, y in shards:
        x, y = np.asarray(x), np.asarray(y)
        cut = int(len(x) * 0.75)
        out.append(ClientDataset(x[:cut], y[:cut], x[cut:], y[cut:]))
    return out


DATASETS = client_datasets()


def build(seed: int, algo: str, lr: float, lam: float) -> FederatedSimulation:
    base = engine.ClientLogic(
        engine.from_flax(Mlp(features=(32,), n_outputs=CLASSES)),
        engine.masked_cross_entropy,
    )
    if algo == "ditto":
        logic = make_it_personal(base, PersonalizedMode.DITTO, lam=lam)
        exchanger = FixedLayerExchanger(exchange_global_subtree)
    elif algo == "mr_mtl":
        logic = make_it_personal(base, PersonalizedMode.MR_MTL, lam=lam)
        exchanger = KeepLocalExchanger()
    else:
        logic, exchanger = base, None
    return FederatedSimulation(
        logic=logic,
        tx=optax.sgd(lr),
        strategy=FedAvg(),
        datasets=DATASETS,
        batch_size=8,
        metrics=MetricManager((efficient.accuracy(),)),
        local_steps=2 if TINY else 5,
        seed=seed,
        exchanger=exchanger,
    )


grid = hp_grid(
    algo=["fedavg", "ditto", "mr_mtl"],
    lr=[0.05] if TINY else [0.01, 0.05],
    lam=[0.1] if TINY else [0.01, 0.1, 1.0],
)
# lam is inert for fedavg — drop duplicate configs
grid = [hp for hp in grid if hp["algo"] != "fedavg" or hp["lam"] == grid[0]["lam"]]

results = sweep(
    build, grid, n_rounds=ROUNDS, n_seeds=1 if TINY else 3,
    score=lambda history: float(history[-1].eval_metrics["accuracy"]),
    minimize=False,
)
print(json.dumps({"alpha": ALPHA, "beta": BETA}))
for r in results:
    print(json.dumps({"params": r.params,
                      "mean_accuracy": round(r.mean_score, 4)}))
best = results[0]
print(json.dumps({"best": best.params, "accuracy": round(best.mean_score, 4)}))

"""AG-News-class experiments: partial weight exchange on a BERT-shaped
transformer (reference: research/ag_news/dynamic_layer_exchange/ +
research/ag_news/sparse_tensor_exchange/ — BERT fine-tuning under
DynamicLayerExchanger / sparse top-score exchange, hp-swept over exchange
budgets; selection semantics from research/*/find_best_hp.py).

The reference runs these on real AG-News through HF BERT; this harness runs
the same experiment shape — drift-ranked dynamic layer exchange vs sparse
COO exchange vs full exchange, swept over exchange budgets — on the
TPU-native transformer. Real AG-News token ids can be dropped in via
FL4HEALTH_AGNEWS_NPZ (x: [N, T] int32 ids, y: [N] labels); without it the
corpus is synthetic (zero-egress box).

Run:  python research/ag_news/sweep.py
Tiny: FL4HEALTH_SWEEP_TINY=1 python research/ag_news/sweep.py
"""

import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent
sys.path.insert(0, str(REPO))

import jax

from fl4health_tpu.utils.bootstrap import honor_cpu_platform_request

honor_cpu_platform_request()
import numpy as np
import optax

from fl4health_tpu.clients import engine
from fl4health_tpu.datasets.synthetic import synthetic_text_classification
from fl4health_tpu.exchange.exchanger import (
    DynamicLayerExchanger,
    SparseExchanger,
)
from fl4health_tpu.metrics import efficient
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models.transformer import TransformerClassifier
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.dynamic_layer import (
    FedAvgDynamicLayer,
    FedAvgSparse,
)
from fl4health_tpu.strategies.fedavg import FedAvg
from fl4health_tpu.utils.hp_search import hp_grid, sweep

TINY = bool(os.environ.get("FL4HEALTH_SWEEP_TINY"))
N_CLIENTS = 2 if TINY else 4
ROUNDS = 2 if TINY else 8
N_CLASSES = 4  # AG-News: World / Sports / Business / Sci-Tech
VOCAB = 64 if TINY else 512
SEQ = 8 if TINY else 64
PER_CLIENT = 24 if TINY else 256


def client_datasets() -> list[ClientDataset]:
    npz = os.environ.get("FL4HEALTH_AGNEWS_NPZ")
    if npz and Path(npz).exists():
        with np.load(npz) as z:
            x, y = z["x"].astype(np.int32), z["y"].astype(np.int32)
        print("# data: real AG-News token ids from", npz)
        rng = np.random.default_rng(0)
        idx = rng.permutation(len(x))
        shards = np.array_split(idx[: N_CLIENTS * PER_CLIENT], N_CLIENTS)
        out = []
        for sh in shards:
            cut = int(len(sh) * 0.75)
            out.append(ClientDataset(x[sh[:cut]], y[sh[:cut]],
                                     x[sh[cut:]], y[sh[cut:]]))
        return out
    print("# data: synthetic AG-News-shaped token corpus")
    out = []
    for i in range(N_CLIENTS):
        x, y = synthetic_text_classification(
            jax.random.PRNGKey(50 + i), PER_CLIENT, VOCAB, SEQ, N_CLASSES,
            class_sep=2.5,
        )
        cut = int(PER_CLIENT * 0.75)
        out.append(ClientDataset(x[:cut], y[:cut], x[cut:], y[cut:]))
    return out


DATASETS = client_datasets()


def build(seed: int, exchange: str, budget: float,
          lr: float) -> FederatedSimulation:
    model = engine.from_flax(TransformerClassifier(
        vocab_size=VOCAB, n_classes=N_CLASSES,
        d_model=16 if TINY else 64, n_heads=2, n_layers=1 if TINY else 2,
        d_ff=32 if TINY else 128, max_len=SEQ,
    ))
    if exchange == "dynamic_layer":
        strategy, exchanger = FedAvgDynamicLayer(), DynamicLayerExchanger(
            mode="topk", exchange_fraction=budget
        )
    elif exchange == "sparse_coo":
        strategy, exchanger = FedAvgSparse(), SparseExchanger(
            sparsity_level=budget
        )
    else:
        strategy, exchanger = FedAvg(), None
    return FederatedSimulation(
        logic=engine.ClientLogic(model, engine.masked_cross_entropy),
        tx=optax.adam(lr),
        strategy=strategy,
        datasets=DATASETS,
        batch_size=8,
        metrics=MetricManager((efficient.accuracy(),)),
        local_steps=2 if TINY else 4,
        seed=seed,
        exchanger=exchanger,
    )


grid = hp_grid(
    exchange=["full", "dynamic_layer", "sparse_coo"],
    budget=[0.5] if TINY else [0.1, 0.25, 0.5],
    lr=[1e-3] if TINY else [5e-4, 1e-3],
)
# budget is inert for full exchange — drop duplicate configs
grid = [hp for hp in grid
        if hp["exchange"] != "full" or hp["budget"] == grid[0]["budget"]]

results = sweep(
    build, grid, n_rounds=ROUNDS, n_seeds=1 if TINY else 3,
    score=lambda history: float(history[-1].eval_metrics["accuracy"]),
    minimize=False,
)
for r in results:
    print(json.dumps({"params": r.params,
                      "mean_accuracy": round(r.mean_score, 4)}))
best = results[0]
print(json.dumps({"best": best.params, "accuracy": round(best.mean_score, 4)}))

"""FLamby Fed-ISIC2019 method grid (reference:
research/flamby/fed_isic2019/ — 6 natural centers, 8-class dermoscopy
images, severe per-center label skew; method subdirs include the base grid
plus ditto_mkmmd / ditto_deep_mmd / mr_mtl_mkmmd / mr_mtl_deep_mmd).

Synthetic stand-in: 6 centers with FLamby's extreme size imbalance (BCN
12413, ViDIR-group 3954/3363, MSK 819, ViDIR-molemax 439, rosendahl 225 —
scaled), class prototypes in image space, and per-center label-marginal
skew + acquisition shift (brightness/contrast per center). Real data drops
in via FL4HEALTH_FLAMBY_DIR/fed_isic2019.npz (x [N,H,W,3] float, y [N]
{0..7}, center [N]).

Run:  python research/flamby/fed_isic2019/sweep.py
Tiny: FL4HEALTH_SWEEP_TINY=1 python research/flamby/fed_isic2019/sweep.py
"""

import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "research" / "flamby"))

from fl4health_tpu.utils.bootstrap import honor_cpu_platform_request

honor_cpu_platform_request()

import numpy as np

import common
from fl4health_tpu.clients import engine
from fl4health_tpu.clients.ditto import KeepLocalExchanger
from fl4health_tpu.clients.mmd import (
    DittoMkMmdClientLogic,
    MrMtlDeepMmdClientLogic,
    MrMtlMkMmdClientLogic,
)
from fl4health_tpu.exchange.exchanger import FixedLayerExchanger
from fl4health_tpu.metrics import efficient
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models import bases
from fl4health_tpu.server.simulation import FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg
from fl4health_tpu.utils.hp_search import hp_grid, sweep

TINY = bool(os.environ.get("FL4HEALTH_SWEEP_TINY"))
ROUNDS = 2 if TINY else 12
N_CLASSES = 8
HW = 8 if TINY else 24
CHANNELS = (4, 8) if TINY else (8, 16)
CENTER_SIZES = (48, 24, 20, 12, 8, 8) if TINY else (1240, 395, 336, 82, 44, 24)
FEATURE_DIM = (HW // 4) ** 2 * CHANNELS[-1]  # ConvFeatures: two 2x2 pools


def synthetic_isic():
    rng = np.random.default_rng(11)
    protos = rng.normal(scale=1.2, size=(N_CLASSES, HW, HW, 3))
    xs, ys, cs = [], [], []
    for c, n in enumerate(CENTER_SIZES):
        # per-center label marginal: Dirichlet skew, heavier at small centers
        marginal = rng.dirichlet([2.0 / (1 + c)] * N_CLASSES)
        y = rng.choice(N_CLASSES, size=n, p=marginal)
        x = protos[y] + rng.normal(scale=1.0, size=(n, HW, HW, 3))
        x = x * rng.uniform(0.8, 1.2) + rng.normal(scale=0.3)  # acquisition
        xs.append(x.astype(np.float32))
        ys.append(y.astype(np.int64))
        cs.append(np.full(n, c))
    return np.concatenate(xs), np.concatenate(ys), np.concatenate(cs)


real = common.real_npz("fed_isic2019")
if real is not None:
    x, y, center = real
    print("# data: real FLamby fed_isic2019 from FL4HEALTH_FLAMBY_DIR")
else:
    x, y, center = synthetic_isic()
    print("# data: synthetic fed_isic2019 stand-in (6 skewed centers)")
DATASETS = common.center_datasets(x, y, center)

ZOO = {
    "plain": lambda: bases.SequentiallySplitModel(
        features_module=bases.ConvFeatures(channels=CHANNELS),
        head_module=bases.DenseHead(N_CLASSES),
    ),
    "features": lambda: bases.ConvFeatures(channels=CHANNELS),
    "head": lambda: bases.DenseHead(N_CLASSES),
}
# FLamby scores ISIC with balanced accuracy (severe class imbalance)
METRICS = lambda: MetricManager(  # noqa: E731
    (efficient.balanced_accuracy(N_CLASSES),)
)
MMD_METHODS = ("ditto_mkmmd", "mr_mtl_mkmmd", "mr_mtl_deep_mmd")


def build(seed, method, lr, lam):
    import optax

    if method not in MMD_METHODS:
        return common.build_method(
            method, ZOO, engine.masked_cross_entropy, DATASETS, lr, lam,
            batch_size=8, local_steps=2 if TINY else 4, metrics=METRICS(),
            seed=seed,
        )
    if method == "ditto_mkmmd":
        model = bases.TwinModel(global_model=ZOO["plain"](),
                                personal_model=ZOO["plain"]())
        logic = DittoMkMmdClientLogic(
            engine.from_flax(model), engine.masked_cross_entropy,
            feature_model=engine.from_flax(ZOO["plain"]()),
            lam=lam, mkmmd_loss_weight=1.0, beta_global_update_interval=2,
        )
        exchanger = FixedLayerExchanger(bases.TwinModel.exchange_global_model)
    elif method == "mr_mtl_mkmmd":
        logic = MrMtlMkMmdClientLogic(
            engine.from_flax(ZOO["plain"]()), engine.masked_cross_entropy,
            lam=lam, mkmmd_loss_weight=1.0, beta_global_update_interval=2,
        )
        exchanger = KeepLocalExchanger()
    else:  # mr_mtl_deep_mmd
        logic = MrMtlDeepMmdClientLogic(
            engine.from_flax(ZOO["plain"]()), engine.masked_cross_entropy,
            feature_sizes={"features": FEATURE_DIM},
            lam=lam, deep_mmd_loss_weight=1.0, optimization_steps=1,
            mmd_kernel_train_interval=2,
        )
        exchanger = KeepLocalExchanger()
    return FederatedSimulation(
        logic=logic,
        tx=optax.adam(lr),
        strategy=FedAvg(),
        datasets=DATASETS,
        batch_size=8,
        metrics=METRICS(),
        local_steps=2 if TINY else 4,
        seed=seed,
        exchanger=exchanger,
        extra_loss_keys=tuple(getattr(logic, "extra_loss_keys", ()) or ()),
    )


grid = common.dedup_inert_lam(hp_grid(
    method=list(common.METHODS) + list(MMD_METHODS),
    lr=[0.003] if TINY else [0.001, 0.003, 0.01],
    lam=[0.1] if TINY else [0.01, 0.1, 1.0],
), extra_lam_methods=MMD_METHODS)

results = sweep(
    build, grid, n_rounds=ROUNDS, n_seeds=1 if TINY else 3,
    score=lambda history: float(
        history[-1].eval_metrics["balanced_accuracy"]
    ),
    minimize=False,
)
common.finish(results, "flamby_isic_", "eval_balanced_accuracy",
              "balanced_accuracy")

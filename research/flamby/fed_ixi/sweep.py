"""FLamby Fed-IXI method grid (reference: research/flamby/fed_ixi/ —
3 natural centers (Guys, HH, IOP), binary brain-mask segmentation on T1
MRI volumes; method subdirs apfl/central/ditto/fedadam/fedavg/fedper/
fedprox/fenda/local/moon/perfcl/scaffold).

Synthetic stand-in: 3 centers with FLamby's relative sizes (Guys 249,
HH 145, IOP 74 — scaled), ellipsoid "brain" masks with per-center scanner
shift (intensity gain/offset, anisotropic ellipsoid axes). Real data drops
in via FL4HEALTH_FLAMBY_DIR/fed_ixi.npz (x [N,D,H,W,1] float, y [N,D,H,W]
{0,1}, center [N]).

Run:  python research/flamby/fed_ixi/sweep.py
Tiny: FL4HEALTH_SWEEP_TINY=1 python research/flamby/fed_ixi/sweep.py
"""

import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "research" / "flamby"))

from fl4health_tpu.utils.bootstrap import honor_cpu_platform_request

honor_cpu_platform_request()

import flax.linen as nn
import numpy as np

import common
from fl4health_tpu.metrics import efficient
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models import bases
from fl4health_tpu.utils.hp_search import hp_grid, sweep

TINY = bool(os.environ.get("FL4HEALTH_SWEEP_TINY"))
ROUNDS = 2 if TINY else 10
SIZE = 8 if TINY else 16
CENTER_SIZES = (12, 8, 4) if TINY else (62, 36, 18)
FEATS = 4 if TINY else 8


class SegFeatures(nn.Module):
    """3-D conv feature extractor returning a dense feature MAP — the
    split-model bases join/head these per voxel (vs ConvFeatures, which
    flattens for classification heads)."""

    features: int = 8

    @nn.compact
    def __call__(self, x, train: bool = True):
        h = nn.Conv(self.features, (3, 3, 3))(x)
        h = nn.relu(h)
        h = nn.Conv(self.features, (3, 3, 3))(h)
        return nn.relu(h)


def synthetic_ixi():
    rng = np.random.default_rng(13)
    coords = np.stack(
        np.meshgrid(*[np.arange(SIZE)] * 3, indexing="ij"), -1
    ).astype(float)
    xs, ys, cs = [], [], []
    for c, n in enumerate(CENTER_SIZES):
        gain, offset = 1.0 + 0.3 * c, 0.2 * c  # scanner shift per center
        axes_bias = 1.0 + 0.15 * c             # anisotropy per center
        for _ in range(n):
            center = rng.uniform(SIZE * 0.35, SIZE * 0.65, size=3)
            axes = rng.uniform(SIZE * 0.2, SIZE * 0.35, size=3)
            axes[0] *= axes_bias
            d = (((coords - center) / axes) ** 2).sum(-1)
            seg = (d < 1.0).astype(np.int32)
            vol = gain * (seg + rng.normal(0, 0.35, (SIZE,) * 3)) + offset
            xs.append(vol[..., None].astype(np.float32))
            ys.append(seg)
            cs.append(c)
    return np.stack(xs), np.stack(ys), np.asarray(cs)


real = common.real_npz("fed_ixi")
if real is not None:
    x, y, center = real
    print("# data: real FLamby fed_ixi from FL4HEALTH_FLAMBY_DIR")
else:
    x, y, center = synthetic_ixi()
    print("# data: synthetic fed_ixi stand-in (3 centers)")
DATASETS = common.center_datasets(x, y, center)

ZOO = {
    "plain": lambda: bases.SequentiallySplitModel(
        features_module=SegFeatures(FEATS),
        head_module=bases.DenseHead(2),  # per-voxel binary logits
    ),
    "features": lambda: SegFeatures(FEATS),
    "head": lambda: bases.DenseHead(2),
}


def build(seed, method, lr, lam):
    return common.build_method(
        method, ZOO, common.masked_seg_cross_entropy, DATASETS, lr, lam,
        batch_size=4, local_steps=2 if TINY else 4,
        metrics=MetricManager((efficient.segmentation_dice(2),)),
        seed=seed, seg=True,
    )


grid = common.dedup_inert_lam(hp_grid(
    method=list(common.METHODS),
    lr=[0.01] if TINY else [0.003, 0.01, 0.03],
    lam=[0.1] if TINY else [0.01, 0.1, 1.0],
))

results = sweep(
    build, grid, n_rounds=ROUNDS, n_seeds=1 if TINY else 3,
    score=lambda history: float(history[-1].eval_metrics["seg_dice"]),
    minimize=False,
)
common.finish(results, "flamby_ixi_", "eval_seg_dice", "dice")

"""Shared method grid for the FLamby research harnesses.

Reference role: /root/reference/research/flamby/ — the FENDA-FL paper's
experimental grid (arXiv 2309.16825). Each FLamby dataset dir there holds
one subdir per method (fed_heart_disease: apfl/central/ditto/fedadam/
fedavg/fedper/fedprox/fenda/local/moon/perfcl/scaffold, plus mkmmd/deep-mmd
arms on fed_isic2019), each with Slurm HP sweeps selected by
research/flamby/find_best_hp.py. This module is the TPU-native counterpart:
``build_method`` wires any of those method arms into a
``FederatedSimulation`` from a per-dataset model zoo, and the three sweeps
(fed_heart_disease/, fed_isic2019/, fed_ixi/) run the grid in-process.

Data: FLamby's clinical corpora cannot exist on a zero-egress box. Each
sweep ships a synthetic stand-in shaped like its dataset (center counts,
feature shapes, per-center heterogeneity) and accepts the real thing via
``FL4HEALTH_FLAMBY_DIR/<name>.npz`` with arrays x, y, center — the same
env-var drop-in contract as the rxrx1 harness.
"""

from __future__ import annotations

import os
from pathlib import Path

import numpy as np
import optax

from fl4health_tpu.clients import engine
from fl4health_tpu.clients.apfl import ApflClientLogic
from fl4health_tpu.clients.ditto import (
    DittoClientLogic,
    KeepLocalExchanger,
    MrMtlClientLogic,
)
from fl4health_tpu.clients.fenda import (
    ConstrainedFendaClientLogic,
    PerFclClientLogic,
)
from fl4health_tpu.clients.fedprox import FedProxClientLogic
from fl4health_tpu.clients.moon import MoonClientLogic
from fl4health_tpu.clients.scaffold import ScaffoldClientLogic
from fl4health_tpu.exchange.exchanger import FixedLayerExchanger
from fl4health_tpu.models import bases
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg
from fl4health_tpu.strategies.fedopt import FedOpt
from fl4health_tpu.strategies.fedprox import FedAvgWithAdaptiveConstraint
from fl4health_tpu.strategies.scaffold import Scaffold

# The reference's per-dataset method lists (dir listings above); mmd arms
# are added by fed_isic2019 itself.
METHODS = (
    "central", "local", "fedavg", "fedadam", "fedprox", "scaffold",
    "ditto", "mr_mtl", "apfl", "fenda", "moon", "fedper", "perfcl",
)


def real_npz(name: str):
    """FL4HEALTH_FLAMBY_DIR/<name>.npz -> (x, y, center) or None."""
    root = os.environ.get("FL4HEALTH_FLAMBY_DIR")
    if not root:
        return None
    path = Path(root) / f"{name}.npz"
    if not path.exists():
        return None
    with np.load(path) as z:
        return z["x"], z["y"], z["center"]


def center_datasets(x, y, center, val_frac=0.25, seed=0):
    """Split arrays into per-center ClientDatasets (FLamby's natural-split
    role — flamby_data_utils.py construct_*_train_val_datasets)."""
    out = []
    rng = np.random.default_rng(seed)
    for c in sorted(np.unique(np.asarray(center))):
        idx = np.flatnonzero(np.asarray(center) == c)
        rng.shuffle(idx)
        cut = max(int(len(idx) * (1 - val_frac)), 1)
        out.append(ClientDataset(
            x_train=x[idx[:cut]], y_train=y[idx[:cut]],
            x_val=x[idx[cut:]], y_val=y[idx[cut:]],
        ))
    return out


def pooled_dataset(datasets):
    """All centers concatenated into one client (the 'central' baseline)."""
    cat = lambda parts: np.concatenate([np.asarray(p) for p in parts])  # noqa: E731
    return [ClientDataset(
        x_train=cat([d.x_train for d in datasets]),
        y_train=cat([d.y_train for d in datasets]),
        x_val=cat([d.x_val for d in datasets]),
        y_val=cat([d.y_val for d in datasets]),
    )]


def masked_seg_cross_entropy(logits, targets, mask):
    """Dense-map criterion with the engine's (logits, targets, example_mask)
    signature, delegating to the seg-loss helpers (losses/segmentation.py)
    so label clipping / voxel weighting stay single-sourced."""
    from fl4health_tpu.losses.segmentation import (
        _voxel_weights,
        masked_voxel_cross_entropy,
    )

    return masked_voxel_cross_entropy(
        logits, targets, _voxel_weights(targets, mask, None)
    )


def _flat(features: dict) -> dict:
    return {k: v.reshape(v.shape[0], -1) for k, v in features.items()}


class SegMoonClientLogic(MoonClientLogic):
    """MOON over dense feature MAPS (fed_ixi): the contrastive term needs
    [B, D] vectors, so feature maps are flattened for the cosine terms while
    the prediction head still sees the map."""

    def _features_of(self, params, model_state, x, rng):
        f = super()._features_of(params, model_state, x, rng)
        return f.reshape(f.shape[0], -1)

    def training_loss(self, preds, features, batch, params, state, ctx):
        return super().training_loss(
            preds, _flat(features), batch, params, state, ctx
        )


class SegConstrainedFendaClientLogic(ConstrainedFendaClientLogic):
    """FENDA over dense feature maps (fed_ixi): the cosine term reduces over
    the last axis, so maps are flattened to [B, D] for it. The contrastive
    arm is refused outright: the parent recomputes old-model features via a
    raw model.apply inside training_loss, which this override cannot
    flatten — mixing flat and map features there would crash or silently
    broadcast wrong."""

    def __init__(self, *args, **kwargs):
        super().__init__(*args, **kwargs)
        if self.con_w > 0.0:
            raise ValueError(
                "SegConstrainedFendaClientLogic supports the cosine term "
                "only; contrastive_loss_weight must be 0 on feature maps"
            )

    def training_loss(self, preds, features, batch, params, state, ctx):
        return super().training_loss(
            preds, _flat(features), batch, params, state, ctx
        )


class SegPerFclClientLogic(PerFclClientLogic):
    """PerFCL over dense feature maps (fed_ixi) — same flattening as
    SegMoonClientLogic, applied to both contrastive feature streams."""

    def _features(self, params, model_state, x, rng):
        return _flat(super()._features(params, model_state, x, rng))

    def training_loss(self, preds, features, batch, params, state, ctx):
        return super().training_loss(
            preds, _flat(features), batch, params, state, ctx
        )


def build_method(
    method: str,
    zoo: dict,
    criterion,
    datasets: list[ClientDataset],
    lr: float,
    lam: float,
    batch_size: int,
    local_steps: int,
    metrics,
    seed: int,
    server_lr: float = 0.01,
    seg: bool = False,
) -> FederatedSimulation:
    """One FLamby method arm as a FederatedSimulation.

    zoo: {"plain": () -> flax module, "features": () -> extractor module,
    "head": () -> head module}. ``seg=True`` selects the feature-map-safe
    contrastive logics for moon/perfcl.
    """
    tx = optax.adam(lr)
    strategy = FedAvg()
    exchanger = None
    sim_datasets = datasets

    if method == "central":
        logic = engine.ClientLogic(engine.from_flax(zoo["plain"]()), criterion)
        sim_datasets = pooled_dataset(datasets)
    elif method == "local":
        logic = engine.ClientLogic(engine.from_flax(zoo["plain"]()), criterion)
        exchanger = KeepLocalExchanger()
    elif method == "fedavg":
        logic = engine.ClientLogic(engine.from_flax(zoo["plain"]()), criterion)
    elif method == "fedadam":
        logic = engine.ClientLogic(engine.from_flax(zoo["plain"]()), criterion)
        strategy = FedOpt(optax.adam(server_lr))
    elif method == "fedprox":
        logic = FedProxClientLogic(
            engine.from_flax(zoo["plain"]()), criterion
        )
        strategy = FedAvgWithAdaptiveConstraint(
            initial_drift_penalty_weight=lam, adapt_loss_weight=False
        )
    elif method == "scaffold":
        logic = ScaffoldClientLogic(
            engine.from_flax(zoo["plain"]()), criterion, learning_rate=lr
        )
        tx = optax.sgd(lr)  # SCAFFOLD's variate algebra assumes vanilla SGD
        strategy = Scaffold(learning_rate=1.0)
    elif method == "ditto":
        model = bases.TwinModel(
            global_model=zoo["plain"](), personal_model=zoo["plain"]()
        )
        logic = DittoClientLogic(engine.from_flax(model), criterion, lam=lam)
        exchanger = FixedLayerExchanger(bases.TwinModel.exchange_global_model)
    elif method == "mr_mtl":
        logic = MrMtlClientLogic(
            engine.from_flax(zoo["plain"]()), criterion, lam=lam
        )
        exchanger = KeepLocalExchanger()
    elif method == "apfl":
        module = bases.ApflModule(
            local_model=zoo["plain"](), global_model=zoo["plain"]()
        )
        logic = ApflClientLogic(engine.from_flax(module), criterion)
        exchanger = FixedLayerExchanger(bases.ApflModule.exchange_global_model)
    elif method == "fenda":
        model = bases.FendaModel(
            first_feature_extractor=zoo["features"](),
            second_feature_extractor=zoo["features"](),
            head_module=bases.HeadModule(head=zoo["head"]()),
        )
        cls = SegConstrainedFendaClientLogic if seg else ConstrainedFendaClientLogic
        logic = cls(engine.from_flax(model), criterion)
        exchanger = FixedLayerExchanger(
            bases.ParallelSplitModel.exchange_global_extractor
        )
    elif method == "moon":
        model = bases.MoonModel(
            base_module=zoo["features"](), head_module=zoo["head"]()
        )
        cls = SegMoonClientLogic if seg else MoonClientLogic
        logic = cls(engine.from_flax(model), criterion,
                    contrastive_weight=lam)
    elif method == "fedper":
        model = bases.SequentiallySplitModel(
            features_module=zoo["features"](), head_module=zoo["head"]()
        )
        logic = engine.ClientLogic(engine.from_flax(model), criterion)
        exchanger = FixedLayerExchanger(
            bases.SequentiallySplitModel.exchange_features_only
        )
    elif method == "perfcl":
        model = bases.PerFclModel(
            first_feature_extractor=zoo["features"](),
            second_feature_extractor=zoo["features"](),
            head_module=bases.HeadModule(head=zoo["head"]()),
        )
        cls = SegPerFclClientLogic if seg else PerFclClientLogic
        logic = cls(engine.from_flax(model), criterion,
                    global_feature_loss_weight=lam,
                    local_feature_loss_weight=lam)
        exchanger = FixedLayerExchanger(
            bases.ParallelSplitModel.exchange_global_extractor
        )
    else:
        raise ValueError(f"unknown flamby method {method!r}")

    return FederatedSimulation(
        logic=logic,
        tx=tx,
        strategy=strategy,
        datasets=sim_datasets,
        batch_size=batch_size,
        metrics=metrics,
        local_steps=local_steps,
        seed=seed,
        exchanger=exchanger,
        extra_loss_keys=tuple(getattr(logic, "extra_loss_keys", ()) or ()),
    )


# Methods whose ``lam`` knob is live (penalty weight / contrastive weight);
# for every other method lam is inert and duplicate grid points are dropped.
LAM_METHODS = frozenset({"fedprox", "ditto", "mr_mtl", "moon", "perfcl"})


def dedup_inert_lam(grid: list[dict], extra_lam_methods=()) -> list[dict]:
    """Drop grid points that differ only in an inert ``lam``."""
    live = LAM_METHODS | set(extra_lam_methods)
    return [hp for hp in grid
            if hp["method"] in live or hp["lam"] == grid[0]["lam"]]


def finish(results, out_prefix: str, metric_key: str, score_name: str):
    """Shared sweep tail: print the ranked arms, materialize the hp-dir
    layout, re-select via find_best_hp_dir, assert agreement, print best."""
    import json as _json
    import os as _os
    import tempfile as _tempfile

    for r in results:
        print(_json.dumps({"params": r.params,
                           f"mean_{score_name}": round(r.mean_score, 4)}))
    out_dir = Path(_os.environ.get("FL4HEALTH_SWEEP_OUT")
                   or _tempfile.mkdtemp(prefix=out_prefix))
    best_dir, best_score = write_hp_dir_and_select(out_dir, results, metric_key)
    best = results[0]
    assert best_dir is not None and abs(best_score - best.mean_score) < 1e-9
    print(_json.dumps({"best": best.params,
                       score_name: round(best.mean_score, 4),
                       "best_hp_dir": best_dir.name}))


def write_hp_dir_and_select(out_dir: Path, results, metric_key: str):
    """Materialize sweep results as the reference's hp-folder layout and
    re-select the winner via find_best_hp_dir (find_best_hp.py:36 flow) —
    pinning that the file-based selection agrees with the in-memory sweep."""
    import json

    from fl4health_tpu.utils.hp_search import find_best_hp_dir

    out_dir.mkdir(parents=True, exist_ok=True)
    for r in results:
        label = "_".join(f"{k}-{v}" for k, v in sorted(r.params.items()))
        run_dir = out_dir / label / "Run0"
        run_dir.mkdir(parents=True, exist_ok=True)
        (run_dir / "metrics.json").write_text(json.dumps(
            {"rounds": {"1": {metric_key: r.mean_score}}}
        ))
    best_dir, best_score = find_best_hp_dir(
        out_dir, metric=metric_key, minimize=False
    )
    return best_dir, best_score

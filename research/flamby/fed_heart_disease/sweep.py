"""FLamby Fed-Heart-Disease method grid (reference:
research/flamby/fed_heart_disease/ — 4 natural centers, 13 tabular
features, binary target; method subdirs apfl/central/ditto/fedadam/fedavg/
fedper/fedprox/fenda/local/moon/perfcl/scaffold with Slurm HP sweeps and
find_best_hp.py selection).

Synthetic stand-in: 4 centers with FLamby's relative sizes (Cleveland 303,
Hungarian 261, Switzerland 46, Long Beach VA 130 — scaled), a shared linear
risk rule, and per-center covariate shift + label noise so personalization
arms have signal to exploit. Real data drops in via
FL4HEALTH_FLAMBY_DIR/fed_heart_disease.npz (x [N,13] float, y [N] {0,1},
center [N]).

Run:  python research/flamby/fed_heart_disease/sweep.py
Tiny: FL4HEALTH_SWEEP_TINY=1 python research/flamby/fed_heart_disease/sweep.py
"""

import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent.parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "research" / "flamby"))

from fl4health_tpu.utils.bootstrap import honor_cpu_platform_request

honor_cpu_platform_request()

import numpy as np

import common
from fl4health_tpu.metrics import efficient
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models import bases
from fl4health_tpu.utils.hp_search import hp_grid, sweep

TINY = bool(os.environ.get("FL4HEALTH_SWEEP_TINY"))
ROUNDS = 2 if TINY else 15
CENTER_SIZES = (40, 34, 12, 20) if TINY else (303, 261, 46, 130)
N_FEATURES = 13


def synthetic_heart():
    rng = np.random.default_rng(7)
    w = rng.normal(size=N_FEATURES)
    xs, ys, cs = [], [], []
    for c, n in enumerate(CENTER_SIZES):
        shift = rng.normal(scale=0.6, size=N_FEATURES)  # covariate shift
        x = rng.normal(size=(n, N_FEATURES)) + shift
        logits = x @ w + rng.normal(scale=1.0, size=n)
        y = (logits > np.median(logits)).astype(np.int64)
        # center-specific label noise (annotation-protocol heterogeneity)
        flip = rng.random(n) < (0.02 + 0.04 * c)
        y = np.where(flip, 1 - y, y)
        xs.append(x.astype(np.float32))
        ys.append(y)
        cs.append(np.full(n, c))
    return np.concatenate(xs), np.concatenate(ys), np.concatenate(cs)


real = common.real_npz("fed_heart_disease")
if real is not None:
    x, y, center = real
    print("# data: real FLamby fed_heart_disease from FL4HEALTH_FLAMBY_DIR")
else:
    x, y, center = synthetic_heart()
    print("# data: synthetic fed_heart_disease stand-in (4 centers)")
DATASETS = common.center_datasets(x, y, center)

ZOO = {
    # FLamby's heart-disease baseline is logistic regression; the split
    # arms need a features/head factorization, so the grid's backbone is a
    # small MLP with a matching linear head.
    "plain": lambda: bases.SequentiallySplitModel(
        features_module=bases.DenseFeatures((16,)),
        head_module=bases.DenseHead(2),
    ),
    "features": lambda: bases.DenseFeatures((16,)),
    "head": lambda: bases.DenseHead(2),
}


def build(seed, method, lr, lam):
    from fl4health_tpu.clients import engine

    return common.build_method(
        method, ZOO, engine.masked_cross_entropy, DATASETS, lr, lam,
        batch_size=8, local_steps=2 if TINY else 4,
        metrics=MetricManager((efficient.accuracy(),)), seed=seed,
    )


grid = common.dedup_inert_lam(hp_grid(
    method=list(common.METHODS),
    lr=[0.01] if TINY else [0.003, 0.01, 0.03],
    lam=[0.1] if TINY else [0.01, 0.1, 1.0],
))

results = sweep(
    build, grid, n_rounds=ROUNDS, n_seeds=1 if TINY else 3,
    score=lambda history: float(history[-1].eval_metrics["accuracy"]),
    minimize=False,
)
common.finish(results, "flamby_heart_", "eval_accuracy", "accuracy")

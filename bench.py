"""Benchmark: FedAvg on a CIFAR-10-class CNN with 64 simulated clients.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

Measures local-steps/sec/chip for the compiled SPMD round (all 64 clients'
local training + aggregation inside jit). ``vs_baseline`` compares against a
reference-style eager simulation measured on the SAME hardware: a Python loop
over clients, each running eager (un-jitted) train steps with host round-trips
per step and per-round parameter serialization — the dispatch pattern of the
reference's Flower/PyTorch stack (see SURVEY.md §3.1-3.2). The north-star in
BASELINE.json is a 10x wall-clock win over a single-A100 Flower sim; the
eager-vs-compiled ratio on identical silicon is the closest locally measurable
proxy.
"""

from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp
import optax

from fl4health_tpu.clients import engine
from fl4health_tpu.datasets.synthetic import synthetic_classification
from fl4health_tpu.metrics import efficient
from fl4health_tpu.metrics.base import MetricManager
from fl4health_tpu.models.cnn import CifarNet
from fl4health_tpu.server.simulation import ClientDataset, FederatedSimulation
from fl4health_tpu.strategies.fedavg import FedAvg

N_CLIENTS = 64
BATCH = 32
LOCAL_STEPS = 5
TIMED_ROUNDS = 3


def make_sim() -> FederatedSimulation:
    datasets = []
    for i in range(N_CLIENTS):
        rng = jax.random.PRNGKey(i)
        x, y = synthetic_classification(rng, BATCH * LOCAL_STEPS + 64, (32, 32, 3), 10)
        datasets.append(
            ClientDataset(
                x_train=x[: BATCH * LOCAL_STEPS],
                y_train=y[: BATCH * LOCAL_STEPS],
                x_val=x[BATCH * LOCAL_STEPS :],
                y_val=y[BATCH * LOCAL_STEPS :],
            )
        )
    return FederatedSimulation(
        logic=engine.ClientLogic(
            engine.from_flax(CifarNet()), engine.masked_cross_entropy
        ),
        tx=optax.sgd(0.05),
        strategy=FedAvg(),
        datasets=datasets,
        batch_size=BATCH,
        metrics=MetricManager((efficient.accuracy(),)),
        local_steps=LOCAL_STEPS,
        seed=0,
    )


def timed_compiled_rounds(sim: FederatedSimulation) -> float:
    """Wall time per round of the compiled fit path (excludes compile)."""
    mask = sim.client_manager.sample_all()
    batches = sim._round_batches(0)
    val_batches, _ = sim._val_batches()
    r = jnp.asarray(1, jnp.int32)
    # warmup/compile
    out = sim._fit_round(sim.server_state, sim.client_states, batches, mask, r, val_batches)
    jax.block_until_ready(out[0])
    t0 = time.perf_counter()
    server_state, client_states = sim.server_state, sim.client_states
    for i in range(TIMED_ROUNDS):
        server_state, client_states, losses, metrics = sim._fit_round(
            server_state, client_states, batches, mask, r + i, val_batches
        )
    jax.block_until_ready(jax.tree_util.tree_leaves(server_state)[0])
    return (time.perf_counter() - t0) / TIMED_ROUNDS


def timed_eager_round(sim: FederatedSimulation) -> float:
    """Reference-style dispatch: Python loop over clients, eager step calls,
    per-round full-parameter host round-trip (numpy serialize/deserialize)."""
    import numpy as np

    logic, tx = sim.logic, sim.tx
    step_fn = engine.make_train_step(logic, tx)  # NOT jitted: eager dispatch
    batches = sim._round_batches(0)
    t0 = time.perf_counter()
    collected = []
    for c in range(N_CLIENTS):
        state = jax.tree_util.tree_map(lambda x: x[c], sim.client_states)
        cb = jax.tree_util.tree_map(lambda x: x[c], batches)
        for s in range(LOCAL_STEPS):
            b = jax.tree_util.tree_map(lambda x: x[s], cb)
            state, _ = step_fn(state, None, b)
        # Flower-style wire: params -> host numpy list -> back
        nds = [np.asarray(x) for x in jax.tree_util.tree_leaves(state.params)]
        collected.append(nds)
    # host-side aggregation over numpy lists (aggregate_utils.py style)
    agg = [np.mean([c[i] for c in collected], axis=0) for i in range(len(collected[0]))]
    _ = [jnp.asarray(a) for a in agg]
    return time.perf_counter() - t0


def main():
    sim = make_sim()
    per_round = timed_compiled_rounds(sim)
    steps_per_round = N_CLIENTS * LOCAL_STEPS
    compiled_sps = steps_per_round / per_round

    eager_time = timed_eager_round(sim)
    eager_sps = steps_per_round / eager_time

    print(
        json.dumps(
            {
                "metric": "fedavg_cifar_cnn_64clients_local_steps_per_sec_per_chip",
                "value": round(compiled_sps, 2),
                "unit": "local_steps/sec/chip",
                "vs_baseline": round(compiled_sps / eager_sps, 2),
            }
        )
    )


if __name__ == "__main__":
    main()
